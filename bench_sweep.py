#!/usr/bin/env python
"""Hardware tuning sweep for the headline workload: runs bench-shaped
measured windows across (max_batch, pipeline_depth) combinations on the
CURRENT backend and prints one JSON line per point plus the best.

    python bench_sweep.py                      # default grid
    BENCH_NODES=5000 BENCH_PODS=10000 python bench_sweep.py
    SWEEP_BATCHES=512,1024,2048 SWEEP_DEPTHS=2,3 python bench_sweep.py

The dispatch-count vs scan-length tradeoff (and the RTT-hiding value of
pipeline depth) is hardware-specific — on the tunneled TPU each result
fetch pays tens of ms, on a local chip far less — so the right tier is
measured, not guessed. Round 5: run this on the real chip and set
config.max_batch / pipeline_depth from the winner.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench import _ensure_live_backend, build_cluster, make_pods  # noqa: E402


def run_point(n_nodes, n_pods, max_batch, depth):
    from kubernetes_tpu.core import FakeClientset
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.testing import make_node

    cs = FakeClientset()
    sched = TPUScheduler(clientset=cs, max_batch=max_batch)
    sched.pipeline_depth = depth
    for i in range(n_nodes):
        cs.create_node(
            make_node().name(f"node-{i}")
            .capacity({"cpu": 32, "memory": "256Gi", "pods": 110})
            .zone(f"zone-{i % 50}").obj())
    sched.warm_for(make_pods(1, "warmshape")[0])
    for p in make_pods(min(max_batch, 1024), "warm"):
        cs.create_pod(p)
    sched.run_until_idle()
    before = sched.scheduled
    for p in make_pods(n_pods, "bench"):
        cs.create_pod(p)
    t0 = time.perf_counter()
    sched.run_until_idle()
    elapsed = time.perf_counter() - t0
    return (sched.scheduled - before) / elapsed if elapsed > 0 else 0.0


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 10000))
    batches = [int(b) for b in os.environ.get(
        "SWEEP_BATCHES", "512,1024,2048").split(",")]
    depths = [int(d) for d in os.environ.get("SWEEP_DEPTHS", "2,3").split(",")]

    platform = _ensure_live_backend()
    best = None
    for mb in batches:
        for depth in depths:
            rate = run_point(n_nodes, n_pods, mb, depth)
            point = {"max_batch": mb, "pipeline_depth": depth,
                     "pods_per_s": round(rate, 1), "platform": platform}
            print(json.dumps(point), flush=True)
            if best is None or rate > best["pods_per_s"]:
                best = point
    print(json.dumps({"best": best}))


if __name__ == "__main__":
    main()
