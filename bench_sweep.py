#!/usr/bin/env python
"""Hardware tuning sweep for the headline workload: runs bench-shaped
measured windows across (max_batch, pipeline_depth) combinations on the
CURRENT backend and prints one JSON line per point plus the best.

    python bench_sweep.py                      # default grid
    BENCH_NODES=5000 BENCH_PODS=10000 python bench_sweep.py
    SWEEP_BATCHES=512,1024,2048 SWEEP_DEPTHS=2,3 python bench_sweep.py
    python bench_sweep.py --bottleneck PERF_r03.json   # classify, don't run

The dispatch-count vs scan-length tradeoff (and the RTT-hiding value of
pipeline depth) is hardware-specific — on the tunneled TPU each result
fetch pays tens of ms, on a local chip far less — so the right tier is
measured, not guessed. Round 5: run this on the real chip and set
config.max_batch / pipeline_depth from the winner.

`--bottleneck PERF_*.json` reads a perf-table result file and prints each
workload's dominant-cost classification (plan-build-bound / device-wait-
bound / host-commit-bound / host-path-bound), so a round's VERDICT can rank
optimization targets without hand-reading the table.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def bottleneck(path: str) -> int:
    """Classify every workload in a PERF_*.json by dominant cost. The
    step-accounting split (plan_build_s / device_wait_s / host_commit_s,
    models/tpu_scheduler.py) covers the device pipeline; pods that never
    reached it classify as host-path-bound; workloads with no split data
    and no host pods are unattributed."""
    with open(path) as f:
        data = json.load(f)
    out = []
    for r in data.get("results", []):
        det = r.get("detail", {}) or {}
        host_pods = det.get("host_path_pods", 0) or 0
        dev_pods = det.get("device_scheduled", 0) or 0
        split = {
            "plan-build-bound": det.get("plan_build_s", 0.0) or 0.0,
            "device-wait-bound": det.get("device_wait_s", 0.0) or 0.0,
            "host-commit-bound": det.get("host_commit_s", 0.0) or 0.0,
        }
        total = sum(split.values())
        if host_pods > dev_pods:
            kind, share = "host-path-bound", None
        elif total <= 0:
            kind, share = "unattributed", None
        else:
            kind = max(split, key=split.get)
            share = round(split[kind] / total, 2)
        entry = {
            "workload": r.get("workload"),
            "bottleneck": kind,
            "pods_per_second": r.get("pods_per_second"),
            "split_s": {k.split("-")[0]: round(v, 2)
                        for k, v in split.items()},
        }
        if share is not None:
            entry["dominant_share"] = share
        if host_pods:
            entry["host_path_pods"] = host_pods
        for k in ("plan_rebuilds_full", "plan_rebuilds_delta",
                  "plan_rebuilds_resume"):
            if det.get(k) is not None:
                entry[k] = det[k]
        out.append(entry)
        print(json.dumps(entry), flush=True)
    by_kind = {}
    for e in out:
        by_kind[e["bottleneck"]] = by_kind.get(e["bottleneck"], 0) + 1
    print(json.dumps({"summary": by_kind}))
    return 0


def run_point(n_nodes, n_pods, max_batch, depth):
    from bench import make_pods
    from kubernetes_tpu.core import FakeClientset
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.testing import make_node

    cs = FakeClientset()
    sched = TPUScheduler(clientset=cs, max_batch=max_batch)
    sched.pipeline_depth = depth
    for i in range(n_nodes):
        cs.create_node(
            make_node().name(f"node-{i}")
            .capacity({"cpu": 32, "memory": "256Gi", "pods": 110})
            .zone(f"zone-{i % 50}").obj())
    sched.warm_for(make_pods(1, "warmshape")[0])
    for p in make_pods(min(max_batch, 1024), "warm"):
        cs.create_pod(p)
    sched.run_until_idle()
    before = sched.scheduled
    for p in make_pods(n_pods, "bench"):
        cs.create_pod(p)
    t0 = time.perf_counter()
    sched.run_until_idle()
    elapsed = time.perf_counter() - t0
    return (sched.scheduled - before) / elapsed if elapsed > 0 else 0.0


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 10000))
    batches = [int(b) for b in os.environ.get(
        "SWEEP_BATCHES", "512,1024,2048").split(",")]
    depths = [int(d) for d in os.environ.get("SWEEP_DEPTHS", "2,3").split(",")]

    from bench import _ensure_live_backend
    platform = _ensure_live_backend()
    best = None
    for mb in batches:
        for depth in depths:
            rate = run_point(n_nodes, n_pods, mb, depth)
            point = {"max_batch": mb, "pipeline_depth": depth,
                     "pods_per_s": round(rate, 1), "platform": platform}
            print(json.dumps(point), flush=True)
            if best is None or rate > best["pods_per_s"]:
                best = point
    print(json.dumps({"best": best}))


if __name__ == "__main__":
    if "--bottleneck" in sys.argv:
        i = sys.argv.index("--bottleneck")
        if i + 1 >= len(sys.argv):
            print("usage: bench_sweep.py --bottleneck PERF_rNN.json",
                  file=sys.stderr)
            sys.exit(2)
        try:
            sys.exit(bottleneck(sys.argv[i + 1]))
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_sweep.py --bottleneck: {e}", file=sys.stderr)
            sys.exit(2)
    main()
