#!/usr/bin/env python
"""Driver benchmark: schedules a SchedulingBasic-shaped workload (BASELINE.md
SchedulingBasic/5000Nodes_10000Pods, threshold 680 pods/s on upstream CI
hardware — test/integration/scheduler_perf/misc/performance-config.yaml:59)
through the device-backed TPUScheduler and prints ONE JSON line:

    {"metric": ..., "value": pods/s, "unit": "pods/s", "vs_baseline": x}

Compile time is excluded via a same-shape warmup run; the measured window is
steady-state scheduling (queue pop → device kernel → bind), matching the
reference collector's approach of measuring inside the scheduling window
(scheduler_perf util.go:686-694).
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 680.0  # SchedulingBasic/5000Nodes_10000Pods


def probe_availability(timeout: float = 0.0) -> dict:
    """Time `jax.devices()` in a SUBPROCESS (the axon tunnel can wedge
    backend init forever — a hang must trip a timeout, never block the
    caller) and return the backend-availability dict. `--probe` prints
    it; the bench mains EMBED it in their detail line so BENCH_*.json
    trajectories keep the hardware-availability context."""
    timeout = timeout or float(os.environ.get("BENCH_PROBE_TIMEOUT", 60))
    code = ("import jax, json; ds = jax.devices(); "
            "print(json.dumps({'platform': ds[0].platform, "
            "'count': len(ds)}))")
    t0 = time.perf_counter()
    try:
        out = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                             capture_output=True, text=True, check=True)
        info = json.loads(out.stdout.strip().splitlines()[-1])
        return {"available": True, "backend": info["platform"],
                "devices": info["count"],
                "elapsed_s": round(time.perf_counter() - t0, 2)}
    except subprocess.TimeoutExpired:
        return {"available": False, "backend": "unreachable",
                "elapsed_s": round(time.perf_counter() - t0, 2),
                "reason": f"jax.devices() hung past {timeout:.0f}s "
                          "(tunnel wedged?)"}
    except (subprocess.CalledProcessError, ValueError, IndexError) as e:
        stderr = getattr(e, "stderr", "") or ""
        return {"available": False, "backend": "unreachable",
                "elapsed_s": round(time.perf_counter() - t0, 2),
                "reason": f"backend init failed: {stderr.strip()[-200:]}"}


def probe(timeout: float = 0.0) -> int:
    """`python bench.py --probe`: one JSON availability line (VERDICT r5
    next-item #1). Exit code 0 = a backend answered, 1 = unreachable."""
    result = probe_availability(timeout)
    print(json.dumps(result))
    return 0 if result["available"] else 1


def _ensure_live_backend(probe_timeout: float = 180.0):
    """The axon TPU tunnel can wedge so hard that jax.devices() blocks
    forever INSIDE backend init (observed for hours on the round-4 box) —
    which would hang the driver's bench run indefinitely. Probe device init
    in a subprocess first; on timeout/failure, force the CPU backend through
    the config API (the plugin ignores JAX_PLATFORMS) so the bench still
    reports a number, tagged with the platform that actually ran.
    Returns (platform note, availability dict for the detail line)."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
        return "cpu (forced)", {"available": False, "backend": "cpu",
                                "reason": "BENCH_FORCE_CPU"}
    avail = probe_availability(probe_timeout)
    if avail["available"]:
        return "device", avail
    import jax
    jax.config.update("jax_platforms", "cpu")
    return "cpu (tpu backend unreachable)", avail


def build_cluster(n_nodes: int, zones: int = 50):
    from kubernetes_tpu.core import FakeClientset
    from kubernetes_tpu.models import TPUScheduler
    from kubernetes_tpu.testing import make_node

    cs = FakeClientset()
    # BENCH_MAX_BATCH sweeps the session batch tier (dispatch count vs scan
    # length tradeoff on real hardware); default = config.max_batch.
    mb = int(os.environ.get("BENCH_MAX_BATCH", 0)) or None
    sched = TPUScheduler(clientset=cs, max_batch=mb)
    for i in range(n_nodes):
        cs.create_node(
            make_node().name(f"node-{i}")
            .capacity({"cpu": 32, "memory": "256Gi", "pods": 110})
            .zone(f"zone-{i % zones}").obj())
    return cs, sched


def make_pods(n, name_prefix):
    from kubernetes_tpu.testing import make_pod
    # One template prototype, N identity clones sharing spec + signature memo
    # (the reference perf harness stamps pods from a podTemplate the same way).
    proto = (make_pod().name("proto")
             .req({"cpu": "100m", "memory": "128Mi"}).labels({"app": name_prefix})
             .obj())
    return [proto.clone_from_template(f"{name_prefix}-{i}") for i in range(n)]


def main_sharded(n_shards: int, trace: bool = False,
                 replicas: int = 0, deschedule: bool = False) -> None:
    """`bench.py --shards N [--trace] [--replicas R] [--deschedule]`: the
    same SchedulingBasic shape through the multi-process shard plane
    (kubernetes_tpu/shard/harness.py) — one apiserver process + N scheduler
    processes over HTTP. N=1 is the like-for-like single-scheduler baseline
    (same transport, same store); the acceptance comparison is N=2 vs N=1
    pods/s. With --trace, every process dumps its span ring (flight
    recorder) and the merged trace analysis — per-stage p50/p99, chain
    completeness, conflict timeline — rides the detail object
    (docs/OBSERVABILITY.md). With --replicas R, R follower apiservers tail
    the leader's WAL and serve each shard's read plane
    (kubernetes_tpu/replication/); the detail line carries per-replica
    role/lag and the leader's replication counters. With --deschedule, an
    HA descheduler pair rides the run (docs/DESCHEDULE.md) and the detail
    line carries each manager's final stats — moves by strategy,
    blocked-by-reason, what-if batch timings — next to the apiserver's
    eviction counters (the "api" filter includes eviction series)."""
    import tempfile

    from kubernetes_tpu.shard.harness import run_sharded_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 10000))
    flightrec_dir = tempfile.mkdtemp(prefix="bench-trace-") if trace else ""
    # PER-SHARD warmup: the uid-hash partition splits the warm burst across
    # shards, so covering each shard's top device-batch tier (the XLA
    # compile the warm phase exists to pay) needs warm_pods to scale with
    # the shard count — otherwise every shard meets its full-queue batch
    # shape for the first time INSIDE the measured window, ~2s of compile
    # per tier that the 1-shard baseline never pays.
    warmup = int(os.environ.get("BENCH_WARMUP", 1024)) * n_shards
    out = run_sharded_cluster(
        n_shards, n_nodes, n_pods, warm_pods=warmup,
        flightrec_dir=flightrec_dir, replicas=replicas,
        deschedule={"managers": 2} if deschedule else None,
        settle_s=(float(os.environ.get("BENCH_SETTLE_S", 10.0))
                  if deschedule else 0.0),
        # 15s, not the chaos tests' 2-3s: the renewer is a Python thread,
        # and on an oversubscribed box (N shards + apiserver on few cores)
        # a tight lease flaps — a starved renewer misses one period, a peer
        # adopts the range, and the overlap burns CPU on duplicate
        # scheduling + 409s until handback. Failover speed is a chaos-test
        # concern, not a throughput-bench one.
        lease_duration=float(os.environ.get("BENCH_LEASE_DURATION", 15.0)))
    detail = {k: out[k] for k in ("shards", "bound", "all_bound",
                                  "elapsed_s", "distinct_bound_pods")}
    detail["api"] = out["api"]
    # Per-shard decoded events/bytes by wire form (watch-cache read plane +
    # shard-filtered streams): the 1/N event-decode claim, measurable on
    # any box — each shard's 'full' count should approach total/N with the
    # remainder arriving slim; 'read_plane' shows where the progress polls
    # landed (followers when --replicas > 0).
    detail["watch_decode"] = out.get("watch_decode")
    # Wire-plane summary (core/wire.py): server bytes by codec/surface,
    # server encode-µs by surface + delta mint/apply counters (PR 18 —
    # attributes any shard-scaling gap to encode CPU), and per-shard
    # decoded bytes by codec — the proof of WHICH plane ran and the
    # decoded-bytes delta vs the JSON baseline (PR-10: 4.87MB full /
    # 1.71MB slim per shard on this workload; PR-13: 2.06MB binary).
    detail["wire"] = out.get("wire")
    detail["read_plane"] = out.get("read_plane")
    if replicas:
        detail["replicas"] = out["replicas"]
        detail["replication"] = out["replication"]
    if deschedule:
        # Descheduler manager final stats (per process): moves_total by
        # strategy, moves_blocked by reason (pdb/budget/gang/hysteresis),
        # what-if batch count + seconds, final utilization stddev.
        detail["deschedule"] = out.get("deschedule")
    detail["shard_metrics"] = out["shard_metrics"]
    # Peak per-process RSS (MiB), sampled by the harness poll loop — the
    # paged read plane's bounded-memory claim as a number.
    detail["rss_mb"] = out.get("rss_mb")
    detail["platform"] = "cpu (sharded subprocesses)"
    # Hardware-availability context rides EVERY bench line (not just
    # --probe), so BENCH_*.json trajectories keep it.
    detail["availability"] = probe_availability()
    # e2e latency truth (scheduler_e2e_scheduling_duration_seconds, merged
    # across shards from /metrics) — the p50/p99 detail line.
    detail["e2e_ms"] = out.get("e2e_ms")
    if trace:
        from kubernetes_tpu import trace as trace_mod
        spans = trace_mod.load_spans([flightrec_dir])
        summary = trace_mod.summarize(spans)
        detail["trace"] = {
            "dir": flightrec_dir,
            "spans": summary["spans"],
            "traces": summary["traces"],
            "processes": summary["processes"],
            "completeness": summary["completeness"],
            "stage_p50_p99_ms": {
                name: [round(st["p50"] * 1e3, 3), round(st["p99"] * 1e3, 3)]
                for name, st in summary["stages"].items()},
            "conflicts": len(summary["conflicts"]),
        }
    print(json.dumps({
        "metric": (f"pods scheduled/sec ({n_nodes} nodes, {n_pods} pods, "
                   f"{n_shards}-shard plane, HTTP transport)"),
        "value": out["pods_per_sec"],
        "unit": "pods/s",
        "vs_baseline": round(out["pods_per_sec"] / BASELINE_PODS_PER_SEC, 2),
        "detail": detail,
    }))


def main(trace: bool = False):
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 10000))
    warmup = int(os.environ.get("BENCH_WARMUP", 1024))

    # BENCH_MESH_DEVICES=N: force an N-device virtual CPU mesh so the
    # SPMD plane (sharded state + shard_map row-local dispatch) benches
    # without hardware — the 50k-node SchedulingBasic acceptance shape.
    # Must land in XLA_FLAGS before any backend init.
    nd = int(os.environ.get("BENCH_MESH_DEVICES", 0))
    if nd > 1 and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={nd}").strip()

    platform_note, availability = _ensure_live_backend()
    cs, sched = build_cluster(n_nodes)

    # Warmup: compile both kernel traces (fresh + chained carry) with inert
    # n_active=0 dispatches, then run one real warm block for host caches.
    sched.warm_for(make_pods(1, "warmshape")[0])
    for p in make_pods(warmup, "warm"):
        cs.create_pod(p)
    sched.run_until_idle()
    # Snapshot every counter so the detail below covers ONLY the measured
    # window (previously device_scheduled was cumulative and exceeded
    # `scheduled` by exactly the warmup pods, which read as double-counting).
    warm_sched = sched.scheduled
    warm_failures = sched.failures
    # Window-diff every attributable counter (the same step-accounting split
    # the perf table reports — plan_build/device_wait/host_commit — plus the
    # plan-rebuild kinds), so the headline bench can attribute its own
    # number instead of printing an unexplained pods/s. One canonical list,
    # shared with the perf harness.
    from kubernetes_tpu.perf.harness import _ThroughputCollector
    WINDOW = _ThroughputCollector.WINDOW_COUNTERS
    win0 = {a: getattr(sched, a, 0) for a in WINDOW}

    for p in make_pods(n_pods, "bench"):
        cs.create_pod(p)
    t0 = time.perf_counter()
    sched.run_until_idle()
    elapsed = time.perf_counter() - t0

    scheduled = sched.scheduled - warm_sched
    pods_per_sec = scheduled / elapsed if elapsed > 0 else 0.0
    from kubernetes_tpu.shard.harness import rss_mb
    detail = {
        "scheduled": scheduled,
        "failures": sched.failures - warm_failures,
        "elapsed_s": round(elapsed, 2),
        "platform": platform_note + "/" + os.environ.get("JAX_PLATFORMS", "default"),
        # Availability + RSS context on every bench line: BENCH_*.json
        # trajectories keep the hardware story, and the memory claim is
        # a number (post-run VmRSS of this process).
        "availability": availability,
        "rss_mb": {"self": rss_mb()},
    }
    for a in WINDOW:
        d = getattr(sched, a, 0) - win0[a]
        detail[a] = round(d, 3) if isinstance(d, float) else d
    # Score-hint fast path engagement (models/score_hints.py): the share of
    # the window's pods bound host-side off the signature-keyed hint, with
    # zero device dispatches. A/B the dispatch-only baseline with
    # TPU_SCHED_SCORE_HINTS=0 on the same harness.
    if hasattr(sched, "hint_hits") and scheduled:
        detail["hint_hit_rate"] = round(detail.get("hint_hits", 0)
                                        / scheduled, 4)
    # Mesh plane: per-step ici/dcn collective counts of the EXACT dispatch
    # this workload's plan runs (shard_map row-local path vs GSPMD), plus
    # the shard_map engagement counter — the MULTICHIP rows regression-pin
    # the collective budget (docs/PERF.md § mesh plane).
    if getattr(sched, "mesh", None) is not None:
        detail["shard_map_dispatches"] = sched.shard_map_dispatches
        try:
            detail["collectives"] = sched.collective_counts(
                make_pods(1, "probe")[0])
        except Exception as e:  # noqa: BLE001 - detail only, never the run
            detail["collectives"] = {"error": str(e)[:200]}
    # e2e latency detail line (queue admission -> bound; fed from span ends
    # on EVERY bound pod — docs/OBSERVABILITY.md).
    e2e = sched.metrics.e2e_scheduling_duration
    if e2e.count():
        detail["e2e_ms"] = {
            "p50": round(e2e.percentile(0.50) * 1e3, 3),
            "p99": round(e2e.percentile(0.99) * 1e3, 3),
            "count": e2e.count()}
    if trace:
        import tempfile

        from kubernetes_tpu import trace as trace_mod
        from kubernetes_tpu.core import spans as _spans
        d = tempfile.mkdtemp(prefix="bench-trace-")
        path = _spans.default_tracer().dump_jsonl(
            os.path.join(d, f"spans-{os.getpid()}.jsonl"))
        summary = trace_mod.summarize(trace_mod.load_spans([path]))
        detail["trace"] = {
            "dir": d, "spans": summary["spans"],
            "traces": summary["traces"],
            "completeness": summary["completeness"],
            "stage_p50_p99_ms": {
                name: [round(st["p50"] * 1e3, 3), round(st["p99"] * 1e3, 3)]
                for name, st in summary["stages"].items()},
        }
    result = {
        "metric": f"pods scheduled/sec ({n_nodes} nodes, {n_pods} pods, device batch path)",
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
        "detail": detail,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    if "--probe" in sys.argv:
        sys.exit(probe())
    _trace = "--trace" in sys.argv
    if "--shards" in sys.argv:
        _replicas = (int(sys.argv[sys.argv.index("--replicas") + 1])
                     if "--replicas" in sys.argv else 0)
        main_sharded(int(sys.argv[sys.argv.index("--shards") + 1]),
                     trace=_trace, replicas=_replicas,
                     deschedule="--deschedule" in sys.argv)
        sys.exit(0)
    main(trace=_trace)
