"""kubernetes_tpu — a TPU-native scheduling framework with the capabilities of
the kube-scheduler subsystem in warmchang/kubernetes.

Layout (SURVEY.md §7):
- api/      the v1 object-model subset the scheduler consumes
- core/     host control plane: queue, cache/snapshot, framework runtime,
            scheduling loop, fake control plane
- plugins/  in-tree plugin oracle implementations (reference semantics)
- ops/      device backend: interned SoA state mirror + the JAX batch kernel
- parallel/ mesh/sharding for the node axis (ICI scale-out)
- models/   assembled scheduling pipelines ("flagship" = batched device path)
- testing/  fluent Pod/Node builders (testing/wrappers.go analogue)
- perf/     scheduler_perf-style benchmark harness
"""

__version__ = "0.1.0"
