"""Trace analyzer CLI: merge per-process span logs, print per-pod
critical-path breakdowns, per-stage latency percentiles, and cross-shard
conflict timelines.

    python -m kubernetes_tpu.trace <spans-or-flightrec .jsonl|dir>...
        [--stage-stats] [--critical-paths N] [--conflicts]
        [--completeness] [--chrome-trace out.json] [--json]

Inputs are span JSONL files produced by ``SpanRecorder.dump_jsonl`` or
flight-recorder artifacts (``flightrec-*.jsonl`` — span rows carry
``kind: span``); directories are scanned for both. Spans from any number
of processes merge by trace id (deterministic from the pod uid, so the
scheduler that bound a pod, the apiserver, and every foreign shard agree
with no coordination — core/spans.py). With no section flag, every
section prints. The stage taxonomy is the pinned contract in
``core/spans.py STAGES``; docs/OBSERVABILITY.md documents the output.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

from .core.spans import CORE_CHAIN, chrome_trace

# Pipeline order for critical-path rendering (wire order of the stages).
_STAGE_ORDER = {name: i for i, name in enumerate((
    "queue.admission", "queue.wait", "plan.build", "device.dispatch",
    "device.wait", "host.commit", "bind.post", "api.bind", "wal.append",
    "bound.fanout", "bound.observe", "pod.e2e"))}


def load_spans(paths: List[str]) -> List[dict]:
    """Load span rows from JSONL files/directories (flightrec artifacts
    included — only their ``kind: span`` rows qualify; a raw span dump has
    no ``kind`` field). Unparseable lines are skipped, not fatal: a crash
    dump may legally end mid-line."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        else:
            files.append(p)
    spans: List[dict] = []
    for path in files:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn final line of a crash dump
                    kind = row.get("kind")
                    if kind not in (None, "span"):
                        continue
                    if "trace" in row and "name" in row:
                        spans.append(row)
        except OSError:
            continue
    return spans


def merge_traces(spans: List[dict]) -> Dict[str, List[dict]]:
    """trace id → its spans, time-ordered."""
    traces: Dict[str, List[dict]] = {}
    for s in spans:
        traces.setdefault(s["trace"], []).append(s)
    for rows in traces.values():
        rows.sort(key=lambda s: (s.get("ts", 0.0),
                                 _STAGE_ORDER.get(s["name"], 99)))
    return traces


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def stage_stats(spans: List[dict]) -> Dict[str, dict]:
    """Per-stage duration percentiles (seconds), stage-order sorted."""
    by_stage: Dict[str, List[float]] = {}
    for s in spans:
        by_stage.setdefault(s["name"], []).append(float(s.get("dur", 0.0)))
    out: Dict[str, dict] = {}
    for name in sorted(by_stage, key=lambda n: (_STAGE_ORDER.get(n, 99), n)):
        vals = sorted(by_stage[name])
        out[name] = {
            "count": len(vals),
            "p50": _pct(vals, 0.50),
            "p95": _pct(vals, 0.95),
            "p99": _pct(vals, 0.99),
        }
    return out


def completeness(traces: Dict[str, List[dict]]) -> dict:
    """Of the traces that ended bound (have a bound.fanout or pod.e2e
    span), how many carry the full CORE_CHAIN, and how many processes each
    spanned. The bench acceptance gate (≥99% complete chains). The
    effective chain is the CORE_CHAIN stages the corpus exhibits AT ALL
    (reported as ``chain``): a memory-only apiserver has no wal.append, an
    in-process bench has no wire stages — per-trace gaps against the
    corpus-wide pipeline shape are what completeness measures."""
    observed = {s["name"] for rows in traces.values() for s in rows}
    chain = tuple(st for st in CORE_CHAIN if st in observed)
    bound = complete = 0
    proc_counts: List[int] = []
    missing: Dict[str, int] = {}
    for rows in traces.values():
        names = {s["name"] for s in rows}
        if "bound.fanout" not in names and "pod.e2e" not in names:
            continue
        bound += 1
        procs = {s.get("proc", "?") for s in rows}
        proc_counts.append(len(procs))
        gaps = [st for st in chain if st not in names]
        if gaps:
            for g in gaps:
                missing[g] = missing.get(g, 0) + 1
        else:
            complete += 1
    return {
        "bound_traces": bound,
        "complete_chains": complete,
        "complete_pct": round(100.0 * complete / bound, 2) if bound else 0.0,
        "chain": list(chain),
        "min_processes": min(proc_counts) if proc_counts else 0,
        "max_processes": max(proc_counts) if proc_counts else 0,
        "missing_stage_counts": missing,
    }


def critical_path(rows: List[dict]) -> List[dict]:
    """One trace's stage breakdown in pipeline order (pod.e2e excluded —
    it IS the total)."""
    stages = [s for s in rows if s["name"] != "pod.e2e"]
    stages.sort(key=lambda s: (_STAGE_ORDER.get(s["name"], 99),
                               s.get("ts", 0.0)))
    return stages


def conflict_timeline(traces: Dict[str, List[dict]]) -> List[dict]:
    """Cross-shard conflict timeline: who lost which node to whom, and the
    wait→retry cost (conflict instant → the eventual bind commit in the
    same trace)."""
    out: List[dict] = []
    for tid, rows in traces.items():
        conflicts = [s for s in rows if s["name"] == "bind.conflict"]
        if not conflicts:
            continue
        bind_end = None
        for s in rows:
            if s["name"] in ("pod.e2e", "api.bind"):
                end = s.get("ts", 0.0) + s.get("dur", 0.0)
                bind_end = end if bind_end is None else max(bind_end, end)
        for c in conflicts:
            attrs = c.get("attrs", {})
            retry = (bind_end - c.get("ts", 0.0)
                     if bind_end is not None and bind_end > c.get("ts", 0.0)
                     else None)
            out.append({
                "trace": tid,
                "ts": c.get("ts", 0.0),
                "loser": c.get("proc", "?"),
                "node": attrs.get("node", ""),
                "reason": attrs.get("reason", "conflict"),
                "retry_cost_s": round(retry, 6) if retry is not None else None,
            })
    out.sort(key=lambda e: e["ts"])
    return out


def failover_timeline(spans: List[dict]) -> List[dict]:
    """Control-plane promotions (replication.promote, 100%-sampled): who
    took over, at which fencing epoch, from which applied seq — rendered
    alongside the conflict timeline so cross-shard 409 bursts around a
    failover window read in causal order."""
    out = [{
        "ts": s.get("ts", 0.0),
        "proc": s.get("proc", "?"),
        "epoch": s.get("attrs", {}).get("epoch"),
        "seq": s.get("attrs", {}).get("seq"),
        "reason": s.get("attrs", {}).get("reason", ""),
    } for s in spans if s.get("name") == "replication.promote"]
    out.sort(key=lambda e: e["ts"])
    return out


def summarize(spans: List[dict]) -> dict:
    traces = merge_traces(spans)
    return {
        "spans": len(spans),
        "traces": len(traces),
        "processes": sorted({s.get("proc", "?") for s in spans}),
        "stages": stage_stats(spans),
        "completeness": completeness(traces),
        "conflicts": conflict_timeline(traces),
        "failovers": failover_timeline(spans),
    }


def _fmt_ms(v: float) -> str:
    return f"{v * 1e3:9.3f}"


def _print_report(summary: dict, traces: Dict[str, List[dict]],
                  n_paths: int, out=sys.stdout) -> None:
    w = out.write
    w(f"{summary['spans']} spans / {summary['traces']} traces from "
      f"{len(summary['processes'])} process(es): "
      f"{', '.join(summary['processes'])}\n")
    comp = summary["completeness"]
    w(f"complete chains: {comp['complete_chains']}/{comp['bound_traces']} "
      f"bound traces ({comp['complete_pct']}%), spanning "
      f"{comp['min_processes']}-{comp['max_processes']} processes\n")
    if comp["missing_stage_counts"]:
        w(f"  missing stages: {comp['missing_stage_counts']}\n")
    w("\nper-stage latency (ms):\n")
    w(f"{'stage':<16} {'count':>7} {'p50':>9} {'p95':>9} {'p99':>9}\n")
    for name, st in summary["stages"].items():
        w(f"{name:<16} {st['count']:>7} {_fmt_ms(st['p50'])} "
          f"{_fmt_ms(st['p95'])} {_fmt_ms(st['p99'])}\n")
    if summary.get("failovers"):
        w("\nfailover timeline:\n")
        for f in summary["failovers"]:
            w(f"  t={f['ts']:.6f} {f['proc']} promoted to leader "
              f"(epoch {f['epoch']}, seq {f['seq']}, {f['reason']})\n")
    if summary["conflicts"]:
        w("\nconflict timeline:\n")
        for c in summary["conflicts"]:
            cost = (f"rebound after {c['retry_cost_s'] * 1e3:.1f}ms"
                    if c["retry_cost_s"] is not None else "never rebound")
            w(f"  t={c['ts']:.6f} {c['loser']} lost "
              f"{c['node'] or '<node?>'} ({c['reason']}) trace={c['trace']} "
              f"-> {cost}\n")
    if n_paths:
        # Longest per-pod critical paths first: where the time actually went.
        with_e2e = []
        for tid, rows in traces.items():
            e2e = next((s for s in rows if s["name"] == "pod.e2e"), None)
            if e2e is not None:
                with_e2e.append((float(e2e.get("dur", 0.0)), tid, rows))
        with_e2e.sort(reverse=True)
        w(f"\ntop {min(n_paths, len(with_e2e))} critical paths:\n")
        for total, tid, rows in with_e2e[:n_paths]:
            w(f"  trace {tid} e2e={total * 1e3:.3f}ms\n")
            for s in critical_path(rows):
                w(f"    {s['name']:<16} {_fmt_ms(float(s.get('dur', 0.0)))}ms"
                  f"  [{s.get('proc', '?')}]"
                  f"{' ' + json.dumps(s['attrs']) if s.get('attrs') else ''}\n")


def main(argv: Optional[List[str]] = None, out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes-tpu-trace", description=(
        "merge per-process span logs by trace id; print per-pod "
        "critical paths, per-stage p50/p95/p99, conflict timelines"))
    ap.add_argument("inputs", nargs="+",
                    help="span/flightrec .jsonl files or directories")
    ap.add_argument("--critical-paths", type=int, default=3, metavar="N",
                    help="show the N slowest per-pod critical paths")
    ap.add_argument("--chrome-trace", default="", metavar="OUT.json",
                    help="also write a Chrome trace_event file (Perfetto)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    args = ap.parse_args(argv)

    spans = load_spans(args.inputs)
    if not spans:
        print("no spans found", file=sys.stderr)
        return 1
    summary = summarize(spans)
    if args.chrome_trace:
        with open(args.chrome_trace, "w") as f:
            json.dump(chrome_trace(spans), f)
        summary["chrome_trace"] = args.chrome_trace
    if args.json:
        out.write(json.dumps(summary, indent=2) + "\n")
    else:
        _print_report(summary, merge_traces(spans), args.critical_paths, out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
