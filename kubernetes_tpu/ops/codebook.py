"""String interning for the device mirror.

Everything string-ish in the API objects (taint keys/values, label key=value
pairs, topology values, node names) must become small dense integer ids before
it can live in device tensors (SURVEY.md §7.2: "everything string-ish must be
interned host-side"). Id 0 is always the reserved empty/absent sentinel so
device code can use `== 0` for "unset" and padding.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Optional


class Codebook:
    """Monotonic hashable→dense-int interner. Id 0 is reserved for the empty
    sentinel (``""`` by default); ids are never reused or reordered, so device
    rows built against an older codebook stay valid as it grows."""

    __slots__ = ("_ids", "_items")

    def __init__(self, sentinel: Hashable = ""):
        self._ids: Dict[Hashable, int] = {sentinel: 0}
        self._items: List[Hashable] = [sentinel]

    def intern(self, item: Hashable) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._items)
            self._ids[item] = i
            self._items.append(item)
        return i

    def lookup(self, item: Hashable) -> int:
        """Id of an already-interned item, or -1 if unseen. -1 never equals
        any stored id, so lookups of unseen values compare false on device."""
        return self._ids.get(item, -1)

    def item(self, i: int) -> Hashable:
        return self._items[i]

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Hashable) -> bool:
        return item in self._ids


# Fixed taint-effect encoding shared by host feature extraction and the device
# kernel (api/types.py NO_SCHEDULE/PREFER_NO_SCHEDULE/NO_EXECUTE).
EFFECT_EMPTY = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3

EFFECT_IDS = {
    "": EFFECT_EMPTY,
    "NoSchedule": EFFECT_NO_SCHEDULE,
    "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
    "NoExecute": EFFECT_NO_EXECUTE,
}

# Toleration operator encoding.
OP_EQUAL = 0
OP_EXISTS = 1
