"""Device mirror of the scheduler snapshot: fixed-capacity SoA node tensors.

This is the TPU-era equivalent of the reference's incremental snapshot refresh
(pkg/scheduler/backend/cache/cache.go:206 UpdateSnapshot, generation walk at
:236-262): the mirror keeps one row per node in `snapshot.node_info_list`
order, re-encodes only rows whose NodeInfo.generation advanced (or whose list
position changed), and flushes them to device with a scatter when few rows are
dirty, a full upload otherwise.

Row order == snapshot list order, so the kernel's rotation arithmetic
(schedule_one.go:816 nextStartNodeIndex) operates directly on row indices.

All quantities are int64: resource units are integers by construction
(api/resource.py canonicalises CPU to millicores, memory to bytes), and the
kernel's score math is specified in exact integer arithmetic so host oracle
and device agree bit-for-bit (see ops/kernel.py).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

import os

import jax

jax.config.update("jax_enable_x64", True)


def enable_persistent_compilation_cache() -> None:
    """Persistent XLA compilation cache: kernel compiles run 30-90s on TPU,
    and the perf/bench harnesses start fresh processes per run — without this
    every process pays every compile again. Called from TPUScheduler.__init__
    (constructing the device-backed scheduler is the opt-in; merely importing
    the library must not redirect an embedding application's JAX caching).
    Opt out with KUBERNETES_TPU_NO_XLA_CACHE=1."""
    if os.environ.get("KUBERNETES_TPU_NO_XLA_CACHE"):
        return
    if jax.config.jax_compilation_cache_dir:
        return  # the application already configured a cache; respect it
    cache_dir = os.environ.get(
        "KUBERNETES_TPU_XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "kubernetes_tpu_xla"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (OSError, AttributeError):  # read-only FS or old jax: best-effort
        pass

import jax.numpy as jnp  # noqa: E402

from ..api import resource as res  # noqa: E402
from ..core.node_info import NodeInfo  # noqa: E402
from .codebook import EFFECT_IDS, Codebook  # noqa: E402

# Resource slot layout: [cpu_milli, memory, ephemeral_storage, *scalar_slots].
BASE_RESOURCES = 3
SLOT_CPU = 0
SLOT_MEMORY = 1
SLOT_EPHEMERAL = 2


class DeviceNodeState(NamedTuple):
    """The pytree of node tensors the kernel consumes."""

    alloc_r: jnp.ndarray      # [NP, R] i64 allocatable per resource slot
    alloc_pods: jnp.ndarray   # [NP]    i64 allocatable pod count
    req_r: jnp.ndarray        # [NP, R] i64 requested (assumed+bound pods)
    nonzero: jnp.ndarray      # [NP, 2] i64 non-zero-default cpu/mem aggregate
    pod_count: jnp.ndarray    # [NP]    i32
    taint_key: jnp.ndarray    # [NP, T] i32 interned taint keys (0 pad)
    taint_val: jnp.ndarray    # [NP, T] i32
    taint_eff: jnp.ndarray    # [NP, T] i32 (EFFECT_* ids; 0 pad = inert)
    unsched: jnp.ndarray      # [NP]    bool node.spec.unschedulable
    valid: jnp.ndarray        # [NP]    bool row holds a live node
    name_id: jnp.ndarray      # [NP]    i32 interned node name
    topo: jnp.ndarray         # [K, NP] i32 per-axis topology value ids (0 = absent)


def patch_tier(n: int) -> int:
    """Dirty-row scatter/patch tiers: {32, 256, pow2 from 2048}. Each
    distinct padded length is a separate XLA compile of the patch jits
    (row scatter + carry re-eval), and event-driven patch waves — peer
    shards' bind bursts above all — arrive in near-arbitrary sizes, so
    pow2 tiers from 1 put ~10 compiles inside a sharded run's measured
    window. Padding repeats a real index; duplicate scatter indices write
    identical values, so a coarse tier is exact (just a few wasted rows
    of device work)."""
    if n <= 32:
        return 32
    if n <= 256:
        return 256
    return _pow2(n, 2048)


def _pow2(n: int, floor: int) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


class TopoAxis:
    """One registered topology key (e.g. topology.kubernetes.io/zone):
    per-key value codebook + its row in the mirror's `topo` tensor.

    Value id 0 means "key absent"; a label present with an EMPTY value (legal
    in Kubernetes, and a real domain for topology spreading) is interned under
    a private token so it gets a distinct non-zero id."""

    __slots__ = ("key", "index", "values")

    _EMPTY_TOKEN = "\x00empty"

    def __init__(self, key: str, index: int):
        self.key = key
        self.index = index
        self.values = Codebook()

    def intern_value(self, val: str) -> int:
        return self.values.intern(val if val != "" else self._EMPTY_TOKEN)

    def lookup_value(self, val: str) -> int:
        return self.values.lookup(val if val != "" else self._EMPTY_TOKEN)


def _scatter_rows_impl(state: DeviceNodeState, idx, rows: DeviceNodeState) -> DeviceNodeState:
    """Dirty-row scatter as ONE compiled executable (13 per-array scatters
    fused; a separate jit per array would compile 13 executables per tier)."""
    updated = [arr.at[idx].set(r) for arr, r in zip(state[:-1], rows[:-1])]
    topo = state.topo.at[:, idx].set(rows.topo)
    return DeviceNodeState(*updated, topo)


_scatter_rows = jax.jit(_scatter_rows_impl)

# Mesh variant: one jitted scatter per (out_shardings pytree, donation) —
# parallel/mesh.py mesh_state_shardings caches the pytree, NamedSharding
# hashes, so the pytree itself is the cache key.
_SHARDED_SCATTER_CACHE: dict = {}


def _sharded_scatter(out_shardings, donate: bool = False):
    """_scatter_rows with explicit out_shardings: a mesh session's state is
    committed to the mirror's placement and the session kernel's jit keys
    on those input shardings — an unconstrained scatter would hand back
    GSPMD-chosen placements and retrace the kernel on next dispatch.

    ``donate=True`` additionally donates the OLD state buffers into the
    scatter (the session patch seam): the patched state replaces the old
    one in-place on device instead of allocating a full sharded copy per
    patch wave. Callers must rebind every live reference to the returned
    pytree — the mirror resident and the session's _SessionDelta.state are
    the only two, both rebound at the patch_rows call site."""
    key = (out_shardings, donate)
    fn = _SHARDED_SCATTER_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_scatter_rows_impl, out_shardings=out_shardings,
                     donate_argnums=(0,) if donate else ())
        _SHARDED_SCATTER_CACHE[key] = fn
    return fn


class NodeStateMirror:
    """Host-side staging + device flush for DeviceNodeState."""

    def __init__(
        self,
        node_capacity: int = 64,
        taint_capacity: int = 4,
        label_capacity: int = 32,
        scalar_capacity: int = 4,
        axis_capacity: int = 4,
        scatter_threshold: float = 0.25,
    ):
        self.np_cap = node_capacity
        self.t_cap = taint_capacity
        self.l_cap = label_capacity
        self.s_cap = scalar_capacity
        self.k_cap = axis_capacity
        self.scatter_threshold = scatter_threshold

        self.keys = Codebook()        # taint keys (shared with tolerations)
        self.vals = Codebook()        # taint values
        self.names = Codebook()       # node names
        self.scalar_slots: Dict[str, int] = {}  # scalar resource -> slot >= BASE_RESOURCES
        self.axes: Dict[str, TopoAxis] = {}

        self._alloc_storage()
        self._row_names: List[str] = []
        self._row_gen: List[int] = []
        self._dirty: set = set()
        self._full_flush = True
        self._device: Optional[DeviceNodeState] = None
        # Shardings the resident device copy is COMMITTED to (None =
        # single-device). Under a mesh, flush() uploads host staging
        # straight to the sharded placement and dirty-row scatters ride a
        # jit pinned to these shardings — the sharded state IS the resident
        # (mesh-first), not a per-session device_put round-trip of a
        # single-device copy.
        self._shardings = None
        self.num_nodes = 0

    # -- storage -----------------------------------------------------------

    @property
    def r_slots(self) -> int:
        return BASE_RESOURCES + self.s_cap

    def _alloc_storage(self) -> None:
        npc, t, l, r, k = self.np_cap, self.t_cap, self.l_cap, self.r_slots, self.k_cap
        self.h_alloc_r = np.zeros((npc, r), np.int64)
        self.h_alloc_pods = np.zeros(npc, np.int64)
        self.h_req_r = np.zeros((npc, r), np.int64)
        self.h_nonzero = np.zeros((npc, 2), np.int64)
        self.h_pod_count = np.zeros(npc, np.int32)
        self.h_taint_key = np.zeros((npc, t), np.int32)
        self.h_taint_val = np.zeros((npc, t), np.int32)
        self.h_taint_eff = np.zeros((npc, t), np.int32)
        self.h_unsched = np.zeros(npc, bool)
        self.h_valid = np.zeros(npc, bool)
        self.h_name_id = np.zeros(npc, np.int32)
        self.h_topo = np.zeros((k, npc), np.int32)

    def _grow(self, node_capacity=None, taint_capacity=None, label_capacity=None,
              scalar_capacity=None, axis_capacity=None) -> None:
        """Capacity tier change: reallocate staging and force a full re-encode
        + full flush (shape change ⇒ the kernel recompiles once per tier,
        SURVEY.md §7 'padding + capacity tiers and a recompile policy')."""
        self.np_cap = node_capacity or self.np_cap
        self.t_cap = taint_capacity or self.t_cap
        self.l_cap = label_capacity or self.l_cap
        self.s_cap = scalar_capacity or self.s_cap
        self.k_cap = axis_capacity or self.k_cap
        self._alloc_storage()
        self._row_names = []
        self._row_gen = []
        self._full_flush = True
        self._device = None

    # -- axes / scalar slots ----------------------------------------------

    def ensure_axis(self, key: str) -> TopoAxis:
        ax = self.axes.get(key)
        if ax is not None:
            return ax
        if len(self.axes) >= self.k_cap:
            self._grow(axis_capacity=self.k_cap * 2)
            # staging was reset; existing axes refill on next sync
        ax = TopoAxis(key, len(self.axes))
        self.axes[key] = ax
        # Existing rows lack the new axis column: force re-encode on next sync.
        self._full_flush = True
        self._row_gen = [-1] * len(self._row_gen)
        return ax

    def scalar_slot(self, resource_name: str) -> int:
        slot = self.scalar_slots.get(resource_name)
        if slot is not None:
            return slot
        if len(self.scalar_slots) >= self.s_cap:
            self._grow(scalar_capacity=self.s_cap * 2)
        slot = BASE_RESOURCES + len(self.scalar_slots)
        self.scalar_slots[resource_name] = slot
        return slot

    # -- row encoding ------------------------------------------------------

    def _resource_vec(self, r: "res.Resource", out: np.ndarray) -> None:
        out[:] = 0
        out[SLOT_CPU] = r.milli_cpu
        out[SLOT_MEMORY] = r.memory
        out[SLOT_EPHEMERAL] = r.ephemeral_storage
        for name, amount in r.scalar_resources.items():
            slot = self.scalar_slot(name)
            if slot >= out.shape[0]:
                # scalar_slot grew the capacity tier and reallocated staging;
                # `out` points into the orphaned old arrays — re-walk.
                raise _Regrown()
            out[slot] = amount

    def _encode_row(self, i: int, ni: NodeInfo) -> None:
        node = ni.node
        self._resource_vec(ni.allocatable, self.h_alloc_r[i])
        self.h_alloc_pods[i] = ni.allocatable.allowed_pod_number
        self._resource_vec(ni.requested, self.h_req_r[i])
        self.h_nonzero[i, 0] = ni.non_zero_requested.milli_cpu
        self.h_nonzero[i, 1] = ni.non_zero_requested.memory
        self.h_pod_count[i] = len(ni.pods)
        taints = node.taints if node else []
        if len(taints) > self.t_cap:
            self._grow(taint_capacity=_pow2(len(taints), self.t_cap * 2))
            raise _Regrown()
        self.h_taint_key[i] = 0
        self.h_taint_val[i] = 0
        self.h_taint_eff[i] = 0
        for j, t in enumerate(taints):
            self.h_taint_key[i, j] = self.keys.intern(t.key)
            self.h_taint_val[i, j] = self.vals.intern(t.value)
            self.h_taint_eff[i, j] = EFFECT_IDS.get(t.effect, 0)
        self.h_unsched[i] = bool(node and node.unschedulable)
        self.h_valid[i] = node is not None
        self.h_name_id[i] = self.names.intern(node.name) if node else 0
        labels = node.labels if node else {}
        for ax in self.axes.values():
            val = labels.get(ax.key)
            self.h_topo[ax.index, i] = ax.intern_value(val) if val is not None else 0

    # -- sync --------------------------------------------------------------

    def sync(self, node_info_list: Sequence[NodeInfo]) -> None:
        """Re-encode rows whose generation or position changed (the device
        analogue of cache.go:236-262's generation walk)."""
        n = len(node_info_list)
        if n > self.np_cap:
            self._grow(node_capacity=_pow2(n, self.np_cap * 2))
        while True:
            try:
                self._sync_rows(node_info_list)
                break
            except _Regrown:
                continue  # capacity tier changed: staging reset, re-walk
        self.num_nodes = n

    def _sync_rows(self, node_info_list: Sequence[NodeInfo]) -> None:
        n = len(node_info_list)
        names, gens = self._row_names, self._row_gen
        for i, ni in enumerate(node_info_list):
            if i < len(names) and names[i] == ni.name and gens[i] == ni.generation:
                continue
            self._encode_row(i, ni)
            if i < len(names):
                names[i] = ni.name
                gens[i] = ni.generation
            else:
                names.append(ni.name)
                gens.append(ni.generation)
            self._dirty.add(i)
        if len(names) > n:  # shrink: invalidate tail rows
            for i in range(n, len(names)):
                self.h_valid[i] = False
                self._dirty.add(i)
            del names[n:]
            del gens[n:]

    # -- flush -------------------------------------------------------------

    def _arrays(self):
        return (
            self.h_alloc_r, self.h_alloc_pods, self.h_req_r, self.h_nonzero,
            self.h_pod_count, self.h_taint_key, self.h_taint_val,
            self.h_taint_eff, self.h_unsched, self.h_valid, self.h_name_id,
        )

    def _dirty_payload(self, dirty):
        """(idx, rows) scatter operands for the given staging rows. Pads to
        a coarse tier (patch_tier) by repeating the last index (scatter-set
        with duplicate indices writes the same value), so the jitted scatter
        compiles once per tier, not once per dirty-count."""
        tier = patch_tier(len(dirty))
        dirty = dirty + [dirty[-1]] * (tier - len(dirty))
        idx = jnp.asarray(dirty, jnp.int32)
        rows = DeviceNodeState(
            *[jnp.asarray(a[dirty]) for a in self._arrays()],
            jnp.asarray(self.h_topo[:, dirty]))
        return idx, rows

    def commit_shardings(self, out_shardings) -> None:
        """Commit the resident device copy to these NamedShardings (None =
        single-device). Called by build_plan before sync/flush; a changed
        commitment forces a full re-upload at the new placement. Identity
        comparison is exact: parallel/mesh.py mesh_state_shardings caches
        one pytree per mesh."""
        if out_shardings is not self._shardings:
            self._shardings = out_shardings
            self._device = None
            self._full_flush = True

    def _upload(self) -> DeviceNodeState:
        """Full host→device upload of staging, straight to the committed
        placement (one transfer per array; no intermediate single-device
        copy when sharded)."""
        if self._shardings is None:
            return DeviceNodeState(
                *[jnp.asarray(a) for a in self._arrays()],
                jnp.asarray(self.h_topo))
        return DeviceNodeState(
            *[jax.device_put(a, s) for a, s in
              zip(self._arrays() + (self.h_topo,), self._shardings)])

    def _resident_deleted(self) -> bool:
        """True when the resident arrays came from a session carry (adopt)
        that was later DONATED back to the kernel or a patch jit. adopt and
        the patch seam keep host staging in line, so a full upload from
        staging reproduces the exact device truth."""
        if self._device is None:
            return False
        try:
            return self._device.req_r.is_deleted()
        except AttributeError:
            return False

    def _scatter_dirty(self, dirty) -> DeviceNodeState:
        """Scatter the given staging rows into the resident device state."""
        idx, rows = self._dirty_payload(dirty)
        if self._shardings is not None:
            return _sharded_scatter(self._shardings)(self._device, idx, rows)
        return _scatter_rows(self._device, idx, rows)

    def flush(self) -> DeviceNodeState:
        """Upload pending changes; returns the device pytree (committed to
        `commit_shardings`' placement). Scatter when the dirty fraction is
        small, full upload otherwise."""
        if not self._full_flush and self._resident_deleted():
            self._full_flush = True
        if self._device is None or self._full_flush:
            self._device = self._upload()
        elif self._dirty:
            if len(self._dirty) > self.scatter_threshold * self.np_cap:
                self._device = self._upload()
            else:
                self._device = self._scatter_dirty(sorted(self._dirty))
        self._dirty.clear()
        self._full_flush = False
        return self._device


    def patch_rows(self, updates, sharded_state=None,
                   out_shardings=None,
                   donate: bool = True) -> Optional[DeviceNodeState]:
        """Event-delta row flush: re-encode the given (row, NodeInfo) pairs
        from the LIVE cache NodeInfos and scatter them into the resident
        device state WITHOUT a snapshot refresh — the journal-driven
        analogue of sync+flush for a session that stays on device. Returns
        the patched DeviceNodeState, or None when a row patch can't apply
        (no resident device copy / full upload pending, a capacity tier grew
        mid-encode, row out of range or name mismatch) — callers fall back
        to the full rebuild path, which recovers from every one of those.

        Mesh sessions pass `sharded_state` (their mesh-committed state) plus
        `out_shardings` (parallel/mesh.py mesh_state_shardings): the dirty
        rows scatter through a jit pinned to those shardings, so the
        patched pytree keeps the exact placement the session kernel's
        traces key on. When the session state IS the mirror's resident
        (the mesh-first steady state — build_plan commits the resident to
        the mesh placement), ONE donated scatter updates both: the old
        buffers are donated into the patch jit and every live reference
        (resident + _SessionDelta.state) is rebound to the result."""
        if self._device is None or self._full_flush:
            return None
        if self._resident_deleted():
            # The resident was donated back to a kernel/patch jit (session
            # resume chain); staging is authoritative — full upload path.
            self._full_flush = True
            return None
        # Validate EVERY row before encoding ANY: a late-row guard failure
        # after earlier rows hit staging would leave those rows encoded with
        # current generations but never scattered — the fallback's sync
        # would then skip them and the device copy would stay stale forever.
        # (_Regrown mid-encode is safe: _grow resets staging + generations
        # and pends a full upload.)
        for row, ni in updates:
            if (row >= self.np_cap or row >= len(self._row_names)
                    or ni.name != self._row_names[row]):
                return None
        try:
            for row, ni in updates:
                self._encode_row(row, ni)
                self._row_gen[row] = ni.generation
        except _Regrown:
            return None  # staging reset: next flush rebuilds everything
        dirty = sorted({row for row, _ in updates})
        idx, rows = self._dirty_payload(dirty)
        if sharded_state is not None and sharded_state is self._device:
            # Mesh-first steady state: session state == resident. One
            # pinned scatter patches it — DONATED (in-place buffer reuse)
            # unless the caller's dispatch pipeline still holds in-flight
            # reads of the old state (`donate=False`, the busy-patch seam).
            self._device = _sharded_scatter(out_shardings, donate=donate)(
                sharded_state, idx, rows)
            self._dirty.difference_update(dirty)
            return self._device
        self._device = (_sharded_scatter(self._shardings)(
            self._device, idx, rows) if self._shardings is not None
            else _scatter_rows(self._device, idx, rows))
        self._dirty.difference_update(dirty)
        if sharded_state is not None:
            return _sharded_scatter(out_shardings)(sharded_state, idx, rows)
        return self._device

    def invalidate(self) -> None:
        """Force a full staging re-encode + full upload on the next
        sync/flush (used when a device session diverged from the host: the
        carry can no longer be trusted as the device truth)."""
        self._full_flush = True
        self._row_gen = [-1] * len(self._row_gen)

    # -- carry adoption (device-resident steady state) ---------------------

    def adopt(
        self,
        node_info_list: Sequence[NodeInfo],
        rows: Sequence[int],
        req_r: jnp.ndarray,
        nonzero: jnp.ndarray,
        pod_count: jnp.ndarray,
        dirty_rows: Sequence[int] = (),
    ) -> None:
        """After a device batch: the kernel's final carry already holds the
        updated per-node aggregates, so install those arrays directly and
        bring the host staging + generations in line WITHOUT marking rows
        dirty — the next flush() then uploads nothing. Rows whose host commit
        failed (carry diverged from cache) go through the normal dirty path.

        This is the device-resident analogue of cache.go's incremental
        UpdateSnapshot: in steady state the only node changes are the batch's
        own placements, which the device already has."""
        if self._device is None or self._full_flush:
            return  # a full upload from (authoritative) staging is pending
        try:
            for i in rows:
                if i < len(node_info_list):
                    ni = node_info_list[i]
                    # Only the resource aggregates change on our own
                    # placements — re-encode just those columns (the full
                    # row encode is ~3x the work and taints/labels/topology
                    # can't have moved without a generation-bumping event,
                    # which ends the session before adopt).
                    self._resource_vec(ni.requested, self.h_req_r[i])
                    self.h_nonzero[i, 0] = ni.non_zero_requested.milli_cpu
                    self.h_nonzero[i, 1] = ni.non_zero_requested.memory
                    self.h_pod_count[i] = len(ni.pods)
                    if i < len(self._row_names):
                        self._row_gen[i] = ni.generation
        except _Regrown:
            return  # staging reset; full flush will rebuild everything
        self._device = self._device._replace(
            req_r=req_r, nonzero=nonzero, pod_count=pod_count)
        for i in dirty_rows:
            self._dirty.add(i)


class _Regrown(Exception):
    """Internal: a capacity tier changed mid-encode; re-walk the snapshot."""
