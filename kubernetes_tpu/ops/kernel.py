"""The batch scheduling kernel: the whole Filter→Score hot path
(schedule_one.go findNodesThatFitPod :630 / prioritizeNodes :945) as ONE
jit-compiled dense pods×nodes evaluation, with the greedy sequential
assignment loop running on device as a lax.scan.

Replaces the reference's per-node goroutine fan-out
(parallelize/parallelism.go:28 Parallelizer, 16 goroutines, √n chunks) with
vectorized masks over the node axis, and the reference's per-pod scheduling
cycles with a scan whose carry holds exactly the state one pod's placement
changes for the next pod: per-node requested vectors, per-domain topology
match counts, and inter-pod-affinity count tables.

Performance shape (measured on TPU-via-tunnel, where each vector op in a
sequential dependency chain pays ~60µs of latency regardless of width):
the scan body is written to MINIMIZE DEPENDENT STAGES, not op count —
- per-step domain-count lookups ride the carry as per-NODE projections
  (mnum/scnt/acnt/fcnt/dproj) updated with elementwise compares against the
  landed row's topology value, instead of take_along_axis gathers (a TPU
  gather serializes and costs ~40µs alone);
- all windowed normalization min/max reductions collapse into ONE stacked
  [k, NP] max-reduction (mins ride as negated lanes), and selection is a
  second single reduction over a packed (score, rotation) key;
- batches whose score vector cannot change except at the landed row carry
  the total score; batches with no cross-window coupling at all take the
  lap-vectorized path (_lap_schedule) which places L pods per iteration.

Semantics parity (bit-exact vs the host oracle, enforced by
tests/test_device_equivalence.py):
- feasibility: NodeName, NodeUnschedulable, TaintToleration,
  node_selector, NodeResourcesFit (fit.go:710 fitsRequest),
  PodTopologySpread DoNotSchedule skew test (filtering.go:358),
  InterPodAffinity required terms incl. the bootstrap case
  (filtering.go:368-426);
- adaptive sampling + rotation: numFeasibleNodesToFind truncation and
  nextStartNodeIndex advance (schedule_one.go:779-892) are emulated with a
  rotation-order cumulative count, so the device picks the IDENTICAL node the
  sequential host loop would;
- scoring: TaintToleration (×3), NodeResourcesFit LeastAllocated/MostAllocated
  (×1), BalancedAllocation integer-quantized (×1), PodTopologySpread
  ScheduleAnyway (×2), InterPodAffinity (×2), each normalized over the kept
  (sampled feasible) set exactly as runtime/framework.go:1526-1582 does;
- selection: max total score, ties broken by first position in rotation order
  (the host's deterministic-tie mode; the reference randomizes ties,
  schedule_one.go selectHost).

Pallas note (evaluated, deliberately not used): a hand-written Pallas kernel
could fuse the lap loop's iterations and pin the node tensors in VMEM
(5k x 8 i64 ~ 320KB — fits), saving per-iteration dispatch + HBM traffic.
It loses on two hard constraints: (1) the scheduler's score math is
SPECIFIED in exact int64 arithmetic so host and device agree bit-for-bit
(memory quantities alone exceed int32), and Pallas-TPU's int64 support is
poor — rescaling to int32 domains would change integer-division results and
break the equivalence contract; (2) the op mix is masked elementwise +
small reductions with no matmul — the MXU is idle either way and XLA
already fuses the VPU work, so the ceiling is per-op issue latency, which
the lap/scan restructuring (few dependent stages) addresses directly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .codebook import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    OP_EXISTS,
)
from .device_state import DeviceNodeState
from .features import BatchFeatures

MAX_NODE_SCORE = 100
_BIG = jnp.int32(1 << 30)
_INF64 = jnp.int64(1 << 60)


class ScanCarry(NamedTuple):
    """The kernel's dynamic state. Returned by schedule_batch and accepted
    back as `carry_in`, so consecutive same-signature batches CHAIN on device
    with no host roundtrip or feature rebuild between them — the device-
    resident generalization of keeping the snapshot incremental
    (cache.go:206): in steady state the only state changes are the batch's
    own placements, which the carry already holds."""

    req_r: jnp.ndarray        # [NP, R] i64 requested per node
    nonzero: jnp.ndarray      # [NP, 2] i64 non-zero-default cpu/mem
    pod_count: jnp.ndarray    # [NP]    i32
    fit_ok: jnp.ndarray       # [NP]    bool
    fit_sc: jnp.ndarray       # [NP]    i64
    ba: jnp.ndarray           # [NP]    i64
    dns_counts: jnp.ndarray   # [C1, V] i32
    sa_counts: jnp.ndarray    # [C2, V] i32
    anti_counts: jnp.ndarray  # [A1, V] i32
    aff_counts: jnp.ndarray   # [A2, V] i32
    ipa_delta: jnp.ndarray    # [KD, V] i64
    start: jnp.ndarray        # i32 rotation index
    blocked: jnp.ndarray      # [NP] bool rows self-blocked by a landing (ports)
    aux_cnt: jnp.ndarray      # [NP] i32 aux units consumed by landings (CSI)


def _tolerates(f: BatchFeatures, taint_key, taint_val, taint_eff):
    """tolerated[n, t] — any toleration row matches the taint
    (component-helpers ToleratesTaint, api/types.py Toleration.tolerates)."""
    tk = f.tol_key[None, None, :]
    tv = f.tol_val[None, None, :]
    te = f.tol_eff[None, None, :]
    to = f.tol_op[None, None, :]
    k = taint_key[:, :, None]
    v = taint_val[:, :, None]
    e = taint_eff[:, :, None]
    eff_ok = (te == 0) | (te == e)
    key_ok = (tk == 0) | (tk == k)
    val_ok = (to == OP_EXISTS) | (tv == v)
    return eff_ok & key_ok & val_ok  # [N, T, L]


def _static_masks(state: DeviceNodeState, f: BatchFeatures):
    """Per-batch node predicates that no assignment can change."""
    # taints
    m = _tolerates(f, state.taint_key, state.taint_val, state.taint_eff)
    tolerated = m.any(axis=2) if f.tol_key.shape[0] else jnp.zeros(state.taint_key.shape, bool)
    sched_relevant = (state.taint_eff == EFFECT_NO_SCHEDULE) | (
        state.taint_eff == EFFECT_NO_EXECUTE)
    taint_ok = ~(sched_relevant & ~tolerated).any(axis=1)  # [N]
    # PreferNoSchedule score counts (taint_toleration.go:182-194)
    pns_tol_ok = (f.tol_eff == 0) | (f.tol_eff == EFFECT_PREFER_NO_SCHEDULE)
    if f.tol_key.shape[0]:
        pns_tolerated = (m & pns_tol_ok[None, None, :]).any(axis=2)
    else:
        pns_tolerated = jnp.zeros(state.taint_key.shape, bool)
    pns_cnt = ((state.taint_eff == EFFECT_PREFER_NO_SCHEDULE) & ~pns_tolerated).sum(
        axis=1).astype(jnp.int64)  # [N]
    # Full node-selector + required-node-affinity verdict, host-evaluated
    # (static per batch — ops/features.py sel_match).
    sel_ok = f.sel_match
    # cheap gates
    name_ok = (f.node_name_id == 0) | (state.name_id == f.node_name_id)
    unsched_ok = ~state.unsched | (f.tolerates_unsched == 1)
    exist_anti_ok = f.exist_anti == 0
    # Profile filter enablement (a disabled filter plugin never rejects).
    name_ok |= f.enable[0] == 0
    unsched_ok |= f.enable[1] == 0
    taint_ok |= f.enable[2] == 0
    sel_ok |= f.enable[3] == 0
    return taint_ok, pns_cnt, sel_ok, name_ok, unsched_ok, exist_anti_ok


def _normalize_default_reverse(raw, mx):
    """default_normalize_score(max=100, reverse=True); mx precomputed over
    the kept set (one lane of the step's batched reduction)."""
    return jnp.where(mx > 0, MAX_NODE_SCORE - MAX_NODE_SCORE * raw // mx,
                     jnp.int64(MAX_NODE_SCORE))


def _resource_eval(f: BatchFeatures, fit_strategy: int,
                   alloc_r, alloc_pods, req_r, nonzero, pod_count,
                   nom_r=None, nom_p=None):
    """Fit filter (fit.go:710) + LeastAllocated/MostAllocated score +
    integer-quantized BalancedAllocation for any leading shape (all nodes
    pre-scan; a single updated row inside the scan — these values only change
    at the row a pod landed on, so the scan carries them instead of
    recomputing [NP, R] work per step).

    `nom_r`/`nom_p` (the nominated-pod lane): pass-1 of the two-pass filter
    (runtime/framework.go:1300-1317) counts nominated pods' requests/count
    against the FILTER only — scores stay pass-2 (real pods), exactly as the
    host computes them."""
    eff_count = pod_count if nom_p is None else pod_count + nom_p
    pods_ok = (eff_count + 1).astype(jnp.int64) <= alloc_pods
    avail = alloc_r - req_r if nom_r is None else alloc_r - req_r - nom_r
    viol = ((f.request > 0) & (f.request > avail)).any(axis=-1)
    fit_ok = (pods_ok & (~viol | (f.has_request == 0))) | (f.enable[4] == 0)
    used0 = nonzero[..., 0] + f.nz_request[0]
    used1 = nonzero[..., 1] + f.nz_request[1]
    fit_num = jnp.zeros_like(used0)
    fit_den = jnp.zeros_like(used0)
    for j in range(f.fit_slots.shape[0]):
        slot = f.fit_slots[j]
        w = f.fit_weights[j]
        alloc = jnp.take(alloc_r, slot, axis=-1)
        used = jnp.where(slot == 0, used0,
                         jnp.where(slot == 1, used1,
                                   jnp.take(req_r, slot, axis=-1) + f.request[slot]))
        if fit_strategy == 0:  # LeastAllocated
            rscore = jnp.where((alloc > 0) & (used <= alloc),
                               (alloc - used) * MAX_NODE_SCORE // jnp.maximum(alloc, 1), 0)
        else:  # MostAllocated
            rscore = jnp.where(alloc > 0,
                               jnp.minimum(used, alloc) * MAX_NODE_SCORE // jnp.maximum(alloc, 1), 0)
        fit_num = fit_num + jnp.where(alloc > 0, rscore * w, 0)
        fit_den = fit_den + jnp.where(alloc > 0, w, 0)
    fit_sc = jnp.where(fit_den > 0, fit_num // jnp.maximum(fit_den, 1), 0)
    SCALE = jnp.int64(1_000_000)
    a_cpu = alloc_r[..., 0]
    a_mem = alloc_r[..., 1]
    q_cpu = jnp.minimum(used0 * SCALE // jnp.maximum(a_cpu, 1), SCALE)
    q_mem = jnp.minimum(used1 * SCALE // jnp.maximum(a_mem, 1), SCALE)
    both = (a_cpu > 0) & (a_mem > 0)
    ba_val = jnp.where(both,
                       (MAX_NODE_SCORE * SCALE - 50 * jnp.abs(q_cpu - q_mem)) // SCALE,
                       jnp.int64(MAX_NODE_SCORE))
    ba = jnp.where(f.ba_skip == 1, 0, ba_val)
    return fit_ok, fit_sc, ba


@partial(jax.jit, static_argnames=("batch_pad", "fit_strategy", "vmax",
                                   "has_pns", "has_ipa_base", "anti_rowlocal",
                                   "has_na_pref", "port_selfblock", "has_aux",
                                   "has_nom"),
         donate_argnames=("carry_in",))
def schedule_batch(
    state: DeviceNodeState,
    f: BatchFeatures,
    batch_pad: int,
    fit_strategy: int,
    vmax: int,
    n_active: Optional[jnp.ndarray] = None,
    carry_in: Optional[ScanCarry] = None,
    has_pns: bool = True,
    has_ipa_base: bool = True,
    anti_rowlocal: bool = False,
    has_na_pref: bool = False,
    port_selfblock: bool = False,
    has_aux: bool = False,
    has_nom: bool = False,
) -> Tuple[jnp.ndarray, ScanCarry]:
    """Greedy-assign up to `batch_pad` identical pods (`n_active` of them
    real; padded steps are inert so the returned carry stays exact).

    Returns (results, carry) where results is the stacked [2, B] array of
    (chosen row or -1, start_index_after) — one array so the host fetches
    with a single transfer; slice results[:, :n_active]. Passing the returned
    ScanCarry back as `carry_in` chains the NEXT batch of identical pods
    without re-uploading features or node state (dispatch pipelining: the
    host commits batch N while the device computes batch N+1 — the TPU-era
    form of schedule_one.go:141's async binding-cycle overlap).

    `has_pns` / `has_ipa_base` / `anti_rowlocal` are host-known batch facts
    (any PreferNoSchedule taints staged; any nonzero preferred-affinity base
    score; every required anti-affinity term keyed to a singleton-per-node
    topology axis, i.e. kubernetes.io/hostname-like). They let the kernel
    drop dead score reductions and — when a placement can only affect its own
    landed row — take the lap-vectorized path."""
    NP = state.valid.shape[0]
    C1 = f.dns_axis.shape[0]
    C2 = f.sa_axis.shape[0]
    A1 = f.anti_axis.shape[0]
    A2 = f.aff_axis.shape[0]
    KD = f.ipa_axis.shape[0]
    idx = jnp.arange(NP, dtype=jnp.int32)
    num = jnp.maximum(f.num_nodes, 1)

    # Feasibility can change only at the landed row when no cross-window
    # topology filter is active — DNS skew and required-affinity counts
    # couple whole domains, but a required ANTI term on a singleton axis
    # (hostname) only ever blocks the landed row itself.
    incremental_feas = C1 == 0 and A2 == 0 and (A1 == 0 or anti_rowlocal)
    # The total score vector changes only at the landed row (no kept-set
    # normalization terms): it rides the carry instead of being recomputed.
    scores_carried = (C2 == 0 and KD == 0 and not has_pns
                      and not has_ipa_base and not has_na_pref)
    # No cross-window coupling at all: place a whole lap of pods per
    # iteration (the fast path for fit-only and hostname-anti-affinity pods).
    # Small batches (gang-sized placement sims) stay on the scan path — its
    # per-step body is ~6 fused ops vs the lap's [LAP_MAX, NP] window
    # tensors, and a 4-member gang gets no lap parallelism anyway (with
    # truncation inactive every window spans the whole rotation, L=1).
    static_scores = incremental_feas and scores_carried and batch_pad > 64

    taint_ok, pns_cnt, sel_ok, name_ok, unsched_ok, exist_anti_ok = _static_masks(state, f)

    # Static topology vid gathers [C, NP].
    dns_vid = state.topo[f.dns_axis] if C1 else jnp.zeros((0, NP), jnp.int32)
    sa_vid = state.topo[f.sa_axis] if C2 else jnp.zeros((0, NP), jnp.int32)
    anti_vid = state.topo[f.anti_axis] if A1 else jnp.zeros((0, NP), jnp.int32)
    aff_vid = state.topo[f.aff_axis] if A2 else jnp.zeros((0, NP), jnp.int32)
    ipa_vid = state.topo[f.ipa_axis] if KD else jnp.zeros((0, NP), jnp.int32)

    # DNS eligibility for count updates (node_eligible, filtering.go AddPod).
    if C1:
        dns_elig = (dns_vid > 0)
        dns_elig &= jnp.where(f.dns_honor_aff[:, None] == 1, sel_ok[None, :], True)
        dns_elig &= jnp.where(f.dns_honor_taints[:, None] == 1, taint_ok[None, :], True)
    else:
        dns_elig = jnp.zeros((0, NP), bool)
    # SA ignored nodes (scoring.go initPreScoreState).
    if C2:
        sa_ignored = ~(sa_vid > 0).all(axis=0) | ~sel_ok
    else:
        sa_ignored = jnp.zeros(NP, bool)
    # Bootstrap only applies on nodes carrying every requested topology key
    # (satisfyPodAffinity checks key presence before the no-matches-anywhere
    # case, filtering.go:398-426). Static per batch.
    if A2:
        aff_has_keys = ((f.aff_active[:, None] == 0) | (aff_vid > 0)).all(axis=0)
    else:
        aff_has_keys = jnp.ones(NP, bool)

    static_ok = (state.valid & name_ok & unsched_ok & taint_ok & sel_ok
                 & exist_anti_ok & f.extra_ok)

    w_tt, w_fit, w_pts, w_ipa, w_ba, w_na, w_il = (f.weights[i] for i in range(7))
    # ImageLocality has no normalization: a static additive score term that
    # rides every path (including carried totals — landings can't change it).
    il_term = w_il * f.il_score

    n_act = jnp.int32(batch_pad) if n_active is None else n_active.astype(jnp.int32)

    def feasibility_proj(fit_ok, dns_counts, mnum, acnt, fcnt, aff_total,
                         blocked, aux_cnt):
        """Per-node ok mask from the dynamic filters
        (findNodesThatPassFilters; PTS skew filtering.go:318-362, IPA
        required filtering.go:368-426, counted CSI attach room), reading
        the carried per-node projections — no gathers on the critical
        path."""
        ok = static_ok & fit_ok & (idx < num)
        if port_selfblock:
            ok &= ~blocked
        if has_aux:
            ok &= aux_cnt + f.aux_inc <= f.aux_room
        if C1:
            # All-int32 skew math (counts are pods-per-domain, far below 2^31;
            # int64 vector ops cost ~2x in the per-op-latency regime).
            min_match = jnp.where(f.dns_dom, dns_counts, _BIG).min(axis=1)  # [C1]
            min_match = jnp.where(f.dns_forced0 == 1, 0, min_match)
            skew_bad = (mnum + f.dns_self[:, None] - min_match[:, None]
                        ) > jnp.minimum(f.dns_max_skew, _BIG)[:, None]
            dns_reject = (f.dns_active[:, None] == 1) & (~(dns_vid > 0) | skew_bad)
            ok &= ~dns_reject.any(axis=0)
        if A1:
            ok &= ~((anti_vid > 0) & (acnt > 0)).any(axis=0)
        if A2:
            term_ok = (f.aff_active[:, None] == 0) | ((aff_vid > 0) & (fcnt > 0))
            bootstrap = (aff_total == 0) & (f.aff_own_all == 1) & aff_has_keys
            ok &= term_ok.all(axis=0) | bootstrap
        return ok

    def step(carry, _):
        (req_r, nonzero, pod_count, fit_ok, fit_sc, ba,
         dns_counts, sa_counts, anti_counts, aff_counts, ipa_delta, start,
         blocked, aux_cnt, okd, F, total,
         mnum, scnt, acnt, fcnt, dproj, aff_total, t, out) = carry
        active = t < n_act

        if not incremental_feas:
            okd = feasibility_proj(fit_ok, dns_counts, mnum, acnt, fcnt,
                                   aff_total, blocked, aux_cnt)
            F = jnp.cumsum(okd.astype(jnp.int32))          # inclusive, row order

        # ---- sampling truncation + rotation (schedule_one.go:779-892) -----
        # Gather-free formulation: rank[row] = #feasible rows at rotation
        # positions <= rot(row), from the row-order prefix-sum with wrap
        # adjustment (feasible count in [start..row] resp. wrapped).
        total_feas = F[-1]
        f_start = jnp.where(start > 0, F[jnp.maximum(start - 1, 0)], 0)
        rank = jnp.where(idx >= start, F - f_start, F + total_feas - f_start)
        kept = okd & (rank <= f.to_find)
        rot_of_row = (idx - start) % num                   # row -> rotation pos

        # ---- reductions: everything as stacked maxes (mins ride negated) --
        # lane 0: window-boundary rotation (evaluated).
        bound_lane = jnp.where(okd & (rank == f.to_find),
                               (num - 1 - rot_of_row).astype(jnp.int64), 0)
        if scores_carried:
            # total is already known: boundary + packed selection key
            # (max-score-then-min-rotation; scores non-negative) collapse
            # into ONE reduction round.
            key = total * NP + (jnp.int32(NP - 1) - rot_of_row)
            red = jnp.max(jnp.stack(
                [jnp.where(kept, key, -1), bound_lane]), axis=1)
            best_key = red[0]
            evaluated = (num - red[1]).astype(jnp.int32)
        else:
            lanes = [bound_lane]
            if has_pns:
                lanes.append(jnp.where(kept, pns_cnt, 0))              # mx_pns
            if C2:
                raw_sa = (scnt.astype(jnp.int64) * f.sa_wq[:, None] +
                          (f.sa_skew[:, None] - 1) * 1024).sum(axis=0)
                live = kept & ~sa_ignored
                lanes.append(jnp.where(live, raw_sa, 0))               # mx_sa
                lanes.append(jnp.where(live, -raw_sa, -_INF64))        # -mn_sa
            if KD or has_ipa_base:
                raw_ipa = f.ipa_base
                if KD:
                    raw_ipa = raw_ipa + dproj.sum(axis=0)
                lanes.append(jnp.where(kept, raw_ipa, -_INF64))        # mx_ipa
                lanes.append(jnp.where(kept, -raw_ipa, -_INF64))       # -mn_ipa
            if has_na_pref:
                lanes.append(jnp.where(kept, f.na_raw, 0))             # mx_na
            red = jnp.max(jnp.stack(lanes), axis=1)
            evaluated = (num - red[0]).astype(jnp.int32)
            li = 1
            # ---- score assembly (runtime/framework.go:1526-1582) ----------
            if has_pns:
                tt = _normalize_default_reverse(pns_cnt, red[li]); li += 1
            else:
                tt = jnp.int64(MAX_NODE_SCORE)
            if C2:
                mx, mn = red[li], -red[li + 1]; li += 2
                norm = jnp.where(
                    mx > 0,
                    MAX_NODE_SCORE * (mx + jnp.minimum(mn, mx) - raw_sa) // jnp.maximum(mx, 1),
                    jnp.int64(MAX_NODE_SCORE))
                pts = jnp.where(sa_ignored, 0, norm)
            else:
                pts = jnp.int64(0)
            if KD or has_ipa_base:
                mx_i, mn_i = red[li], -red[li + 1]; li += 2
                diff = mx_i - mn_i
                ipa = jnp.where(diff > 0,
                                MAX_NODE_SCORE * (raw_ipa - mn_i) // jnp.maximum(diff, 1), 0)
            else:
                ipa = jnp.int64(0)
            if has_na_pref:
                # default_normalize_score(max=100, reverse=False): raw*100//mx
                # over the kept set; all-zero raws stay zero.
                mx_na = red[li]; li += 1
                na = jnp.where(mx_na > 0,
                               MAX_NODE_SCORE * f.na_raw // jnp.maximum(mx_na, 1), 0)
            else:
                na = jnp.int64(0)
            total = (w_tt * tt + w_fit * fit_sc + w_ba * ba + w_pts * pts
                     + w_ipa * ipa + w_na * na + il_term)
            # second reduction round: packed selection over the fresh scores
            key = total * NP + (jnp.int32(NP - 1) - rot_of_row)
            best_key = jnp.max(jnp.where(kept, key, -1))
        any_kept = (best_key >= 0) & active
        chosen_rot = jnp.int32(NP - 1) - (best_key % NP).astype(jnp.int32)
        chosen = jnp.where(any_kept, (start + chosen_rot) % num, -1).astype(jnp.int32)

        # ---- carry updates (inert when this step is padding) --------------
        row = jnp.maximum(chosen, 0)
        apply = jnp.where(any_kept, 1, 0).astype(jnp.int64)
        req_r = req_r.at[row].add(f.request * apply)
        nonzero = nonzero.at[row].add(f.nz_request * apply)
        pod_count = pod_count.at[row].add(apply.astype(jnp.int32))
        # Re-evaluate ONLY the landed row's resource-derived values (when
        # nothing was applied the inputs are unchanged, so this is identity).
        r_ok, r_fit, r_ba = _resource_eval(
            f, fit_strategy, state.alloc_r[row], state.alloc_pods[row],
            req_r[row], nonzero[row], pod_count[row],
            nom_r=f.nom_req[row] if has_nom else None,
            nom_p=f.nom_pods[row] if has_nom else None)
        fit_ok = fit_ok.at[row].set(r_ok)
        fit_sc = fit_sc.at[row].set(r_fit)
        ba = ba.at[row].set(r_ba)
        # All scatter/gather index operands stay int32 (matching `row` and
        # the vid tables): with x64 enabled a bare arange defaults to int64,
        # and mixed s64/s32 index tuples miscompile under GSPMD on this
        # environment's XLA (compare(s64, s32) after spmd-partitioning —
        # ROADMAP open item, fixed by this uniform-dtype normalization).
        if C1:
            c1i = jnp.arange(C1, dtype=jnp.int32)
            upd = (f.dns_self * dns_elig[c1i, row].astype(jnp.int32)
                   * apply.astype(jnp.int32))
            dns_counts = dns_counts.at[c1i, dns_vid[:, row]].add(upd)
            mnum = mnum + upd[:, None] * (dns_vid == dns_vid[:, row][:, None])
        if C2:
            upd = (f.sa_self * jnp.where(sa_ignored[row], 0, 1) * apply.astype(jnp.int32))
            sa_counts = sa_counts.at[jnp.arange(C2, dtype=jnp.int32),
                                     sa_vid[:, row]].add(upd)
            scnt = scnt + upd[:, None] * (sa_vid == sa_vid[:, row][:, None])
        if A1:
            upd = f.anti_self * (anti_vid[:, row] > 0).astype(jnp.int32) * apply.astype(jnp.int32)
            anti_counts = anti_counts.at[jnp.arange(A1, dtype=jnp.int32),
                                         anti_vid[:, row]].add(upd)
            acnt = acnt + upd[:, None] * (anti_vid == anti_vid[:, row][:, None])
        if A2:
            upd = f.aff_self * (aff_vid[:, row] > 0).astype(jnp.int32) * apply.astype(jnp.int32)
            aff_counts = aff_counts.at[jnp.arange(A2, dtype=jnp.int32),
                                       aff_vid[:, row]].add(upd)
            fcnt = fcnt + upd[:, None] * (aff_vid == aff_vid[:, row][:, None])
            aff_total = aff_total + upd.sum()
        if KD:
            upd = f.ipa_wland * (ipa_vid[:, row] > 0) * apply
            ipa_delta = ipa_delta.at[jnp.arange(KD, dtype=jnp.int32),
                                     ipa_vid[:, row]].add(upd)
            dproj = dproj + upd[:, None] * (ipa_vid == ipa_vid[:, row][:, None])
        if port_selfblock:
            blocked = blocked.at[row].set(blocked[row] | any_kept)
        if has_aux:
            aux_cnt = aux_cnt.at[row].add(f.aux_inc * apply.astype(jnp.int32))
        if incremental_feas:
            # Feasibility flips only at the landed row: patch okd and shift
            # the prefix-sum tail by the delta (replaces the full cumsum).
            new_ok_row = static_ok[row] & r_ok & (row < num)
            if A1:
                new_ok_row &= ~((anti_vid[:, row] > 0) & (acnt[:, row] > 0)).any()
            if port_selfblock:
                new_ok_row &= ~blocked[row]
            if has_aux:
                new_ok_row &= aux_cnt[row] + f.aux_inc <= f.aux_room[row]
            delta = new_ok_row.astype(jnp.int32) - okd[row].astype(jnp.int32)
            okd = okd.at[row].set(new_ok_row)
            F = F + jnp.where(idx >= row, delta, 0)
        if scores_carried:
            total = total.at[row].set(
                w_tt * jnp.int64(MAX_NODE_SCORE) + w_fit * r_fit + w_ba * r_ba
                + il_term[row])
        start = jnp.where(active, (start + evaluated) % num, start).astype(jnp.int32)
        # Results accumulate in the CARRY via a one-hot masked write (the
        # int32 step counter `t` also rides the carry): lax.scan's own
        # ys-stacking would index its dynamic_update_slice with the internal
        # s64 loop counter (x64 mode), which this environment's XLA
        # miscompiles under GSPMD — compare(s64, s32) after
        # spmd-partitioning, the ROADMAP open item. The elementwise write
        # keeps the carry uniformly int32-indexed and is also exact under
        # vmap (the cells axis), where a batched-index update slice is not.
        out = jnp.where(jnp.arange(batch_pad, dtype=jnp.int32)[None, :] == t,
                        jnp.stack([chosen, start])[:, None], out)

        new_carry = (req_r, nonzero, pod_count, fit_ok, fit_sc, ba,
                     dns_counts, sa_counts, anti_counts, aff_counts,
                     ipa_delta, start, blocked, aux_cnt, okd, F, total,
                     mnum, scnt, acnt, fcnt, dproj, aff_total,
                     t + jnp.int32(1), out)
        return new_carry, None

    if carry_in is None:
        fit_ok0, fit_sc0, ba0 = _resource_eval(
            f, fit_strategy, state.alloc_r, state.alloc_pods,
            state.req_r, state.nonzero, state.pod_count,
            nom_r=f.nom_req if has_nom else None,
            nom_p=f.nom_pods if has_nom else None)
        ipa_delta0 = jnp.zeros((KD, vmax), jnp.int64)
        ext0 = ScanCarry(state.req_r, state.nonzero, state.pod_count,
                         fit_ok0, fit_sc0, ba0,
                         f.dns_counts, f.sa_counts, f.anti_counts,
                         f.aff_counts, ipa_delta0, f.start_index,
                         jnp.zeros(NP, bool), jnp.zeros(NP, jnp.int32))
    else:
        ext0 = carry_in
    if static_scores:
        return _lap_schedule(state, f, batch_pad, fit_strategy,
                             ext0, static_ok, n_act, idx, num,
                             w_tt, w_fit, w_ba, il_term, anti_vid,
                             port_selfblock, has_aux, has_nom)
    # Per-node projections of the count tables (one gather per table per
    # CALL, kept elementwise-fresh by the scan) + okd/F seeds. Index dtype
    # is uniformly int32 — see the scatter-dtype note in `step`.
    i32v = jnp.int32
    mnum0 = (jnp.take_along_axis(ext0.dns_counts, dns_vid.astype(i32v), axis=1)
             if C1 else jnp.zeros((0, NP), jnp.int32))
    scnt0 = (jnp.take_along_axis(ext0.sa_counts, sa_vid.astype(i32v), axis=1)
             if C2 else jnp.zeros((0, NP), jnp.int32))
    acnt0 = (jnp.take_along_axis(ext0.anti_counts, anti_vid.astype(i32v), axis=1)
             if A1 else jnp.zeros((0, NP), jnp.int32))
    fcnt0 = (jnp.take_along_axis(ext0.aff_counts, aff_vid.astype(i32v), axis=1)
             if A2 else jnp.zeros((0, NP), jnp.int32))
    if KD:
        d0 = jnp.take_along_axis(ext0.ipa_delta, ipa_vid.astype(i32v), axis=1)
        dproj0 = d0 * jnp.where(ipa_vid > 0, 1, 0)
    else:
        dproj0 = jnp.zeros((0, NP), jnp.int64)
    aff_total0 = (ext0.aff_counts * (f.aff_active[:, None] == 1)).sum()
    okd0 = feasibility_proj(ext0.fit_ok, ext0.dns_counts, mnum0, acnt0,
                            fcnt0, aff_total0, ext0.blocked, ext0.aux_cnt)
    F0 = jnp.cumsum(okd0.astype(jnp.int32))
    if scores_carried:
        total0 = (w_tt * jnp.int64(MAX_NODE_SCORE) + w_fit * ext0.fit_sc
                  + w_ba * ext0.ba + il_term)
    else:
        total0 = jnp.zeros(NP, jnp.int64)
    out0 = jnp.full((2, batch_pad), -1, jnp.int32)
    carry0 = tuple(ext0) + (okd0, F0, total0,
                            mnum0, scnt0, acnt0, fcnt0, dproj0, aff_total0,
                            jnp.int32(0), out0)
    final, _ = lax.scan(step, carry0, None, length=batch_pad)
    # chosen+starts stacked into ONE array: the host fetches results with a
    # single device→host transfer (each fetch pays a full RTT on tunneled
    # TPUs). The final ScanCarry rides back (device-resident) so the host can
    # chain the next batch (carry_in) and keep the mirror resident
    # (NodeStateMirror.adopt) instead of re-uploading — the device-side
    # analogue of the incremental snapshot.
    return final[-1], ScanCarry(*final[:14])


@partial(jax.jit, static_argnames=("fit_strategy", "has_nom"))
def patch_carry_rows(
    state: DeviceNodeState,
    f: BatchFeatures,
    carry: ScanCarry,
    idx: jnp.ndarray,        # [K] i32 rows to patch (pow2-padded, dups OK)
    req_rows: jnp.ndarray,   # [K, R] i64 post-event requested aggregates
    nz_rows: jnp.ndarray,    # [K, 2] i64
    cnt_rows: jnp.ndarray,   # [K] i32
    fit_strategy: int = 0,
    has_nom: bool = False,
) -> ScanCarry:
    """Event-delta patch of a live session carry: install the post-event
    per-node aggregates for the journal's dirty rows and re-evaluate ONLY
    those rows' resource-derived values — the carry-side analogue of the
    mirror's dirty-row scatter. Valid only for pod-local plans (no count
    tables to touch); taint/allocatable changes ride the separately patched
    `state`, whose rows this reads. Duplicate padded indices write identical
    values, so the pow2 index tier is exact."""
    ok, sc, ba = _resource_eval(
        f, fit_strategy, state.alloc_r[idx], state.alloc_pods[idx],
        req_rows, nz_rows, cnt_rows,
        nom_r=f.nom_req[idx] if has_nom else None,
        nom_p=f.nom_pods[idx] if has_nom else None)
    return carry._replace(
        req_r=carry.req_r.at[idx].set(req_rows),
        nonzero=carry.nonzero.at[idx].set(nz_rows),
        pod_count=carry.pod_count.at[idx].set(cnt_rows),
        fit_ok=carry.fit_ok.at[idx].set(ok),
        fit_sc=carry.fit_sc.at[idx].set(sc),
        ba=carry.ba.at[idx].set(ba))


# One jit per (carry sharding set, statics): a mesh session's carry shardings
# are stable for the session's lifetime, so this stays a handful of entries.
_CARRY_PATCH_PINNED_CACHE: dict = {}


def patch_carry_rows_pinned(
    state: DeviceNodeState,
    f: BatchFeatures,
    carry: ScanCarry,
    idx: jnp.ndarray,
    req_rows: jnp.ndarray,
    nz_rows: jnp.ndarray,
    cnt_rows: jnp.ndarray,
    fit_strategy: int = 0,
    has_nom: bool = False,
) -> ScanCarry:
    """patch_carry_rows with out_shardings pinned to the live carry's OWN
    committed shardings, and the stale carry DONATED into the patch (its
    buffers are dead the moment the call returns — every caller rebinds
    its reference to the result, so the patched carry reuses the old
    carry's device memory instead of allocating a sharded copy per patch
    wave). A mesh session's chained-carry kernel trace keys on the carry's
    placement; the patch must hand back the identical placement or the
    next dispatch retraces — the exact failure mode that kept mesh
    sessions on the full-rebuild path (ROADMAP: delta resume under a
    sharded mesh)."""
    out = ScanCarry(*[x.sharding for x in carry])
    key = (out, fit_strategy, has_nom)
    fn = _CARRY_PATCH_PINNED_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            partial(patch_carry_rows.__wrapped__,
                    fit_strategy=fit_strategy, has_nom=has_nom),
            out_shardings=out, donate_argnums=(2,))
        _CARRY_PATCH_PINNED_CACHE[key] = fn
    return fn(state, f, carry, idx, req_rows, nz_rows, cnt_rows)


@partial(jax.jit, static_argnames=("batch_pad", "fit_strategy", "vmax",
                                   "has_pns", "has_na_pref",
                                   "port_selfblock", "has_aux"))
def schedule_placements(
    state: DeviceNodeState,
    f: BatchFeatures,
    batch_pad: int,
    fit_strategy: int,
    vmax: int,
    masks: jnp.ndarray,          # [P, NP] bool candidate-placement row masks
    n_active: Optional[jnp.ndarray] = None,
    has_pns: bool = True,
    has_na_pref: bool = False,
    port_selfblock: bool = False,
    has_aux: bool = False,
    spread_overrides: Optional[Tuple] = None,
) -> jnp.ndarray:
    """Evaluate a pod group against P candidate placements IN PARALLEL — the
    device form of podGroupSchedulingPlacementAlgorithm's per-placement
    simulation loop (schedule_one_podgroup.go:971): each lane restricts the
    node universe to one placement's rows and runs the full greedy member
    assignment from the CURRENT cluster state (fresh carry — simulations
    never contaminate the resident state). Returns the stacked [P, 2, B]
    results; the host gates lanes with PlacementFeasible and scores the
    survivors (findBestPodGroupPlacement :1173).

    Placement simulations evaluate their whole candidate (no adaptive
    truncation) from rotation origin 0 — the host oracle uses the identical
    spec (core/scheduler.py _evaluate_placement), making host and device
    placement evaluation bit-identical for eligible plans (no
    inter-pod-affinity / image terms; see models/tpu_scheduler.py
    _placement_plan_restriction_invariant).

    `spread_overrides` lifts the no-topology-spread restriction: the host
    oracle computes its PreFilter spread state over the placement-RESTRICTED
    node list (cache.py assume_placement), so each lane gets its own
    restricted count tables — a (dns_counts [P,C1,V], dns_dom [P,C1,V],
    dns_forced0 [P,C1], sa_counts [P,C2,V], sa_wq [P,C2]) tuple built by
    models/tpu_scheduler.py _placement_spread_overrides."""

    def run_lane(f2):
        results, _carry = schedule_batch.__wrapped__(
            state, f2, batch_pad, fit_strategy, vmax,
            n_active=n_active, carry_in=None,
            has_pns=has_pns, has_ipa_base=False, anti_rowlocal=False,
            has_na_pref=has_na_pref, port_selfblock=port_selfblock,
            has_aux=has_aux)
        return results

    if spread_overrides is None:
        def one(mask):
            return run_lane(f._replace(
                extra_ok=f.extra_ok & mask,
                start_index=jnp.int32(0),
                to_find=f.num_nodes,
            ))

        return jax.vmap(one)(masks)

    def one_sp(mask, dns_counts, dns_dom, dns_forced0, sa_counts, sa_wq):
        return run_lane(f._replace(
            extra_ok=f.extra_ok & mask,
            start_index=jnp.int32(0),
            to_find=f.num_nodes,
            dns_counts=dns_counts, dns_dom=dns_dom, dns_forced0=dns_forced0,
            sa_counts=sa_counts, sa_wq=sa_wq,
        ))

    return jax.vmap(one_sp)(masks, *spread_overrides)


@partial(jax.jit, static_argnames=("k",))
def dry_run_preemption(
    state: DeviceNodeState,
    f: BatchFeatures,
    vic_req: jnp.ndarray,    # [NP, K, R] i64 victim requests, MoreImportantPod order
    vic_valid: jnp.ndarray,  # [NP, K] bool
    k: int,
) -> jnp.ndarray:
    """Batched DryRunPreemption (preemption.go:425 SelectVictimsOnNode for
    every candidate node in ONE dense what-if — SURVEY §7.7's 'natural second
    TPU kernel').

    Per node: remove all lower-priority pods (columns of vic_req), check the
    preemptor fits; then reprieve victims most-important-first (the host's
    MoreImportantPod order, pre-sorted into the K axis), keeping each victim
    whose re-addition still leaves the preemptor feasible. The preemptor's
    non-resource filters are static per node (the device gate excludes
    topology-coupled preemptors and clusters with anti-affinity pods), so
    the per-victim feasibility check reduces to the fit arithmetic of
    _resource_eval — bit-identical to the host oracle's filter verdicts.

    Returns one stacked bool array [NP, 1+K] (a single device→host fetch):
    column 0 = feasible (non-empty minimal victim set), columns 1..K = the
    victim mask; scores/PDBs/selection stay host-side
    (pickOneNodeForPreemption, preemption.go:286)."""
    NP = state.valid.shape[0]
    idx = jnp.arange(NP, dtype=jnp.int32)
    num = jnp.maximum(f.num_nodes, 1)
    taint_ok, _pns, sel_ok, name_ok, unsched_ok, exist_anti_ok = _static_masks(state, f)
    static_ok = (state.valid & name_ok & unsched_ok & taint_ok & sel_ok
                 & exist_anti_ok & f.extra_ok & (idx < num))

    n_pot = vic_valid.sum(axis=1).astype(jnp.int32)          # [NP]
    sum_vic = (vic_req * vic_valid[:, :, None]).sum(axis=1)  # [NP, R]
    base_req = state.req_r - sum_vic
    cnt0 = state.pod_count - n_pot

    def fit(req_r, pod_cnt):
        # The scheduling kernel's exact fit filter; scores are dead code
        # under jit (XLA eliminates them). No nominated lane: the host dry
        # run ignores nominations too (run_filter_plugins, not two-pass).
        ok, _sc, _ba = _resource_eval(
            f, 0, state.alloc_r, state.alloc_pods, req_r,
            jnp.zeros_like(req_r[..., :2]), pod_cnt)
        return ok

    feasible0 = static_ok & fit(base_req, cnt0) & (n_pot > 0)

    def step(carry, i):
        kept_req, kept_cnt = carry
        vr = vic_req[:, i]                                   # [NP, R]
        valid = vic_valid[:, i]                              # [NP]
        keep = valid & feasible0 & fit(base_req + kept_req + vr,
                                       cnt0 + kept_cnt + 1)
        kept_req = kept_req + vr * keep[:, None]
        kept_cnt = kept_cnt + keep.astype(jnp.int32)
        return (kept_req, kept_cnt), valid & feasible0 & ~keep

    (_kr, _kc), victims_t = lax.scan(
        step, (jnp.zeros_like(sum_vic), jnp.zeros(NP, jnp.int32)),
        jnp.arange(k, dtype=jnp.int32))
    victim_mask = jnp.moveaxis(victims_t, 0, 1)              # [NP, K]
    feasible = feasible0 & victim_mask.any(axis=1)
    return jnp.concatenate([feasible[:, None], victim_mask], axis=1)


# Max pods placed per lap iteration (bounds the segment tensors; L_full =
# total_feasible // to_find never exceeds ~20 for the reference's adaptive
# percentage formula, schedule_one.go:866, but custom percentageOfNodesToScore
# can push it higher — excess windows spill to later laps).
LAP_MAX = 32


def _lap_schedule(state, f, batch_pad, fit_strategy, ext0,
                  static_ok, n_act, idx, num, w_tt, w_fit, w_ba, il_term,
                  anti_vid, port_selfblock, has_aux, has_nom=False):
    """Lap-vectorized greedy assignment for the static-score case.

    Key fact: with adaptive sampling live (schedule_one.go:866-892), pod i
    examines the window holding the first `to_find` feasible nodes after its
    start index, and pod i+1's window begins where pod i's ended. Windows of
    consecutive pods are therefore DISJOINT until the rotation laps the
    cluster — and with no cross-window topology coupling, a placement changes
    scores and feasibility only at its own landed row, which later windows in
    the same lap never see. So all `L = total_feasible // to_find` pods of
    one lap are independent: one segmented argmax places them all. The
    sequential scan (1 pod/step) collapses to ~B·to_find/N steps — at 5k
    nodes the 1024-pod batch runs in ~100 lap iterations of which each does
    ONE pass over the node tensors. This is the TPU-shaped replacement for
    the goroutine pool: maximal vector work per sequential dependency, not
    per worker.

    Required anti-affinity terms on singleton axes (hostname) ride this path
    too: a landing only blocks its own row, which later windows never
    examine; `anti_counts` is refreshed per lap from the placements."""
    NP = state.valid.shape[0]
    A1 = anti_vid.shape[0]
    tf = jnp.maximum(f.to_find, 1)
    B = batch_pad
    SEG = LAP_MAX + 1  # window segments + 1 dump lane

    lanes = jnp.arange(LAP_MAX, dtype=jnp.int32)             # [LAP_MAX]

    def cond(c):
        return c[0] < n_act

    def body(c):
        (done, req_r, nonzero, pod_count, anti_counts, blocked, aux_cnt,
         start, out) = c
        # Dense per-lap recompute (no scatters/gathers — TPU scatters
        # serialize per index, so one-hot masked vector ops win):
        fit_ok, fit_sc, ba = _resource_eval(
            f, fit_strategy, state.alloc_r, state.alloc_pods,
            req_r, nonzero, pod_count,
            nom_r=f.nom_req if has_nom else None,
            nom_p=f.nom_pods if has_nom else None)
        okd = static_ok & fit_ok & (idx < num)
        if port_selfblock:
            okd &= ~blocked
        if has_aux:
            okd &= aux_cnt + f.aux_inc <= f.aux_room
        if A1:
            acnt = jnp.take_along_axis(anti_counts, anti_vid.astype(jnp.int32), axis=1)
            okd &= ~((anti_vid > 0) & (acnt > 0)).any(axis=0)
        F = jnp.cumsum(okd.astype(jnp.int32))
        total = (w_tt * jnp.int64(MAX_NODE_SCORE) + w_fit * fit_sc
                 + w_ba * ba + il_term)
        total_feas = F[-1]
        f_start = jnp.where(start > 0, F[jnp.maximum(start - 1, 0)], 0)
        rank = jnp.where(idx >= start, F - f_start, F + total_feas - f_start)
        rot = (idx - start) % num
        l_full = total_feas // tf
        L = jnp.clip(jnp.minimum(l_full, n_act - done), 1, LAP_MAX)
        # window of each feasible row; singleton window 0 when sampling
        # truncation is inactive (total_feas <= to_find ⇒ all rows rank<=tf)
        w = jnp.minimum((rank - 1) // tf, LAP_MAX)
        seg = jnp.where(okd & (w < L), w, LAP_MAX)           # [NP]
        in_w = seg[None, :] == lanes[:, None]                # [LAP_MAX, NP]
        # max-score-then-min-rotation packed argmax per window
        key = total * NP + (jnp.int32(NP - 1) - rot)
        key_w = jnp.max(jnp.where(in_w, key[None, :], -1), axis=1)
        has_w = (lanes < L) & (key_w >= 0)
        rot_w = jnp.int32(NP - 1) - (key_w % NP).astype(jnp.int32)
        row_w = jnp.where(has_w, (start + rot_w) % num, -1).astype(jnp.int32)
        # window end boundaries: the row with feasible rank (w+1)*to_find is
        # the last one examined for window w (numFeasibleNodesToFind cut);
        # empty ⇒ the window ran to the end of the rotation (evaluated=num).
        is_b = okd & (rank % tf == 0)
        seg_b = jnp.where(is_b, jnp.minimum(rank // tf - 1, LAP_MAX), LAP_MAX)
        in_b = seg_b[None, :] == lanes[:, None]
        ev_w = jnp.min(jnp.where(in_b, rot[None, :] + 1, num), axis=1)  # [LAP_MAX]
        # per-pod cumulative start: start_after lane w = boundary of its window
        start_w = (start + ev_w) % num                        # [LAP_MAX]
        # ---- apply the L placements (windows are disjoint ⇒ each row gets
        # at most one pod: a one-hot sum over lanes is an exact update) -----
        chosen_1h = (idx[None, :] == row_w[:, None]) & has_w[:, None]
        cnt = chosen_1h.any(axis=0)                           # [NP] bool
        c64 = cnt.astype(jnp.int64)
        req_r = req_r + f.request[None, :] * c64[:, None]
        nonzero = nonzero + f.nz_request[None, :] * c64[:, None]
        pod_count = pod_count + cnt.astype(jnp.int32)
        if port_selfblock:
            blocked |= cnt
        if has_aux:
            aux_cnt = aux_cnt + f.aux_inc * cnt.astype(jnp.int32)
        if A1:
            # hostname-anti landings: +self at each landed row's own value
            # (duplicate vids cannot occur — the axis is singleton-per-node).
            rr = jnp.maximum(row_w, 0)
            upd = (f.anti_self[:, None] * (anti_vid[:, rr] > 0).astype(jnp.int32)
                   * has_w[None, :].astype(jnp.int32))        # [A1, LAP_MAX]
            anti_counts = anti_counts.at[
                jnp.arange(A1, dtype=jnp.int32)[:, None],
                anti_vid[:, rr]].add(upd)
        # ---- emit results (positions >= n_act are sliced off by the host) -
        chosen_w = jnp.where(has_w, row_w, -1)
        block = jnp.stack([chosen_w, start_w.astype(jnp.int32)])  # [2, LAP_MAX]
        out = lax.dynamic_update_slice(out, block, (jnp.int32(0), done))
        start = start_w[jnp.maximum(L - 1, 0)]
        return (done + L, req_r, nonzero, pod_count, anti_counts, blocked,
                aux_cnt, start, out)

    out0 = jnp.full((2, B + LAP_MAX), -1, jnp.int32)
    c0 = (jnp.int32(0), ext0.req_r, ext0.nonzero, ext0.pod_count,
          ext0.anti_counts, ext0.blocked, ext0.aux_cnt, ext0.start, out0)
    (done, req_r, nonzero, pod_count, anti_counts, blocked, aux_cnt, start,
     out) = lax.while_loop(cond, body, c0)
    # The carry's fit_ok seeds the next chained batch of the SAME plan, so
    # it keeps the nominated lane (a changed nomination set never chains —
    # Nominator.version invalidates the session).
    fit_ok, fit_sc, ba = _resource_eval(
        f, fit_strategy, state.alloc_r, state.alloc_pods,
        req_r, nonzero, pod_count,
        nom_r=f.nom_req if has_nom else None,
        nom_p=f.nom_pods if has_nom else None)
    carry = ScanCarry(req_r, nonzero, pod_count, fit_ok, fit_sc, ba,
                      ext0.dns_counts, ext0.sa_counts, anti_counts,
                      ext0.aff_counts, ext0.ipa_delta, start, blocked,
                      aux_cnt)
    return out[:, :B], carry
