"""What-if rescore of BOUND pods — the descheduler's scoring core.

The scheduler answers "where should this pending pod land?"; the
descheduler asks the inverse: "for a pod already bound, does a strictly
better row exist?". Both questions share one arithmetic — ops/kernel.py's
`_resource_eval` fit filter + LeastAllocated + integer-quantized
BalancedAllocation — and this module evaluates it as ONE dense
candidate-pods × nodes matrix, with each candidate's own usage
subtracted from its source row first (the move vacates it).

Two implementations, bit-identical by construction:

- ``whatif_scores(batch)`` — a numpy host walker with zero device
  requirements (the controller-process default: no jax import, no
  compile wait in a 250ms reconcile tick);
- ``whatif_scores(batch, device=True)`` — a jax.jit mirror of the same
  int64 formulas, shape-padded so a steady descheduler tick reuses one
  compiled executable (the SNIPPETS.md donation pattern keeps these
  buffers resident beside the scheduler's own batch tensors).

Bit-parity is load-bearing, not cosmetic: a standby descheduler
re-deriving a dead ACTIVE's plan — possibly on different hardware —
must mint the SAME ``uid@node`` move set, or the exactly-once eviction
ledger stops absorbing the replay. tests/test_descheduler.py fuzzes the
two paths against each other on hint-eligible shapes.

Every integer division below runs on non-negative numerators (guards
mirror `_resource_eval`'s `where` clauses), where numpy's and XLA's
int64 ``//`` agree exactly.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..api.types import find_matching_untolerated_taint
from ..core.node_info import NodeInfo

MAX_NODE_SCORE = 100
BA_SCALE = 1_000_000

# Resource slot layout — the NodeStateMirror row convention
# (ops/device_state.py): [cpu_milli, memory, ephemeral_storage, *scalars].
SLOT_CPU = 0
SLOT_MEMORY = 1
SLOT_EPHEMERAL = 2
BASE_RESOURCES = 3


class WhatIfBatch(NamedTuple):
    """One dense candidates × nodes what-if problem (all int64/bool numpy).

    Node rows use the mirror's encoding; ``mask[p, n]`` folds the
    host-evaluated static gates (row validity, taint toleration) so both
    score paths consume one shared feasibility plane and parity reduces
    to the fit/BA arithmetic alone.
    """

    alloc_r: np.ndarray      # [N, R] allocatable per slot
    alloc_pods: np.ndarray   # [N]    allocatable pod count
    req_r: np.ndarray        # [N, R] requested per slot (bound pods)
    nonzero: np.ndarray      # [N, 2] non-zero-default cpu/mem aggregate
    pod_count: np.ndarray    # [N]    bound pods per node
    request: np.ndarray      # [P, R] candidate request vector
    nz_request: np.ndarray   # [P, 2] candidate non-zero cpu/mem
    src: np.ndarray          # [P]    candidate's current row index
    mask: np.ndarray         # [P, N] landing eligibility

    @property
    def n_pods(self) -> int:
        return int(self.request.shape[0])

    @property
    def n_nodes(self) -> int:
        return int(self.alloc_r.shape[0])


def _resource_vec(r, slots: Dict[str, int], out: np.ndarray) -> None:
    out[SLOT_CPU] = r.milli_cpu
    out[SLOT_MEMORY] = r.memory
    out[SLOT_EPHEMERAL] = r.ephemeral_storage
    for name, amount in r.scalar_resources.items():
        out[slots[name]] = amount


def encode_batch(node_infos: Sequence[NodeInfo],
                 candidates: Sequence[object]) -> WhatIfBatch:
    """Encode a snapshot + candidate pod list into one WhatIfBatch.

    Rows follow NodeStateMirror's slot layout with the scalar-slot map
    rebuilt per batch (a descheduler tick is a fresh snapshot; there is
    no cross-tick device residency to preserve on the host path). The
    taint gate is evaluated here once and folded into ``mask`` — shared
    verbatim by both score paths.
    """
    slots: Dict[str, int] = {}
    for ni in node_infos:
        for name in ni.allocatable.scalar_resources:
            slots.setdefault(name, BASE_RESOURCES + len(slots))
    for pod in candidates:
        for name in pod.resource_request().scalar_resources:
            slots.setdefault(name, BASE_RESOURCES + len(slots))
    R = BASE_RESOURCES + len(slots)
    N, P = len(node_infos), len(candidates)
    alloc_r = np.zeros((N, R), np.int64)
    alloc_pods = np.zeros(N, np.int64)
    req_r = np.zeros((N, R), np.int64)
    nonzero = np.zeros((N, 2), np.int64)
    pod_count = np.zeros(N, np.int64)
    by_name = {ni.name: i for i, ni in enumerate(node_infos)}
    for i, ni in enumerate(node_infos):
        _resource_vec(ni.allocatable, slots, alloc_r[i])
        alloc_pods[i] = ni.allocatable.allowed_pod_number
        _resource_vec(ni.requested, slots, req_r[i])
        nonzero[i, 0] = ni.non_zero_requested.milli_cpu
        nonzero[i, 1] = ni.non_zero_requested.memory
        pod_count[i] = len(ni.pods)
    request = np.zeros((P, R), np.int64)
    nz_request = np.zeros((P, 2), np.int64)
    src = np.zeros(P, np.int64)
    mask = np.zeros((P, N), bool)
    for p, pod in enumerate(candidates):
        req = pod.resource_request()
        _resource_vec(req, slots, request[p])
        nz_request[p, 0] = req.milli_cpu or NodeInfo.DEFAULT_MILLI_CPU
        nz_request[p, 1] = req.memory or NodeInfo.DEFAULT_MEMORY
        src[p] = by_name.get(pod.node_name, 0)
        for i, ni in enumerate(node_infos):
            node = ni.node
            if node is None or getattr(node, "unschedulable", False):
                continue
            if find_matching_untolerated_taint(
                    node.taints, pod.tolerations) is not None:
                continue
            mask[p, i] = True
    return WhatIfBatch(alloc_r, alloc_pods, req_r, nonzero, pod_count,
                       request, nz_request, src, mask)


def _score_host(b: WhatIfBatch) -> Tuple[np.ndarray, np.ndarray]:
    """`_resource_eval` (fit filter + LeastAllocated + BalancedAllocation,
    default profile weights) on the vacated state, pure numpy int64."""
    P, N = b.n_pods, b.n_nodes
    vacate = np.zeros((P, N), np.int64)
    vacate[np.arange(P), b.src] = 1
    req_r = b.req_r[None, :, :] - vacate[:, :, None] * b.request[:, None, :]
    nonzero = (b.nonzero[None, :, :]
               - vacate[:, :, None] * b.nz_request[:, None, :])
    pod_count = b.pod_count[None, :] - vacate
    alloc_r = np.broadcast_to(b.alloc_r[None, :, :], req_r.shape)
    # fit filter (fit.go:710)
    pods_ok = pod_count + 1 <= b.alloc_pods[None, :]
    avail = alloc_r - req_r
    req = b.request[:, None, :]
    viol = ((req > 0) & (req > avail)).any(axis=-1)
    fit_ok = pods_ok & ~viol & b.mask
    used0 = nonzero[..., 0] + b.nz_request[:, 0, None]
    used1 = nonzero[..., 1] + b.nz_request[:, 1, None]
    # LeastAllocated over (cpu, memory), weight 1 each (default profile)
    fit_num = np.zeros_like(used0)
    fit_den = np.zeros_like(used0)
    for slot, used in ((SLOT_CPU, used0), (SLOT_MEMORY, used1)):
        alloc = alloc_r[..., slot]
        rscore = np.where(
            (alloc > 0) & (used <= alloc),
            (alloc - used) * MAX_NODE_SCORE // np.maximum(alloc, 1), 0)
        fit_num = fit_num + np.where(alloc > 0, rscore, 0)
        fit_den = fit_den + np.where(alloc > 0, 1, 0)
    fit_sc = np.where(fit_den > 0, fit_num // np.maximum(fit_den, 1), 0)
    # integer-quantized BalancedAllocation
    a_cpu = alloc_r[..., SLOT_CPU]
    a_mem = alloc_r[..., SLOT_MEMORY]
    q_cpu = np.minimum(used0 * BA_SCALE // np.maximum(a_cpu, 1), BA_SCALE)
    q_mem = np.minimum(used1 * BA_SCALE // np.maximum(a_mem, 1), BA_SCALE)
    both = (a_cpu > 0) & (a_mem > 0)
    ba = np.where(both,
                  (MAX_NODE_SCORE * BA_SCALE
                   - 50 * np.abs(q_cpu - q_mem)) // BA_SCALE,
                  np.int64(MAX_NODE_SCORE))
    return fit_ok, (fit_sc + ba).astype(np.int64)


# -- device mirror ----------------------------------------------------------

_jit_cache: dict = {}


def _pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


def _device_fn():
    """Lazily build (and cache) the jitted mirror. jax is imported only
    here — a host-walker descheduler process never pays the import."""
    fn = _jit_cache.get("fn")
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)

    def score(alloc_r, alloc_pods, req_r0, nonzero0, pod_count0,
              request, nz_request, src, mask):
        P = request.shape[0]
        vacate = jnp.zeros(mask.shape, jnp.int64).at[
            jnp.arange(P, dtype=jnp.int32), src].set(1)
        req_r = req_r0[None, :, :] - vacate[:, :, None] * request[:, None, :]
        nonzero = (nonzero0[None, :, :]
                   - vacate[:, :, None] * nz_request[:, None, :])
        pod_count = pod_count0[None, :] - vacate
        alloc = alloc_r[None, :, :]
        pods_ok = pod_count + 1 <= alloc_pods[None, :]
        req = request[:, None, :]
        viol = ((req > 0) & (req > alloc - req_r)).any(axis=-1)
        fit_ok = pods_ok & ~viol & mask
        used0 = nonzero[..., 0] + nz_request[:, 0, None]
        used1 = nonzero[..., 1] + nz_request[:, 1, None]
        fit_num = jnp.zeros_like(used0)
        fit_den = jnp.zeros_like(used0)
        for slot, used in ((SLOT_CPU, used0), (SLOT_MEMORY, used1)):
            a = alloc[..., slot]
            rscore = jnp.where(
                (a > 0) & (used <= a),
                (a - used) * MAX_NODE_SCORE // jnp.maximum(a, 1), 0)
            fit_num = fit_num + jnp.where(a > 0, rscore, 0)
            fit_den = fit_den + jnp.where(a > 0, 1, 0)
        fit_sc = jnp.where(fit_den > 0,
                           fit_num // jnp.maximum(fit_den, 1), 0)
        a_cpu = alloc[..., SLOT_CPU]
        a_mem = alloc[..., SLOT_MEMORY]
        q_cpu = jnp.minimum(used0 * BA_SCALE // jnp.maximum(a_cpu, 1),
                            BA_SCALE)
        q_mem = jnp.minimum(used1 * BA_SCALE // jnp.maximum(a_mem, 1),
                            BA_SCALE)
        both = (a_cpu > 0) & (a_mem > 0)
        ba = jnp.where(both,
                       (MAX_NODE_SCORE * BA_SCALE
                        - 50 * jnp.abs(q_cpu - q_mem)) // BA_SCALE,
                       jnp.int64(MAX_NODE_SCORE))
        return fit_ok, (fit_sc + ba).astype(jnp.int64)

    fn = _jit_cache["fn"] = jax.jit(score)
    return fn


def _score_device(b: WhatIfBatch) -> Tuple[np.ndarray, np.ndarray]:
    """Pad to power-of-two tiers (one executable per steady tick) and
    dispatch the jitted mirror; slice the pads back off on the host."""
    fn = _device_fn()
    P, N = b.n_pods, b.n_nodes
    PP, NP_ = _pow2(max(P, 1)), _pow2(max(N, 1))

    def pad(a, shape):
        out = np.zeros(shape, a.dtype)
        out[tuple(slice(0, s) for s in a.shape)] = a
        return out

    R = b.alloc_r.shape[1]
    fit_ok, score = fn(
        pad(b.alloc_r, (NP_, R)), pad(b.alloc_pods, (NP_,)),
        pad(b.req_r, (NP_, R)), pad(b.nonzero, (NP_, 2)),
        pad(b.pod_count, (NP_,)), pad(b.request, (PP, R)),
        pad(b.nz_request, (PP, 2)), pad(b.src, (PP,)),
        pad(b.mask, (PP, NP_)))
    return (np.asarray(fit_ok)[:P, :N], np.asarray(score)[:P, :N])


def whatif_scores(batch: WhatIfBatch,
                  device: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    """Score the batch: returns ``(fit_ok [P, N] bool, score [P, N] i64)``
    with ``score = fit_sc + ba`` (0..200). ``device=True`` dispatches the
    jitted mirror (bit-identical); default walks on the host."""
    if batch.n_pods == 0 or batch.n_nodes == 0:
        shape = (batch.n_pods, batch.n_nodes)
        return np.zeros(shape, bool), np.zeros(shape, np.int64)
    if device:
        return _score_device(batch)
    return _score_host(batch)


class Move(NamedTuple):
    pod_index: int        # index into the candidate list
    src: int              # current row
    dst: int              # best landing row
    improvement: int      # score(dst) - score(src); >= 1 when src unfit


def best_moves(batch: WhatIfBatch, fit_ok: np.ndarray,
               score: np.ndarray) -> List[Optional[Move]]:
    """Pick each candidate's best strictly-different landing row.

    Deterministic: ties break to the LOWEST row index (numpy argmax
    first-occurrence), so two managers scoring the same snapshot plan
    the same move set — the exactly-once replay contract. A candidate
    whose source row no longer fits it (drift shrank the node under a
    bound pod) scores its current seat as ``current - 1``, so a
    merely-equal landing row still registers a positive improvement.
    """
    out: List[Optional[Move]] = []
    P = batch.n_pods
    for p in range(P):
        row_ok = fit_ok[p].copy()
        s = int(batch.src[p])
        cur_fit = bool(row_ok[s])
        cur = int(score[p, s]) if cur_fit else int(score[p, s]) - 1
        row_ok[s] = False
        if not row_ok.any():
            out.append(None)
            continue
        masked = np.where(row_ok, score[p], np.int64(-1))
        dst = int(masked.argmax())
        out.append(Move(p, s, dst, int(masked[dst]) - cur))
    return out
