"""Device (TPU) execution layer.

The host scheduling core (kubernetes_tpu/core) stays authoritative; this
package mirrors the node snapshot into fixed-capacity SoA tensors
(`device_state`), extracts per-batch pod features (`features`), and evaluates
the whole Filter→Score hot path as one jit-compiled pods×nodes kernel with a
greedy sequential assignment scan (`kernel`) — the TPU-native replacement for
the reference's 16-goroutine Parallelizer fan-out
(pkg/scheduler/framework/parallelize/parallelism.go:28) per SURVEY.md §2.4/§7.
"""

from .codebook import Codebook

__all__ = ["Codebook"]
