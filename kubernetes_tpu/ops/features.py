"""Per-batch pod feature extraction for the device kernel.

A *batch* is a row-block of consecutive same-signature pending pods (identical
scheduling-relevant spec — the generalization of the reference's
OpportunisticBatching pod signatures, runtime/batch.go:33, to true kernel
batching per SURVEY.md §2.4). Because every pod in the batch is identical, the
expensive O(all-pods) PreFilter aggregations (PodTopologySpread
filtering.go:241 calPreFilterState, InterPodAffinity filtering.go:287) are
computed ONCE here on the host, and the *sequential* inter-pod dependency —
each assignment shifting the counts the next pod sees — runs entirely on
device inside the kernel's lax.scan carry (ops/kernel.py).

Everything here mirrors the host-oracle plugin semantics exactly; equivalence
is enforced by tests/test_device_equivalence.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..api import resource as res
from ..api.types import (
    DO_NOT_SCHEDULE,
    HONOR,
    LABEL_HOSTNAME,
    NO_SCHEDULE,
    SCHEDULE_ANYWAY,
    Pod,
    Taint,
    find_matching_untolerated_taint,
)
from ..core.node_info import NodeInfo, PodInfo
from ..core.scheduler import num_feasible_nodes_to_find
from ..plugins.basic import NodeUnschedulable
from ..plugins.helpers import compile_terms
from ..plugins.podtopologyspread import (
    _compile_constraints,
    _count_pods_matching,
    PodTopologySpread,
)
from .codebook import EFFECT_IDS, EFFECT_PREFER_NO_SCHEDULE, OP_EQUAL, OP_EXISTS
from .device_state import BASE_RESOURCES, NodeStateMirror

_UNSCHED_TAINT = Taint(key=NodeUnschedulable.TAINT_KEY, effect=NO_SCHEDULE)

DEFAULT_BA_RESOURCES = (res.CPU, res.MEMORY)


def _pow2(n: int, floor: int = 1) -> int:
    if n <= 0:
        return 0
    c = floor
    while c < n:
        c *= 2
    return c


class BatchFeatures(NamedTuple):
    """Dynamic (traced) inputs to the batch kernel. All count tables are
    [*, VMAX]; VMAX and every leading dimension are padded to power-of-two
    tiers so jit recompiles are bounded (SURVEY.md §7 'capacity tiers')."""

    # resources
    request: jnp.ndarray          # [R] i64
    nz_request: jnp.ndarray       # [2] i64 (cpu/mem with non-zero defaults)
    has_request: jnp.ndarray      # i64 scalar (0 => all-zero request)
    ba_skip: jnp.ndarray          # i64 scalar (BalancedAllocation PreScore skip)
    # tolerations (pad eff = -1 rows never tolerate)
    tol_key: jnp.ndarray          # [LT] i32
    tol_val: jnp.ndarray          # [LT] i32
    tol_eff: jnp.ndarray          # [LT] i32
    tol_op: jnp.ndarray           # [LT] i32
    # cheap filters
    node_name_id: jnp.ndarray     # i32 (0 = unset)
    tolerates_unsched: jnp.ndarray  # i32
    # Full node-selector + required-node-affinity verdict per node, evaluated
    # host-side with the oracle semantics (matchExpressions In/NotIn/Exists/
    # DoesNotExist/Gt/Lt AND matchFields metadata.name — node_affinity.go
    # Filter). Static per batch: node labels cannot change mid-session.
    sel_match: jnp.ndarray        # [NP] bool
    # Extra host-evaluated static filters folded into static_ok: NodePorts
    # conflicts vs existing pods (nodeports.go Fits) and NodeDeclaredFeatures
    # (fork plugin). Placement-dependent port self-conflicts ride the carry's
    # `blocked` vector instead (BatchPlan.port_selfblock).
    extra_ok: jnp.ndarray         # [NP] bool
    # Static additive / normalized score inputs.
    il_score: jnp.ndarray         # [NP] i64 ImageLocality score (0-100, no norm)
    na_raw: jnp.ndarray           # [NP] i64 preferred-node-affinity raw sum
    # PodTopologySpread DoNotSchedule
    dns_axis: jnp.ndarray         # [C1] i32 axis row in state.topo
    dns_active: jnp.ndarray       # [C1] i32 (0 = padding row, never rejects)
    dns_max_skew: jnp.ndarray     # [C1] i64
    dns_self: jnp.ndarray         # [C1] i32 selector matches the batch pod itself
    dns_forced0: jnp.ndarray      # [C1] i32 min-match forced to 0 (minDomains)
    dns_honor_aff: jnp.ndarray    # [C1] i32 nodeAffinityPolicy == Honor
    dns_honor_taints: jnp.ndarray  # [C1] i32 nodeTaintsPolicy == Honor
    dns_counts: jnp.ndarray       # [C1, V] i32
    dns_dom: jnp.ndarray          # [C1, V] bool eligible-domain mask
    # PodTopologySpread ScheduleAnyway
    sa_axis: jnp.ndarray          # [C2] i32
    sa_wq: jnp.ndarray            # [C2] i64 round(log(size+2)*1024)
    sa_skew: jnp.ndarray          # [C2] i64
    sa_self: jnp.ndarray          # [C2] i32
    sa_counts: jnp.ndarray        # [C2, V] i32
    # InterPodAffinity required
    anti_axis: jnp.ndarray        # [A1] i32
    anti_self: jnp.ndarray        # [A1] i32
    anti_counts: jnp.ndarray      # [A1, V] i32 (own anti ∪ landed contributions)
    exist_anti: jnp.ndarray       # [NP] i32 existing pods' anti-affinity hits
    aff_axis: jnp.ndarray         # [A2] i32
    aff_self: jnp.ndarray         # [A2] i32
    aff_active: jnp.ndarray       # [A2] i32 (0 = padding row, auto-pass)
    aff_counts: jnp.ndarray       # [A2, V] i32
    aff_own_all: jnp.ndarray      # i32 incoming matches all its own terms
    # InterPodAffinity scoring
    ipa_base: jnp.ndarray         # [NP] i64
    ipa_axis: jnp.ndarray         # [KD] i32
    ipa_wland: jnp.ndarray        # [KD] i64 score delta per landing at axis value
    # Fit / BalancedAllocation scoring config
    fit_slots: jnp.ndarray        # [FR] i32 resource slot per scored resource
    fit_weights: jnp.ndarray      # [FR] i64
    # plugin weights: [tt, fit, pts, ipa, ba, na, il]
    weights: jnp.ndarray          # [7] i64
    # filter enablement from the profile's filter plugin set:
    # [NodeName, NodeUnschedulable, TaintToleration, NodeAffinity, NodeResourcesFit]
    enable: jnp.ndarray           # [5] i32
    # Counted row-local auxiliary constraint (CSI attach limits,
    # nodevolumelimits/csi.go): room left per node for the batch's limited
    # driver; each landing consumes aux_inc units of its row's room.
    aux_room: jnp.ndarray         # [NP] i32 (BIG = unconstrained)
    aux_inc: jnp.ndarray          # i32 scalar (0 = no aux constraint)
    # Nominated-pod lane (runtime/framework.go:1275 two-pass filter, pass 1):
    # per-node request/count totals of preemption-nominated pods with
    # priority >= the batch pod's — the FIT FILTER counts them as if running
    # (pass 1 is strictly tighter than pass 2 for resources, so one pass
    # suffices); scores ignore them, exactly like the host. Shape [0]/[0, R]
    # when the plan has no nominated lane (has_nom=False).
    nom_req: jnp.ndarray          # [NP or 0, R] i64
    nom_pods: jnp.ndarray         # [NP or 0] i32
    # sampling / loop
    num_nodes: jnp.ndarray        # i32
    start_index: jnp.ndarray      # i32
    to_find: jnp.ndarray          # i32


@dataclass
class BatchPlan:
    """A built batch: kernel inputs + host bookkeeping."""

    features: BatchFeatures
    batch_pad: int                # scan length (>= len(pods))
    fit_strategy: int             # 0 = LeastAllocated, 1 = MostAllocated
    vmax: int
    # Host-known batch facts passed as static jit args so the kernel can drop
    # dead score reductions from the scan body (ops/kernel.py fast paths).
    has_pns: bool = True          # any PreferNoSchedule taint staged
    has_ipa_base: bool = True     # any nonzero preferred-affinity base score
    # Every required anti-affinity term is keyed to a singleton-per-node
    # topology axis (kubernetes.io/hostname-like): a landing can only block
    # its own row, so the kernel's lap-vectorized path stays exact.
    anti_rowlocal: bool = False
    # Pod carries preferred node-affinity terms (na_raw nonzero possible):
    # adds a kept-set normalization, disabling the carried-score fast path.
    has_na_pref: bool = False
    # Pod requests host ports: a landing occupies them, so the landed row
    # blocks itself for the rest of the session (identical pods always
    # port-conflict with each other) — row-local, lap-path compatible.
    port_selfblock: bool = False
    # Counted aux constraint live (CSI attach limits) — row-local,
    # lap-path compatible.
    has_aux: bool = False
    # Nominated-pod lane live: the fit filter subtracts nom_req/nom_pods
    # (static per plan; any nomination add/delete invalidates the session
    # via Nominator.version).
    has_nom: bool = False
    # No pod-derived feature coupling anywhere in the plan: no spread or
    # (anti-)affinity count tables, no landing score deltas, no existing-pod
    # anti-affinity hits. A pod arriving on / leaving node n then dirties
    # ONLY row n's resource aggregates — the precondition for the event-
    # journal delta patch (models/tpu_scheduler.py _classify_delta).
    pod_local: bool = False
    @property
    def row_local(self) -> bool:
        """True when a landing changes feasibility AND scores only at its
        own landed row (the kernel's scores_carried ∧ incremental_feas with
        zero cross-row coupling of any kind): the precondition for the
        explicit shard_map lap kernel (parallel/mesh.py sharded_lap_schedule
        — per-shard work is provably local, collectives are two small
        per-lap exchanges) and, with the same math host-side, for the
        score-hint walk (models/score_hints.py)."""
        return (self.pod_local and not self.has_pns and not self.has_na_pref
                and not self.has_nom and not self.port_selfblock
                and not self.has_aux)

    # Host-side per-node topology-spread columns (numpy, NOT shipped to the
    # kernel): per-constraint per-node matching-pod counts + domain
    # eligibility. schedule_placements rebuilds each candidate placement's
    # RESTRICTED count tables from these (the host oracle computes its
    # PreFilter state over the placement-restricted node list —
    # core/cache.py assume_placement), lifting the old no-spread
    # restriction invariant. None when the plan has no spread features.
    dns_node_counts: Optional[object] = None   # np [C1, n] i32
    dns_node_elig: Optional[object] = None     # np [C1, n] bool (key+policies)
    dns_min_domains: Optional[object] = None   # list[Optional[int]] per C1 row
    sa_node_counts: Optional[object] = None    # np [C2, n] i32
    sa_node_live: Optional[object] = None      # np [n] bool (~sa_ignored)
    sa_hostname_axis: Optional[object] = None  # list[bool] per C2 row
    sa_max_skew: Optional[object] = None       # list[int] per C2 row


class Unsupported(Exception):
    """Pod uses a feature outside the device kernel's coverage — the caller
    must take the host path (SURVEY.md §7.4 'sequential fallback')."""


ZONE_KEYS = ("topology.kubernetes.io/zone", "topology.kubernetes.io/region",
             "failure-domain.beta.kubernetes.io/zone",
             "failure-domain.beta.kubernetes.io/region")


def volume_device_support(pod: Pod, clientset, pvc_refs=None,
                          limited_drivers=frozenset()):
    """Device eligibility for a pod's PVC-backed volumes. Returns
    (reason, limited_driver, inc): reason is None when the volumes impose
    either NO per-node constraint (bound PV, no node affinity, no zone
    labels, not RWOP, unshared claim) or exactly one counted CSI
    attach-limit constraint — which the kernel models as the aux counted
    row-local resource (limited_driver/inc feed build_batch's aux vectors).

    Parity argument: under these conditions the volume plugins' Filter
    verdicts are all-pass except NodeVolumeLimits, whose distinct-claim
    count over unshared fresh claims equals the kernel's per-landing count
    (plugins/volumes.py NodeVolumeLimits.filter)."""
    from ..api.storage import RWOP

    names = [v.pvc_name for v in pod.volumes if v.pvc_name]
    if not names:
        return None, "", 0
    if clientset is None:
        return "pvc-backed volumes", "", 0
    driver_incs: Dict[str, int] = {}
    for name in names:
        key = f"{pod.namespace}/{name}"
        pvc = clientset.pvcs.get(key)
        if pvc is None or not pvc.volume_name:
            return "unbound pvc", "", 0
        if RWOP in pvc.access_modes:
            return "rwop pvc", "", 0
        if pvc_refs is not None and pvc_refs.get(key, 0) > 0:
            return "shared pvc", "", 0
        pv = clientset.pvs.get(pvc.volume_name)
        if pv is None:
            return "missing pv", "", 0
        if pv.node_affinity is not None:
            return "pv node affinity", "", 0
        if any(k in pv.labels for k in ZONE_KEYS):
            return "pv zone labels", "", 0
        driver = pv.csi_driver
        if not driver:
            sc = clientset.storage_classes.get(pvc.storage_class)
            driver = sc.provisioner if sc is not None else ""
        if driver and driver in limited_drivers:
            driver_incs[driver] = driver_incs.get(driver, 0) + 1
    if len(driver_incs) > 1:
        return "multiple attach-limited drivers", "", 0
    if driver_incs:
        d, inc = next(iter(driver_incs.items()))
        return None, d, inc
    return None, "", 0


def dra_device_support(pod: Pod, clientset, dra_in_use=None,
                       session_claims=None):
    """Device eligibility for a pod's resource claims: returns
    (reason, shape, inc). Eligible when the pod has exactly ONE unallocated,
    unreserved, unshared claim with ONE request — the claim-template shape.
    The kernel then models the node's FREE MATCHING DEVICE count as the
    counted aux resource; the host commit picks the actual devices on the
    chosen node only (plugins/dynamicresources.py filter, restricted to one
    node). `shape` keys session compatibility: every member of a batch must
    request identically or the per-landing decrement is wrong."""
    names = list(getattr(pod, "resource_claims", ()) or ())
    if not names:
        return None, None, 0
    if clientset is None or len(names) != 1:
        return "dynamic resource claims", None, 0
    key = f"{pod.namespace}/{names[0]}"
    claim = clientset.resource_claims.get(key)
    if claim is None:
        return "resource claim not found", None, 0
    if claim.allocated or claim.reserved_for:
        return "allocated resource claim", None, 0
    if getattr(clientset, "has_consuming_devices", False):
        # Devices that consume node allocatable add a second constraint
        # dimension the aux count cannot model (the plugin's
        # _check_node_allocatable).
        return "node-allocatable-consuming devices", None, 0
    if session_claims is not None and f"dra:{key}" in session_claims:
        return "claim shared within session", None, 0
    if len(claim.requests) != 1:
        return "multi-request claim", None, 0
    r = claim.requests[0]
    shape = (r.device_class, r.count, tuple(sorted(r.selectors.items())),
             r.expression)
    return None, shape, int(r.count)


def count_free_matching_devices(clientset, node_name: str, shape,
                                dra_in_use) -> int:
    """Free devices on `node_name` matching the session's claim shape —
    the aux_room source for DRA batches (mirror of
    plugins/dynamicresources.py filter's per-device predicate)."""
    from ..plugins.dynamicresources import DynamicResources

    device_class, _count, sel_items, expression = shape
    sel = dict(sel_items)
    if device_class:
        dc = clientset.device_classes.get(device_class)
        if dc is not None:
            sel.update(dc.selectors)
    matcher = _compiled_expr(expression) if expression else None
    n = 0
    for sl in clientset.resource_slices.get(node_name, ()):
        for dev in sl.devices:
            if (node_name, sl.driver, dev.name) in dra_in_use:
                continue
            if not all(dev.attributes.get(k) == v for k, v in sel.items()):
                continue
            if matcher is not None and not matcher(dev, sl.driver):
                continue
            n += 1
    return n


from functools import lru_cache


@lru_cache(maxsize=256)
def _compiled_expr(expression: str):
    """Compiled device-selector cache (expression strings are the whole
    input to compilation; bounded so long-lived processes with many claim
    shapes can't grow it without limit)."""
    from ..api.dra import compile_device_expression
    return compile_device_expression(expression)


def batch_supported(pod: Pod, snapshot, fit_plugin=None, ba_plugin=None,
                    clientset=None, pvc_refs=None,
                    limited_drivers=frozenset(),
                    dra_enabled=False, dra_in_use=None, session_claims=None,
                    _volume_verdict=None) -> Optional[str]:
    """Returns a reason string when the pod needs the host path, else None.

    Host ports, node-affinity expressions (required AND preferred), image
    locality, NodeDeclaredFeatures, and bound-PVC volumes (incl. one
    counted CSI attach limit) are covered on device via host-evaluated
    static per-node vectors (sel_match / extra_ok / na_raw / il_score /
    aux_room) — only genuinely stateful host machinery (unbound volume
    binding, DRA allocation, nominated-pod two-pass) still falls back."""
    if pod.nominated_node_name:
        return "nominated node fast path"
    aff = pod.affinity
    na = aff.node_affinity if aff is not None else None
    if na is not None and na.required is not None:
        # matchFields metadata.name pins trigger the NodeAffinity
        # PreFilterResult narrowing (node_affinity.go PreFilter): the host
        # cycle then rotates/samples over the NARROWED node list, which the
        # kernel's full-cluster rotation cannot reproduce — and the narrowed
        # universe is tiny, so the host cycle is already O(1) per pod.
        if any(t.match_fields for t in na.required.terms):
            return "node-affinity metadata.name narrowing"
    reason, vol_d, vol_inc = (_volume_verdict if _volume_verdict is not None
                              else volume_device_support(
                                  pod, clientset, pvc_refs=pvc_refs,
                                  limited_drivers=limited_drivers))
    if reason is not None:
        return reason
    if getattr(pod, "resource_claims", None):
        if not dra_enabled:
            # Profile has no DynamicResources plugin: claims are inert for
            # scheduling (host semantics) — the pod batches as plain.
            pass
        else:
            dreason, _shape, dinc = dra_device_support(
                pod, clientset, dra_in_use=dra_in_use,
                session_claims=session_claims)
            if dreason is not None:
                return dreason
            if dinc and (vol_d and vol_inc):
                return "volume and DRA counted constraints together"
    if fit_plugin is not None and fit_plugin.scoring_strategy not in ("LeastAllocated", "MostAllocated"):
        return "requestedToCapacityRatio strategy"
    if ba_plugin is not None and tuple(
            spec["name"] for spec in ba_plugin.resources) != DEFAULT_BA_RESOURCES:
        return "balanced-allocation custom resources"
    return None


def _resource_vec(mirror: NodeStateMirror, r: "res.Resource") -> np.ndarray:
    out = np.zeros(mirror.r_slots, np.int64)
    out[0] = r.milli_cpu
    out[1] = r.memory
    out[2] = r.ephemeral_storage
    for name, amount in r.scalar_resources.items():
        out[mirror.scalar_slot(name)] = amount
    return out


def build_batch(
    pod: Pod,
    batch_size: int,
    mirror: NodeStateMirror,
    snapshot,
    ns_labels_fn=None,
    *,
    percentage_of_nodes_to_score: int = 0,
    start_index: int = 0,
    weights: Tuple[int, ...] = (3, 1, 2, 2, 1, 2, 1),
    filters_on: Tuple[bool, bool, bool, bool, bool] = (True, True, True, True, True),
    extra_filters: Optional[Dict[str, bool]] = None,
    hard_pod_affinity_weight: int = 1,
    ignore_preferred_terms_of_existing_pods: bool = False,
    fit_plugin=None,
    clientset=None,
    pvc_refs=None,
    limited_drivers=frozenset(),
    dra_enabled=False,
    dra_in_use=None,
    nominated=None,
) -> BatchPlan:
    """Build kernel inputs for a batch of `batch_size` pods identical to `pod`.

    `mirror` must already be synced to `snapshot`. Raises Unsupported for
    feature combinations the kernel does not cover.

    `nominated`: [(node_row, PodInfo)] of preemption-nominated pods with
    priority >= the batch pod's, pre-filtered by the caller (the device
    gate guarantees the batch pod carries no feature a nominated pod could
    interact with beyond resources — models/tpu_scheduler.py
    _nominated_device_block).
    """
    verdict = volume_device_support(
        pod, clientset, pvc_refs=pvc_refs, limited_drivers=limited_drivers)
    reason = batch_supported(pod, snapshot, fit_plugin=fit_plugin,
                             clientset=clientset, pvc_refs=pvc_refs,
                             limited_drivers=limited_drivers,
                             dra_enabled=dra_enabled, dra_in_use=dra_in_use,
                             _volume_verdict=verdict)
    if reason:
        raise Unsupported(reason)
    _vr, aux_driver, aux_inc_n = verdict
    dra_shape = None
    if dra_enabled and getattr(pod, "resource_claims", None):
        _dr, dra_shape, dra_inc = dra_device_support(
            pod, clientset, dra_in_use=dra_in_use)
        if dra_shape is not None and dra_inc:
            aux_driver, aux_inc_n = "", 0  # volume aux unused with DRA aux

    nodes: List[NodeInfo] = snapshot.node_info_list
    n = len(nodes)
    i32, i64 = np.int32, np.int64

    # -- resources (slot interning only; vectors are built after the
    # re-sync point, since interning can grow the slot capacity) -----------
    req = pod.resource_request()
    for name in req.scalar_resources:
        mirror.scalar_slot(name)
    # Nominated pods' scalar slots intern HERE, before the re-sync point —
    # a grow later would orphan every feature vector already built at the
    # old r_slots width.
    nom_reqs = [(row, npi.pod.resource_request())
                for row, npi in (nominated or ())]
    for _row, r in nom_reqs:
        for name in r.scalar_resources:
            mirror.scalar_slot(name)
    if fit_plugin is not None:
        specs = fit_plugin.resources
        strategy = {"LeastAllocated": 0, "MostAllocated": 1}[fit_plugin.scoring_strategy]
    else:
        specs = ({"name": res.CPU, "weight": 1}, {"name": res.MEMORY, "weight": 1})
        strategy = 0
    for spec in specs:
        if spec["name"] not in (res.CPU, res.MEMORY, res.EPHEMERAL_STORAGE, res.PODS):
            mirror.scalar_slot(spec["name"])
    has_request = i64(0 if req.is_zero() else 1)
    ba_skip = i64(1 if (req.milli_cpu == 0 and req.memory == 0) else 0)

    # -- tolerations ------------------------------------------------------
    tols = pod.tolerations
    lt = _pow2(len(tols))
    tol_key = np.zeros(lt, i32)
    tol_val = np.zeros(lt, i32)
    tol_eff = np.full(lt, -1, i32)  # pad: never tolerates
    tol_op = np.zeros(lt, i32)
    for j, t in enumerate(tols):
        tol_key[j] = mirror.keys.intern(t.key)
        tol_val[j] = mirror.vals.intern(t.value)
        tol_eff[j] = EFFECT_IDS.get(t.effect, 0)
        tol_op[j] = OP_EXISTS if t.operator == "Exists" else OP_EQUAL
    tolerates_unsched = i32(
        1 if any(t.tolerates(_UNSCHED_TAINT) for t in tols) else 0)

    # -- cheap filters ----------------------------------------------------
    node_name_id = i32(mirror.names.lookup(pod.node_name) if pod.node_name else 0)
    if pod.node_name and node_name_id == -1:
        # Requested node not in the snapshot: no node can match.
        node_name_id = i32(-2)

    # Host-side per-node predicates reused by the topology aggregations below
    # (identical to the plugin oracles' helpers). sel_match_host carries the
    # FULL node-selector + required-node-affinity semantics and is shipped to
    # the kernel verbatim — affinity matchExpressions/matchFields need no
    # device re-implementation because they are static per batch.
    sel_match_host = [pod.required_node_selector_matches(ni.node) for ni in nodes]
    taint_ok_host = [
        find_matching_untolerated_taint(ni.node.taints, tols) is None for ni in nodes
    ]

    extra = extra_filters or {}

    # -- extra static filters: NodeDeclaredFeatures + NodePorts -------------
    extra_ok_host = np.ones(len(nodes), bool)
    req_feats = [s.strip() for s in pod.annotations.get(
        "features.k8s.io/required", "").split(",") if s.strip()]
    if req_feats and extra.get("NodeDeclaredFeatures", True):
        for r_i, ni in enumerate(nodes):
            declared = ni.node.declared_features if ni.node else {}
            if not all(declared.get(ft, False) for ft in req_feats):
                extra_ok_host[r_i] = False
    ports = pod.host_ports()
    port_selfblock = False
    if ports and extra.get("NodePorts", True):
        # Identical pods always conflict with their own ports, so a landing
        # blocks its row (kernel carry `blocked`); existing-pod conflicts are
        # static — evaluated with the host plugin's own predicate.
        from ..plugins.basic import host_ports_conflict
        port_selfblock = True
        for r_i, ni in enumerate(nodes):
            if host_ports_conflict(ports, ni.used_ports):
                extra_ok_host[r_i] = False

    # -- ImageLocality static score (imagelocality.go scaledImageScore) -----
    il_host = None
    if weights[6] and any(c.image for c in pod.containers):
        from ..plugins.basic import ImageLocality
        total_nodes = max(1, len(nodes))
        il_host = np.zeros(len(nodes), np.int64)
        for r_i, ni in enumerate(nodes):
            il_host[r_i] = ImageLocality.scaled_score(
                pod, ni, snapshot.image_num_nodes, total_nodes)

    # -- preferred node affinity raw score (node_affinity.go Score) ---------
    na_host = None
    has_na_pref = False
    na_spec = pod.affinity.node_affinity if pod.affinity else None
    if na_spec is not None and na_spec.preferred and weights[5]:
        has_na_pref = True
        na_host = np.zeros(len(nodes), np.int64)
        for r_i, ni in enumerate(nodes):
            t = 0
            for pref in na_spec.preferred:
                if pref.preference.matches(ni.node):
                    t += pref.weight
            na_host[r_i] = t

    # -- PodTopologySpread ------------------------------------------------
    dns = _compile_constraints(pod, DO_NOT_SCHEDULE)
    sa = _compile_constraints(pod, SCHEDULE_ANYWAY)
    for c in dns + sa:
        mirror.ensure_axis(c.topology_key)

    # -- InterPodAffinity terms -------------------------------------------
    pi = PodInfo.of(pod)
    aff_terms = compile_terms(pi.required_affinity_terms, pod)
    anti_terms = compile_terms(pi.required_anti_affinity_terms, pod)
    pref_aff = [(w.weight, t) for w, t in
                ((w, compile_terms((w.term,), pod)[0]) for w in pi.preferred_affinity_terms)]
    pref_anti = [(w.weight, t) for w, t in
                 ((w, compile_terms((w.term,), pod)[0]) for w in pi.preferred_anti_affinity_terms)]
    for t in list(aff_terms) + list(anti_terms):
        mirror.ensure_axis(t.topology_key)
    for _, t in pref_aff + pref_anti:
        mirror.ensure_axis(t.topology_key)
    # Existing pods' terms introduce axes too; collect before building tables.
    existing_term_cache: Dict[str, tuple] = {}

    def existing_terms(epi: PodInfo, which: str):
        ck = (epi.pod.uid, which)
        terms = existing_term_cache.get(ck)
        if terms is None:
            raw = getattr(epi, which)
            terms = compile_terms(raw, epi.pod)
            existing_term_cache[ck] = terms
        return terms

    for ni in nodes:
        for epi in ni.pods_with_affinity:
            for which in ("required_anti_affinity_terms", "required_affinity_terms",
                          "preferred_affinity_terms", "preferred_anti_affinity_terms"):
                raw = getattr(epi, which)
                for item in raw:
                    key = item.term.topology_key if hasattr(item, "term") else item.topology_key
                    mirror.ensure_axis(key)

    if mirror._full_flush:
        # New axes or capacity tiers were registered: rows must re-encode
        # before any vid/slot gathers below.
        mirror.sync(nodes)

    npc = mirror.np_cap
    request = _resource_vec(mirror, req)
    nz_request = np.array(
        [req.milli_cpu or NodeInfo.DEFAULT_MILLI_CPU,
         req.memory or NodeInfo.DEFAULT_MEMORY], i64)

    vmax = _pow2(max((len(ax.values) for ax in mirror.axes.values()), default=1) + 1, 64)

    # ---- DNS tables ------------------------------------------------------
    c1 = _pow2(len(dns))
    dns_axis = np.zeros(c1, i32)
    dns_active = np.zeros(c1, i32)            # pad rows: inert
    dns_max_skew = np.full(c1, 1 << 40, i64)  # pad: never rejects
    dns_self = np.zeros(c1, i32)
    dns_forced0 = np.ones(c1, i32)            # pad: min 0
    dns_honor_aff = np.zeros(c1, i32)
    dns_honor_taints = np.zeros(c1, i32)
    dns_counts = np.zeros((c1, vmax), i32)
    dns_dom = np.zeros((c1, vmax), bool)
    dns_node_counts = np.zeros((len(dns), n), i32) if dns else None
    dns_node_elig = np.zeros((len(dns), n), bool) if dns else None
    dns_min_domains = [c.min_domains for c in dns] if dns else None
    for ci, c in enumerate(dns):
        ax = mirror.axes[c.topology_key]
        dns_axis[ci] = ax.index
        dns_active[ci] = 1
        dns_max_skew[ci] = c.max_skew
        dns_self[ci] = 1 if c.selector.matches(pod.labels) else 0
        dns_honor_aff[ci] = 1 if c.node_affinity_policy == HONOR else 0
        dns_honor_taints[ci] = 1 if c.node_taints_policy == HONOR else 0
        vids = mirror.h_topo[ax.index]
        n_domains = set()
        for r_i, ni in enumerate(nodes):
            node = ni.node
            if c.topology_key not in node.labels:
                continue
            if dns_honor_aff[ci] and not sel_match_host[r_i]:
                continue
            if dns_honor_taints[ci] and not taint_ok_host[r_i]:
                continue
            vid = vids[r_i]
            dns_dom[ci, vid] = True
            n_domains.add(vid)
            cnt = _count_pods_matching(ni, c.selector, pod.namespace)
            dns_counts[ci, vid] += cnt
            dns_node_counts[ci, r_i] = cnt
            dns_node_elig[ci, r_i] = True
        forced = c.min_domains is not None and len(n_domains) < c.min_domains
        dns_forced0[ci] = 1 if (forced or not n_domains) else 0

    # ---- SA tables -------------------------------------------------------
    c2 = _pow2(len(sa))
    sa_axis = np.zeros(c2, i32)
    sa_wq = np.zeros(c2, i64)
    sa_skew = np.ones(c2, i64)
    sa_self = np.zeros(c2, i32)
    sa_counts = np.zeros((c2, vmax), i32)
    sa_node_counts = np.zeros((len(sa), n), i32) if sa else None
    sa_node_live = None
    sa_hostname_axis = [c.topology_key == LABEL_HOSTNAME for c in sa] if sa else None
    sa_max_skew_l = [int(c.max_skew) for c in sa] if sa else None
    if sa:
        # scoring.go initPreScoreState: a node is ignored when it misses any
        # constraint's topology key or fails the pod's required node affinity.
        sa_ignored = [
            (not all(c.topology_key in ni.node.labels for c in sa)) or not sel_match_host[r_i]
            for r_i, ni in enumerate(nodes)
        ]
        sa_node_live = ~np.asarray(sa_ignored, bool)
        for ci, c in enumerate(sa):
            ax = mirror.axes[c.topology_key]
            sa_axis[ci] = ax.index
            sa_skew[ci] = c.max_skew
            sa_self[ci] = 1 if c.selector.matches(pod.labels) else 0
            vids = mirror.h_topo[ax.index]
            domains = set()
            size_hostname = 0
            for r_i, ni in enumerate(nodes):
                if sa_ignored[r_i]:
                    continue
                vid = vids[r_i]
                cnt = _count_pods_matching(ni, c.selector, pod.namespace)
                sa_counts[ci, vid] += cnt
                sa_node_counts[ci, r_i] = cnt
                domains.add(vid)
                size_hostname += 1
            if c.topology_key == LABEL_HOSTNAME:
                size = size_hostname
            else:
                size = len(domains)
            sa_wq[ci] = int(round(math.log(size + 2) * 1024))

    # ---- IPA required tables --------------------------------------------
    a1 = _pow2(len(anti_terms))
    anti_axis = np.zeros(a1, i32)
    anti_self = np.zeros(a1, i32)
    anti_counts = np.zeros((a1, vmax), i32)
    a2 = _pow2(len(aff_terms))
    aff_axis = np.zeros(a2, i32)
    aff_self = np.zeros(a2, i32)
    aff_active = np.zeros(a2, i32)
    aff_counts = np.zeros((a2, vmax), i32)
    exist_anti = np.zeros(npc, i32)
    anti_rowlocal = bool(anti_terms)
    for ti, t in enumerate(anti_terms):
        ax = mirror.axes[t.topology_key]
        anti_axis[ti] = ax.index
        anti_self[ti] = 1 if t.matches(pod, ns_labels_fn) else 0
        if anti_rowlocal:
            vids = mirror.h_topo[ax.index, :n]
            nz = vids[vids > 0]
            if nz.size and np.bincount(nz).max() > 1:
                anti_rowlocal = False  # shared domains: cross-window coupling
    for ti, t in enumerate(aff_terms):
        aff_axis[ti] = mirror.axes[t.topology_key].index
        aff_self[ti] = 1 if t.matches(pod, ns_labels_fn) else 0
        aff_active[ti] = 1
    aff_own_all = i32(1 if aff_terms and all(
        t.matches(pod, ns_labels_fn) for t in aff_terms) else 0)

    # Existing pods' required anti-affinity vs the incoming pod
    # (filtering.go:217-241) — accumulated per (axis, value) then broadcast to
    # a per-row hit count.
    exist_pairs: Dict[Tuple[int, int], int] = {}
    for r_i, ni in enumerate(nodes):
        if not ni.pods_with_required_anti_affinity:
            continue
        node = ni.node
        for epi in ni.pods_with_required_anti_affinity:
            for term in existing_terms(epi, "required_anti_affinity_terms"):
                tp_val = node.labels.get(term.topology_key)
                if tp_val is None:
                    continue
                if term.matches(pod, ns_labels_fn):
                    ax = mirror.axes[term.topology_key]
                    key = (ax.index, ax.lookup_value(tp_val))
                    exist_pairs[key] = exist_pairs.get(key, 0) + 1
    for (ax_i, vid), cnt in exist_pairs.items():
        if cnt > 0 and vid >= 0:
            exist_anti[:n] += (mirror.h_topo[ax_i, :n] == vid).astype(i32)

    # Incoming pod's required terms vs all existing pods (filtering.go:247-284).
    if aff_terms or anti_terms:
        for r_i, ni in enumerate(nodes):
            if not ni.pods:
                continue
            for epi in ni.pods:
                ep = epi.pod
                for ti, term in enumerate(aff_terms):
                    vid = mirror.h_topo[mirror.axes[term.topology_key].index, r_i]
                    if vid > 0 and term.matches(ep, ns_labels_fn):
                        aff_counts[ti, vid] += 1
                for ti, term in enumerate(anti_terms):
                    vid = mirror.h_topo[mirror.axes[term.topology_key].index, r_i]
                    if vid > 0 and term.matches(ep, ns_labels_fn):
                        anti_counts[ti, vid] += 1

    # ---- IPA scoring -----------------------------------------------------
    # Base per-node preferred-term score (scoring.go PreScore accumulation),
    # plus per-axis landing deltas for batch-internal contributions.
    topology_score: Dict[str, Dict[str, int]] = {}

    def _add_score(tp_key: str, tp_val: str, w: int) -> None:
        if w == 0:
            return
        topology_score.setdefault(tp_key, {})
        topology_score[tp_key][tp_val] = topology_score[tp_key].get(tp_val, 0) + w

    has_pref = bool(pref_aff or pref_anti)
    scan_nodes = nodes if has_pref else snapshot.have_pods_with_affinity_list
    for ni in scan_nodes:
        node = ni.node
        if node is None:
            continue
        pods_iter = ni.pods if has_pref else ni.pods_with_affinity
        for epi in pods_iter:
            ep = epi.pod
            for weight, term in pref_aff:
                tp_val = node.labels.get(term.topology_key)
                if tp_val is not None and term.matches(ep, ns_labels_fn):
                    _add_score(term.topology_key, tp_val, weight)
            for weight, term in pref_anti:
                tp_val = node.labels.get(term.topology_key)
                if tp_val is not None and term.matches(ep, ns_labels_fn):
                    _add_score(term.topology_key, tp_val, -weight)
            if hard_pod_affinity_weight > 0:
                for term in existing_terms(epi, "required_affinity_terms"):
                    tp_val = node.labels.get(term.topology_key)
                    if tp_val is not None and term.matches(pod, ns_labels_fn):
                        _add_score(term.topology_key, tp_val, hard_pod_affinity_weight)
            if not ignore_preferred_terms_of_existing_pods:
                for wt in epi.preferred_affinity_terms:
                    term = compile_terms((wt.term,), ep)[0]
                    tp_val = node.labels.get(term.topology_key)
                    if tp_val is not None and term.matches(pod, ns_labels_fn):
                        _add_score(term.topology_key, tp_val, wt.weight)
                for wt in epi.preferred_anti_affinity_terms:
                    term = compile_terms((wt.term,), ep)[0]
                    tp_val = node.labels.get(term.topology_key)
                    if tp_val is not None and term.matches(pod, ns_labels_fn):
                        _add_score(term.topology_key, tp_val, -wt.weight)

    ipa_base = np.zeros(npc, i64)
    for tp_key, vals in topology_score.items():
        ax = mirror.axes.get(tp_key)
        if ax is None:
            continue  # key only on deleted nodes; no live node can match
        col = np.zeros(vmax, i64)
        for v, w in vals.items():
            vid = ax.lookup_value(v)
            if vid >= 0:
                col[vid] = w
        ipa_base[:n] += col[np.clip(mirror.h_topo[ax.index, :n], 0, vmax - 1)]
        ipa_base[:n][mirror.h_topo[ax.index, :n] == 0] -= col[0]  # absent key adds nothing

    # Landing deltas: contributions a landed batch pod makes to the *next*
    # batch pod's topology_score, aggregated per axis. Both directions of each
    # preferred term apply for identical pods (pre_score's a/c loops).
    land: Dict[int, int] = {}
    mult = 1 if ignore_preferred_terms_of_existing_pods else 2
    for weight, term in pref_aff:
        if term.matches(pod, ns_labels_fn):
            ax_i = mirror.axes[term.topology_key].index
            land[ax_i] = land.get(ax_i, 0) + weight * mult
    for weight, term in pref_anti:
        if term.matches(pod, ns_labels_fn):
            ax_i = mirror.axes[term.topology_key].index
            land[ax_i] = land.get(ax_i, 0) - weight * mult
    if hard_pod_affinity_weight > 0:
        for term in aff_terms:
            if term.matches(pod, ns_labels_fn):
                ax_i = mirror.axes[term.topology_key].index
                land[ax_i] = land.get(ax_i, 0) + hard_pod_affinity_weight
    kd = _pow2(len(land))
    ipa_axis = np.zeros(kd, i32)
    ipa_wland = np.zeros(kd, i64)
    for j, (ax_i, w) in enumerate(sorted(land.items())):
        ipa_axis[j] = ax_i
        ipa_wland[j] = w

    # ---- Fit scoring config (slots pre-interned above) ------------------
    fr = _pow2(len(specs))
    fit_slots = np.zeros(fr, i32)
    fit_weights = np.zeros(fr, i64)  # pad weight 0: excluded
    slot_of = {res.CPU: 0, res.MEMORY: 1, res.EPHEMERAL_STORAGE: 2}
    for j, spec in enumerate(specs):
        name = spec["name"]
        fit_slots[j] = slot_of.get(name, mirror.scalar_slot(name) if name not in slot_of else 0)
        fit_weights[j] = spec.get("weight", 1)

    to_find = num_feasible_nodes_to_find(n, percentage_of_nodes_to_score)

    # ---- nominated-pod lane (two-pass filter pass 1, resources only) -----
    # (scalar slots were interned at the top of build_batch, before re-sync)
    has_nom = bool(nominated)
    if has_nom:
        nom_req = np.zeros((npc, mirror.r_slots), i64)
        nom_pods = np.zeros(npc, i32)
        for row, r in nom_reqs:
            nom_req[row] += _resource_vec(mirror, r)
            nom_pods[row] += 1
    else:
        nom_req = np.zeros((0, mirror.r_slots), i64)
        nom_pods = np.zeros(0, i32)

    # ---- counted aux constraint: CSI attach room / DRA free devices ------
    AUX_BIG = (1 << 30)
    aux_room = np.full(npc, AUX_BIG, i32)
    has_aux_flag = False
    if dra_shape is not None:
        iu = dra_in_use if dra_in_use is not None else set()
        for r_i, ni in enumerate(nodes):
            aux_room[r_i] = count_free_matching_devices(
                clientset, ni.name, dra_shape, iu)
        aux_inc_n = dra_shape[1]
        has_aux_flag = True
    if aux_driver and aux_inc_n:
        driver_of: Dict[str, Optional[str]] = {}

        def _claim_driver(key: str) -> Optional[str]:
            d = driver_of.get(key)
            if d is None and key not in driver_of:
                pvc = clientset.pvcs.get(key)
                d = None
                if pvc is not None:
                    pv = clientset.pvs.get(pvc.volume_name) if pvc.volume_name else None
                    if pv is not None and pv.csi_driver:
                        d = pv.csi_driver
                    else:
                        sc = clientset.storage_classes.get(pvc.storage_class)
                        d = sc.provisioner if sc is not None else None
                driver_of[key] = d
            return driver_of.get(key)

        for r_i, ni in enumerate(nodes):
            cn = clientset.csi_nodes.get(ni.name)
            limit = cn.driver_limits.get(aux_driver) if cn is not None else None
            if limit is None:
                continue
            existing = sum(1 for key in ni.pvc_ref_counts
                           if _claim_driver(key) == aux_driver)
            aux_room[r_i] = max(0, limit - existing)

    feats = BatchFeatures(
        request=jnp.asarray(request),
        nz_request=jnp.asarray(nz_request),
        has_request=jnp.asarray(has_request),
        ba_skip=jnp.asarray(ba_skip),
        tol_key=jnp.asarray(tol_key), tol_val=jnp.asarray(tol_val),
        tol_eff=jnp.asarray(tol_eff), tol_op=jnp.asarray(tol_op),
        node_name_id=jnp.asarray(node_name_id),
        tolerates_unsched=jnp.asarray(tolerates_unsched),
        sel_match=jnp.asarray(_pad_bool(sel_match_host, npc)),
        extra_ok=jnp.asarray(_pad_bool(extra_ok_host, npc, default=True)),
        il_score=jnp.asarray(_pad_i64(il_host, npc)),
        na_raw=jnp.asarray(_pad_i64(na_host, npc)),
        dns_axis=jnp.asarray(dns_axis), dns_active=jnp.asarray(dns_active),
        dns_max_skew=jnp.asarray(dns_max_skew),
        dns_self=jnp.asarray(dns_self), dns_forced0=jnp.asarray(dns_forced0),
        dns_honor_aff=jnp.asarray(dns_honor_aff),
        dns_honor_taints=jnp.asarray(dns_honor_taints),
        dns_counts=jnp.asarray(dns_counts), dns_dom=jnp.asarray(dns_dom),
        sa_axis=jnp.asarray(sa_axis), sa_wq=jnp.asarray(sa_wq),
        sa_skew=jnp.asarray(sa_skew), sa_self=jnp.asarray(sa_self),
        sa_counts=jnp.asarray(sa_counts),
        anti_axis=jnp.asarray(anti_axis), anti_self=jnp.asarray(anti_self),
        anti_counts=jnp.asarray(anti_counts),
        exist_anti=jnp.asarray(exist_anti),
        aff_axis=jnp.asarray(aff_axis), aff_self=jnp.asarray(aff_self),
        aff_active=jnp.asarray(aff_active), aff_counts=jnp.asarray(aff_counts),
        aff_own_all=jnp.asarray(aff_own_all),
        ipa_base=jnp.asarray(ipa_base),
        ipa_axis=jnp.asarray(ipa_axis), ipa_wland=jnp.asarray(ipa_wland),
        fit_slots=jnp.asarray(fit_slots), fit_weights=jnp.asarray(fit_weights),
        weights=jnp.asarray(np.array(weights, i64)),
        enable=jnp.asarray(np.array([1 if b else 0 for b in filters_on], i32)),
        aux_room=jnp.asarray(aux_room),
        aux_inc=jnp.asarray(np.int32(aux_inc_n)),
        nom_req=jnp.asarray(nom_req),
        nom_pods=jnp.asarray(nom_pods),
        num_nodes=jnp.asarray(np.int32(n)),
        start_index=jnp.asarray(np.int32(start_index % max(1, n))),
        to_find=jnp.asarray(np.int32(to_find)),
    )
    return BatchPlan(
        features=feats,
        batch_pad=_batch_tier(batch_size),
        fit_strategy=strategy,
        vmax=vmax,
        has_pns=bool((mirror.h_taint_eff[:n] == EFFECT_PREFER_NO_SCHEDULE).any()),
        has_ipa_base=bool((ipa_base != 0).any()),
        pod_local=bool(c1 == 0 and c2 == 0 and a1 == 0 and a2 == 0
                       and kd == 0 and not (ipa_base != 0).any()
                       and not (exist_anti != 0).any()),
        anti_rowlocal=anti_rowlocal,
        has_na_pref=has_na_pref,
        port_selfblock=port_selfblock,
        has_aux=has_aux_flag or bool(aux_driver and aux_inc_n),
        has_nom=has_nom,
        dns_node_counts=dns_node_counts,
        dns_node_elig=dns_node_elig,
        dns_min_domains=dns_min_domains,
        sa_node_counts=sa_node_counts,
        sa_node_live=sa_node_live,
        sa_hostname_axis=sa_hostname_axis,
        sa_max_skew=sa_max_skew_l,
    )


def _pad_bool(vals, npc: int, default: bool = False) -> np.ndarray:
    out = np.full(npc, default, bool)
    if vals is not None:
        out[:len(vals)] = vals
    return out


def _pad_i64(vals, npc: int) -> np.ndarray:
    out = np.zeros(npc, np.int64)
    if vals is not None:
        out[:len(vals)] = vals
    return out


def _batch_tier(n: int) -> int:
    """Coarse scan-length tiers: each distinct tier is a separate XLA compile
    (~1 min on first use), so bound them to {8, 64, 512, 1024, ...}. Padded
    steps cost device time but sliced-off outputs keep semantics exact."""
    if n <= 8:
        return 8
    if n <= 64:
        return 64
    return _pow2(n, 512)


PREEMPT_K_CAP = 256  # victims-per-node tier ceiling (recompile guard)


def build_preemption_victims(pod: Pod, snapshot, mirror: NodeStateMirror):
    """Victim tensors for the dry-run kernel: per node, every lower-priority
    pod in MoreImportantPod reprieve order (higher priority first, then
    earlier start — preemption.go:480-520 / the host Evaluator's sort).
    Returns (vic_req [npc, K, R] i64, vic_valid [npc, K] bool,
    potential [n] list-of-PodInfo in the same order) or None when some node
    exceeds the K cap (host path owns it)."""
    nodes = snapshot.node_info_list
    prio = pod.priority
    potential = []
    kmax = 0
    for ni in nodes:
        pis = [pi for pi in ni.pods if pi.pod.priority < prio]
        pis.sort(key=lambda pi: (-pi.pod.priority, pi.pod.creation_ts))
        potential.append(pis)
        if len(pis) > kmax:
            kmax = len(pis)
    if kmax == 0 or kmax > PREEMPT_K_CAP:
        return None
    k = _pow2(kmax, 8)
    npc = mirror.np_cap
    # Intern every victim scalar-resource slot BEFORE allocating (interning
    # can grow r_slots; the caller's build_plan re-syncs the mirror after).
    reqs = [[pi.pod.resource_request() for pi in pis] for pis in potential]
    for rs in reqs:
        for r in rs:
            for name in r.scalar_resources:
                mirror.scalar_slot(name)
    vic_req = np.zeros((npc, k, mirror.r_slots), np.int64)
    vic_valid = np.zeros((npc, k), bool)
    for r_i, rs in enumerate(reqs):
        for j, r in enumerate(rs):
            vic_req[r_i, j] = _resource_vec(mirror, r)
            vic_valid[r_i, j] = True
    return vic_req, vic_valid, potential


def diagnose_unschedulable(pod: Pod, mirror: NodeStateMirror, snapshot,
                           fw) -> Optional["object"]:
    """Per-node failure Diagnosis for a pod the device found infeasible
    EVERYWHERE — vectorized over the mirror's staging arrays instead of the
    pure-Python per-node filter loop (which costs ~0.3s at 5k nodes and used
    to run once per hopeless pod; the Unschedulable-flood workloads pay it
    hundreds of times).

    Covers pods whose filters are all static per batch (no topology spread /
    pod affinity — those return None and take the exact host rerun). The
    verdict codes and plugin attributions match the host plugins in profile
    filter order; messages are the plugins' standard texts.
    """
    if (pod.topology_spread_constraints
            or (pod.affinity is not None
                and (pod.affinity.pod_affinity or pod.affinity.pod_anti_affinity))):
        return None
    from ..core.framework import Diagnosis, Status

    nodes: List[NodeInfo] = snapshot.node_info_list
    n = len(nodes)
    if n == 0:
        return None
    names = {p.name for p in fw.filter_plugins}

    # (plugin, unresolvable, fails[n] bool, message) in profile filter order.
    checks: List[Tuple[str, bool, np.ndarray, str]] = []

    if "NodeName" in names and pod.node_name:
        fails = np.array([ni.name != pod.node_name for ni in nodes])
        checks.append(("NodeName", True, fails,
                       "node(s) didn't match the requested node name"))
    if "NodeUnschedulable" in names:
        unsched = mirror.h_unsched[:n].copy()
        if any(t.tolerates(_UNSCHED_TAINT) for t in pod.tolerations):
            unsched[:] = False
        checks.append(("NodeUnschedulable", True, unsched,
                       "node(s) were unschedulable"))
    if "TaintToleration" in names:
        tainted_rows = (mirror.h_taint_eff[:n] != 0).any(axis=1)
        fails = np.zeros(n, bool)
        for r_i in np.nonzero(tainted_rows)[0]:
            fails[r_i] = find_matching_untolerated_taint(
                nodes[r_i].node.taints, pod.tolerations) is not None
        checks.append(("TaintToleration", True, fails,
                       "node(s) had untolerated taint(s)"))
    if "NodeAffinity" in names and (
            pod.node_selector or (pod.affinity and pod.affinity.node_affinity
                                  and pod.affinity.node_affinity.required)):
        fails = np.array([not pod.required_node_selector_matches(ni.node)
                          for ni in nodes])
        checks.append(("NodeAffinity", True, fails,
                       "node(s) didn't match Pod's node affinity/selector"))
    ports = pod.host_ports()
    if "NodePorts" in names and ports:
        from ..plugins.basic import host_ports_conflict
        fails = np.array([host_ports_conflict(ports, ni.used_ports)
                          for ni in nodes])
        checks.append(("NodePorts", False, fails,
                       "node(s) didn't have free ports for the requested pod ports"))
    if "NodeResourcesFit" in names:
        req = pod.resource_request()
        req_vec = _resource_vec(mirror, req)
        alloc = mirror.h_alloc_r[:n]
        used = mirror.h_req_r[:n]
        pos = req_vec > 0
        insufficient = (req_vec[None, :] > (alloc - used)) & pos[None, :]
        over_capacity = (req_vec[None, :] > alloc) & pos[None, :]
        pods_full = (mirror.h_pod_count[:n] + 1) > mirror.h_alloc_pods[:n]
        # Unresolvable when the request exceeds allocatable outright
        # (fit.go fitsRequest Unresolvable flag) — preemption can't help.
        checks.append(("NodeResourcesFit", True,
                       over_capacity.any(axis=1),
                       "Insufficient resources (request exceeds allocatable)"))
        checks.append(("NodeResourcesFit", False,
                       insufficient.any(axis=1) | pods_full,
                       "Insufficient resources"))
    if "NodeDeclaredFeatures" in names:
        feats = [s.strip() for s in pod.annotations.get(
            "features.k8s.io/required", "").split(",") if s.strip()]
        if feats:
            fails = np.array([
                not all((ni.node.declared_features if ni.node else {}).get(ft, False)
                        for ft in feats) for ni in nodes])
            checks.append(("NodeDeclaredFeatures", False, fails,
                           "node(s) didn't declare required features"))

    if not checks:
        return None
    fail_stack = np.stack([c[2] for c in checks])          # [C, n]
    any_fail = fail_stack.any(axis=0)
    if not any_fail.all():
        return None  # some node passes every static filter: not our case
    first = np.argmax(fail_stack, axis=0)                  # first failing check
    diag = Diagnosis()
    statuses = {}
    for ci, (plugin, unresolvable, _f, msg) in enumerate(checks):
        statuses[ci] = (Status.unresolvable(msg) if unresolvable
                        else Status.unschedulable(msg))
        statuses[ci].plugin = plugin
        diag.unschedulable_plugins.add(plugin)
    # Only plugins that actually rejected somewhere count.
    rejected_plugins = {checks[ci][0] for ci in set(first.tolist())}
    diag.unschedulable_plugins &= rejected_plugins
    for r_i, ni in enumerate(nodes):
        diag.node_to_status[ni.name] = statuses[int(first[r_i])]
    return diag
