"""Cluster-autoscaler loop (controller-family member).

Watches the PENDING-POD BACKLOG AGE off the informer cache — the signal
the reference autoscaler derives from unschedulable pod events — and
adds hollow nodes in waves when the oldest pending pod has waited past
`pending_age_s`, bounded by `max_nodes` and a scale cooldown. The
inverse direction removes ONLY nodes this loop itself added
(`<prefix>-N` names) and only while they hold no bound pods and the
backlog is empty, never shrinking the cluster below `min_nodes`.
Node adds/removes ride the public REST surface (bulk node POST /
DELETE — the hollow plane's own register/delete verbs), so WAL,
replication, and watch fanout see autoscaled capacity exactly as
registered kubelets.

Ages are tracked against THIS controller's clock from first sight of
each pending pod: after a takeover the new ACTIVE manager re-ages the
backlog from zero — one full `pending_age_s` of grace before it scales,
the same failover posture as the node-lifecycle controller's heartbeat
ages.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..core.apiserver import node_from_wire


def _default_node_wire(name: str) -> dict:
    # A generously-sized hollow shape: autoscaled capacity must actually
    # absorb the backlog that triggered it.
    return {"name": name, "uid": f"node/{name}",
            "labels": {"autoscaler.kubernetes.io/managed": "true"},
            "allocatable": {"cpu": 16000, "memory": 64 << 30,
                            "ephemeral": 0, "pods": 110, "scalar": {}},
            "taints": [], "unschedulable": False}


class ClusterAutoscaler:
    def __init__(self, clientset, min_nodes: int = 0,
                 max_nodes: int = 100, wave: int = 2,
                 pending_age_s: float = 2.0, cooldown_s: float = 5.0,
                 prefix: str = "autoscale",
                 node_wire_fn: Optional[Callable[[str, int], dict]] = None,
                 now: Callable[[], float] = time.monotonic):
        self.cs = clientset
        self.min_nodes = int(min_nodes)
        self.max_nodes = int(max_nodes)
        self.wave = max(1, int(wave))
        self.pending_age_s = float(pending_age_s)
        self.cooldown_s = float(cooldown_s)
        self.prefix = prefix
        self._node_wire = node_wire_fn or (
            lambda name, _seq: _default_node_wire(name))
        self._now = now
        self._pending_since: Dict[str, float] = {}
        self._last_scale = -float("inf")
        self._seq = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.nodes_added = 0
        self.nodes_removed = 0
        self.errors = 0

    def reconcile_once(self) -> None:
        now = self._now()
        pending = {p.uid for p in self.cs.pods.values()
                   if not p.node_name and p.deletion_ts is None}
        for uid in [u for u in self._pending_since if u not in pending]:
            del self._pending_since[uid]
        oldest = 0.0
        for uid in pending:
            oldest = max(oldest,
                         now - self._pending_since.setdefault(uid, now))
        if now - self._last_scale < self.cooldown_s:
            return
        total = len(self.cs.nodes)
        if pending and oldest >= self.pending_age_s:
            if total < self.max_nodes:
                self._scale_up(min(self.wave, self.max_nodes - total), now)
            return
        if not pending:
            self._scale_down(now)

    def _scale_up(self, k: int, now: float) -> None:
        added = 0
        for _ in range(k):
            name = f"{self.prefix}-{self._seq}"
            self._seq += 1
            try:
                self.cs.create_node(
                    node_from_wire(self._node_wire(name, self._seq - 1)))
                added += 1
            except Exception:  # noqa: BLE001 - 409/transport: retry later
                self.errors += 1
        if added:
            self.nodes_added += added
            self.scale_ups += 1
            self._last_scale = now

    def _scale_down(self, now: float) -> None:
        total = len(self.cs.nodes)
        removable = total - self.min_nodes
        if removable <= 0:
            return
        occupied = {p.node_name for p in self.cs.pods.values()
                    if p.node_name}
        empties = sorted(n for n in self.cs.nodes
                         if n.startswith(self.prefix + "-")
                         and n not in occupied)
        removed = 0
        for name in empties[:min(self.wave, removable)]:
            try:
                self.cs.delete_node(name)
                removed += 1
            except Exception:  # noqa: BLE001
                self.errors += 1
        if removed:
            self.nodes_removed += removed
            self.scale_downs += 1
            self._last_scale = now

    def stats(self) -> dict:
        return {"scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "nodes_added": self.nodes_added,
                "nodes_removed": self.nodes_removed,
                "pending_tracked": len(self._pending_since),
                "errors": self.errors}
