"""Descheduler — drift-repair controller (sig-scheduling descheduler
sibling, layered on the PR-16 eviction plane; docs/DESCHEDULE.md).

The cluster only gets *scheduled* once; churn (hollow drift waves,
node-lifecycle evictions, autoscaler waves, rolling updates) then moves
the ground truth out from under the placements. This controller is the
plane that revisits them: a reconcile tick snapshots bound placements
from the watch-cache read plane, pluggable strategies nominate drifted
pods, and every nominee is rescored against EVERY node as one dense
what-if matrix (ops/whatif.py — the scheduler's own fit/BA arithmetic,
host walker by default, bit-identical jit mirror with ``device=True``).

A move is emitted only when:

- its scored improvement clears the hysteresis floor
  (``clears_hysteresis`` — the gate the ``deschedule-discipline``
  analyzer rule pins onto every eviction slice), and
- its gang moves WHOLE: a PodGroup member never moves alone — either
  every member has a qualifying landing or the group stays put, so the
  gang scheduler restarts the group at the new placement instead of
  tearing a partial hole in it.

Emission rides the PR-16 funnel unchanged: deterministic ``uid@node``
intents through ``RateLimitedEvictor`` per-zone buckets into the
PDB-precondition-gated eviction subresource. Exactly-once across
kill9/failover falls out of determinism — a standby re-plans the same
snapshot, mints the same intents, and the apiserver's WAL'd ledger
answers the duplicates with ``already=True``.

HA mirrors the workload manager: every tick races a PUT-CAS lease;
the loser idles STANDBY with warm informers.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from ..core.node_info import NodeInfo, PodInfo
from ..ops import whatif
from .evictor import RateLimitedEvictor, intent_for
from .node_lifecycle import ZONE_LABEL
from .workload import OWNER_LABEL

MANAGER_LEASE = "descheduler"

BLOCK_REASONS = ("pdb", "budget", "gang", "hysteresis")


def clears_hysteresis(improvement: int, floor: int,
                      must_move: bool = False) -> bool:
    """The scored-improvement gate. Every eviction the descheduler emits
    sits downstream of this predicate (deschedule-discipline pins it):
    a move below the floor is churn, not repair — and a floor of N
    points breaks the evict/re-bind/evict ping-pong cycle two nearly
    balanced nodes would otherwise trade forever. ``must_move``
    (violation strategies: the CURRENT seat is illegal) waives the
    floor but still requires a feasible landing upstream."""
    return must_move or improvement >= floor


class Snapshot(NamedTuple):
    node_infos: List[NodeInfo]          # sorted by node name
    row: Dict[str, int]                 # node name -> row index
    bound: List[object]                 # bound pods, sorted by uid
    gangs: Dict[str, List[object]]      # pod_group -> bound members


class Strategy:
    """One drift detector. ``candidates`` returns bound pods worth
    rescoring — detection only; the what-if matrix decides."""

    name = "strategy"
    must_move = False

    def candidates(self, snap: Snapshot) -> List[object]:
        raise NotImplementedError


class LowNodeUtilization(Strategy):
    """Spread repair: nodes whose cpu-request utilization sits more than
    ``margin`` above the cluster mean nominate their largest pods
    (largest first converges the stddev fastest; ties break by uid so
    two managers nominate identically)."""

    name = "low-node-utilization"

    def __init__(self, margin: float = 0.10, per_node: int = 4):
        self.margin = float(margin)
        self.per_node = int(per_node)

    def candidates(self, snap: Snapshot) -> List[object]:
        utils = []
        for ni in snap.node_infos:
            cap = ni.allocatable.milli_cpu
            utils.append(ni.requested.milli_cpu / cap if cap > 0 else 0.0)
        if not utils:
            return []
        mean = sum(utils) / len(utils)
        out: List[object] = []
        for ni, u in zip(snap.node_infos, utils):
            if u <= mean + self.margin:
                continue
            pods = sorted((pi.pod for pi in ni.pods),
                          key=lambda p: (-p.resource_request().milli_cpu,
                                         p.uid))
            out.extend(pods[:self.per_node])
        return out


class DuplicateReplicas(Strategy):
    """A workload's replicas co-located on one node defeat the point of
    replication (reference RemoveDuplicates): for each (node, owner)
    group keep the lowest-uid member, nominate the rest."""

    name = "duplicate-replicas"

    def candidates(self, snap: Snapshot) -> List[object]:
        groups: Dict[tuple, List[object]] = {}
        for pod in snap.bound:
            owner = (pod.labels or {}).get(OWNER_LABEL) \
                or (pod.labels or {}).get("app")
            if owner:
                groups.setdefault((pod.node_name, owner), []).append(pod)
        out: List[object] = []
        for members in groups.values():
            if len(members) > 1:
                out.extend(sorted(members, key=lambda p: p.uid)[1:])
        return out


class TaintViolation(Strategy):
    """Churn moved the ground truth: the node a pod is bound to now
    carries a NoSchedule/NoExecute taint the pod does not tolerate.
    The seat is illegal, so the hysteresis floor is waived — any
    feasible landing beats staying."""

    name = "taint-violation"
    must_move = True

    def candidates(self, snap: Snapshot) -> List[object]:
        from ..api.types import find_matching_untolerated_taint

        out: List[object] = []
        for ni in snap.node_infos:
            if ni.node is None or not ni.node.taints:
                continue
            for pi in ni.pods:
                if find_matching_untolerated_taint(
                        ni.node.taints, pi.pod.tolerations) is not None:
                    out.append(pi.pod)
        return out


def default_strategies(margin: float = 0.10) -> List[Strategy]:
    return [TaintViolation(), DuplicateReplicas(),
            LowNodeUtilization(margin=margin)]


class _Plan(NamedTuple):
    pod: object
    strategy: str
    improvement: int


class DeschedulerController:
    """The descheduler process body: HA lease tick → snapshot → detect →
    one what-if batch → gang-whole hysteresis-gated planning → the
    PR-16 eviction funnel. Single reconcile thread; tests drive
    ``tick_once`` directly."""

    def __init__(self, clientset, identity: str = "descheduler-0",
                 lease_ttl: float = 2.0, tick: float = 0.25,
                 hysteresis: int = 5,
                 strategies: Optional[Sequence[Strategy]] = None,
                 primary_qps: float = 20.0, secondary_qps: float = 0.1,
                 unhealthy_threshold: float = 0.55, burst: float = 8.0,
                 max_moves_per_tick: int = 64, device: bool = False,
                 now: Callable[[], float] = time.monotonic):
        self.cs = clientset
        self.identity = identity
        self.lease_ttl = float(lease_ttl)
        self.tick = float(tick)
        self.hysteresis = int(hysteresis)
        self.strategies = list(strategies if strategies is not None
                               else default_strategies())
        self.max_moves_per_tick = int(max_moves_per_tick)
        self.device = bool(device)
        self._now = now
        self.evictor = RateLimitedEvictor(
            clientset, primary_qps=primary_qps, secondary_qps=secondary_qps,
            unhealthy_threshold=unhealthy_threshold, burst=burst, now=now)
        self.active = False
        self.ticks = 0
        self.active_ticks = 0
        self.standby_ticks = 0
        self.takeovers = 0
        self.lease_errors = 0
        self.moves_total: Dict[str, int] = {
            s.name: 0 for s in self.strategies}
        self.blocked_total: Dict[str, int] = {r: 0 for r in BLOCK_REASONS}
        self.no_target = 0          # nominee with no feasible other row
        self.whatif_batches = 0
        self.whatif_seconds = 0.0
        self.drift: Dict[str, int] = {s.name: 0 for s in self.strategies}
        # uid -> deterministic uid@node intent, as planned. Two managers
        # over one snapshot build identical maps — the chaos suite's
        # takeover assertion reads this seam.
        self.planned_intents: Dict[str, str] = {}
        self.util_stddev_milli = 0  # last measured cpu-util stddev x1000
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the HA tick ---------------------------------------------------------

    def tick_once(self) -> None:
        self.ticks += 1
        try:
            got = self.cs.upsert_lease(MANAGER_LEASE, self.identity,
                                       self.lease_ttl)
        except Exception:  # noqa: BLE001 - leader churn mid-failover
            self.lease_errors += 1
            got = None
        if got is None:
            self.active = False
            self.standby_ticks += 1
            return
        if not self.active:
            self.takeovers += 1
            self.active = True
        self.active_ticks += 1
        try:
            self.reconcile_once()
        except Exception:  # noqa: BLE001 - transient read-plane races
            self.errors += 1

    # -- snapshot ------------------------------------------------------------

    def _snapshot(self) -> Snapshot:
        nodes = sorted(self.cs.nodes.values(), key=lambda n: n.name)
        infos = [NodeInfo(n) for n in nodes]
        row = {ni.name: i for i, ni in enumerate(infos)}
        bound = sorted(
            (p for p in self.cs.pods.values()
             if p.node_name in row and p.deletion_ts is None),
            key=lambda p: p.uid)
        gangs: Dict[str, List[object]] = {}
        for p in bound:
            infos[row[p.node_name]].add_pod(PodInfo.of(p))
            if p.pod_group:
                gangs.setdefault(p.pod_group, []).append(p)
        return Snapshot(infos, row, bound, gangs)

    @staticmethod
    def _util_stddev_milli(snap: Snapshot) -> int:
        utils = [ni.requested.milli_cpu / ni.allocatable.milli_cpu
                 for ni in snap.node_infos if ni.allocatable.milli_cpu > 0]
        if not utils:
            return 0
        mean = sum(utils) / len(utils)
        var = sum((u - mean) ** 2 for u in utils) / len(utils)
        return int(var ** 0.5 * 1000)

    # -- one reconcile pass --------------------------------------------------

    def reconcile_once(self) -> int:
        """Detect → score → plan → emit. Returns moves enqueued."""
        snap = self._snapshot()
        self.util_stddev_milli = self._util_stddev_milli(snap)
        nominated: Dict[str, str] = {}   # uid -> strategy (first wins)
        by_uid: Dict[str, object] = {}
        must: Dict[str, bool] = {}
        for strat in self.strategies:
            found = strat.candidates(snap)
            self.drift[strat.name] = len(found)
            for pod in found:
                if pod.uid not in nominated:
                    nominated[pod.uid] = strat.name
                    by_uid[pod.uid] = pod
                    must[pod.uid] = strat.must_move
        # gang-whole expansion: a nominated member drags every bound
        # member of its PodGroup into the batch under the same strategy.
        for uid in list(nominated):
            pod = by_uid[uid]
            if pod.pod_group:
                for member in snap.gangs.get(pod.pod_group, ()):
                    if member.uid not in nominated:
                        nominated[member.uid] = nominated[uid]
                        by_uid[member.uid] = member
                        must[member.uid] = must[uid]
        if not nominated:
            return 0
        candidates = sorted(by_uid.values(), key=lambda p: p.uid)
        # batch cap: 2x the per-tick move budget leaves headroom for
        # hysteresis/gang rejections without unbounded matrix growth
        candidates = candidates[:self.max_moves_per_tick * 2]
        kept = {p.uid for p in candidates}
        t0 = self._now()
        batch = whatif.encode_batch(snap.node_infos, candidates)
        fit_ok, score = whatif.whatif_scores(batch, device=self.device)
        moves = whatif.best_moves(batch, fit_ok, score)
        self.whatif_batches += 1
        self.whatif_seconds += max(0.0, self._now() - t0)
        plans: List[_Plan] = []
        gang_plans: Dict[str, List[Optional[_Plan]]] = {}
        for pod, move in zip(candidates, moves):
            strat = nominated[pod.uid]
            plan = None
            if move is None:
                self.no_target += 1
            elif clears_hysteresis(move.improvement, self.hysteresis,
                                   must[pod.uid]):
                plan = _Plan(pod, strat, move.improvement)
            else:
                self.blocked_total["hysteresis"] += 1
            if pod.pod_group:
                gang_plans.setdefault(pod.pod_group, []).append(plan)
            elif plan is not None:
                plans.append(plan)
        # gang-whole: every bound member must hold a qualifying landing,
        # and the whole gang must be in this batch — else nothing moves.
        for gang, gplans in gang_plans.items():
            members = snap.gangs.get(gang, ())
            whole = (len(gplans) == len(members)
                     and all(m.uid in kept for m in members)
                     and all(p is not None for p in gplans))
            if whole:
                plans.extend(gplans)
            else:
                self.blocked_total["gang"] += 1
        emitted = 0
        for plan in plans[:self.max_moves_per_tick]:
            if self._emit(plan, snap):
                emitted += 1
        self.evictor.run_once()
        # server-side gates observed through the funnel's own counters
        self.blocked_total["pdb"] = self.evictor.evictions_budget_blocked
        self.blocked_total["budget"] = self.evictor.evictions_throttled_total
        return emitted

    def _emit(self, plan: _Plan, snap: Snapshot) -> bool:
        """One approved move into the funnel. The intent the server will
        ledger is minted here — deterministic ``uid@node`` — purely for
        the plan's observability seam; `RateLimitedEvictor._evict_one`
        mints the identical id when the token grants."""
        pod = plan.pod
        node = pod.node_name
        self.planned_intents[pod.uid] = intent_for(pod.uid, node)
        ni = snap.node_infos[snap.row[node]]
        zone = (ni.node.labels or {}).get(ZONE_LABEL, "") if ni.node else ""
        if self.evictor.enqueue(zone, node, pod.uid):
            self.moves_total[plan.strategy] = (
                self.moves_total.get(plan.strategy, 0) + 1)
            return True
        return False

    # -- standing loop -------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="descheduler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick_once()
            if self._stop.wait(self.tick):
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        ev = self.evictor
        return {"identity": self.identity, "active": self.active,
                "ticks": self.ticks, "active_ticks": self.active_ticks,
                "standby_ticks": self.standby_ticks,
                "takeovers": self.takeovers,
                "lease_errors": self.lease_errors,
                "moves": dict(self.moves_total),
                "blocked": dict(self.blocked_total),
                "no_target": self.no_target,
                "planned_intents": dict(self.planned_intents),
                "whatif_batches": self.whatif_batches,
                "whatif_seconds": round(self.whatif_seconds, 6),
                "drift": dict(self.drift),
                "util_stddev_milli": self.util_stddev_milli,
                "errors": self.errors,
                "evictions_total": ev.evictions_total,
                "evictions_replayed": ev.evictions_replayed,
                "evictions_cancelled": ev.evictions_cancelled,
                "eviction_errors": ev.eviction_errors,
                "pending_evictions": ev.pending_count()}

    def metrics_text(self) -> str:
        out = ["# TYPE descheduler_moves_total counter"]
        for strat, v in sorted(self.moves_total.items()):
            out.append(f'descheduler_moves_total{{strategy="{strat}"}} {v}')
        out.append("# TYPE descheduler_moves_blocked_total counter")
        for reason in BLOCK_REASONS:
            out.append(f'descheduler_moves_blocked_total'
                       f'{{reason="{reason}"}} '
                       f'{self.blocked_total.get(reason, 0)}')
        out.append(
            "# TYPE descheduler_whatif_batch_duration_seconds summary")
        out.append(f"descheduler_whatif_batch_duration_seconds_sum "
                   f"{self.whatif_seconds:.6f}")
        out.append(f"descheduler_whatif_batch_duration_seconds_count "
                   f"{self.whatif_batches}")
        out.append("# TYPE descheduler_drift_candidates gauge")
        for strat, v in sorted(self.drift.items()):
            out.append(
                f'descheduler_drift_candidates{{strategy="{strat}"}} {v}')
        for name, v in (
                ("descheduler_ticks_total", self.ticks),
                ("descheduler_takeovers_total", self.takeovers),
                ("descheduler_lease_errors_total", self.lease_errors),
                ("descheduler_evictions_total",
                 self.evictor.evictions_total),
                ("descheduler_evictions_replayed_total",
                 self.evictor.evictions_replayed)):
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {v}")
        out.append("# TYPE descheduler_util_stddev_milli gauge")
        out.append(f"descheduler_util_stddev_milli {self.util_stddev_milli}")
        out.append("# TYPE descheduler_manager_active gauge")
        out.append(f"descheduler_manager_active {int(self.active)}")
        return "\n".join(out) + "\n"
