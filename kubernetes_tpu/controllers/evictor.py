"""Rate-limited, zone-aware pod eviction (NodeLifecycleController's
RateLimitedTimedQueue + DisruptionState, upstream node_lifecycle_controller.go).

Every eviction leaves through ONE funnel: `run_once` takes a token from the
zone's bucket (the rate limiter) and `_evict_one` stamps the deterministic
intent id (the idempotency record) before calling the apiserver's eviction
subresource. The analyzer's `eviction-discipline` rule pins this shape — a
pod delete/evict call site in controllers/ must sit on a call-graph slice
containing both the limiter and the intent record.

Zone disruption states (upstream's large-cluster semantics): a zone whose
unhealthy fraction crosses `unhealthy_threshold` drops to the SECONDARY
eviction rate (partial disruption); a fully-unhealthy zone stops evicting
entirely (full disruption) — a partitioned hollow plane, or a dead network
segment, must never trigger a mass-eviction storm for what is probably an
infrastructure failure, not 500 simultaneous node deaths.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

ZONE_NORMAL = "Normal"
ZONE_PARTIAL = "PartialDisruption"
ZONE_FULL = "FullDisruption"

# Deleted-node pod GC drains through this reserved queue at the primary
# rate, always: its source node no longer EXISTS, so no zone census can
# legitimately brake it. The "/" makes the key impossible as a
# topology.kubernetes.io/zone label VALUE (label values reject "/"), so a
# health census can never collide with — and throttle — the GC funnel;
# set_zone_state refuses the key outright as a second line of defense.
GC_ZONE = "gc/deleted-node"


class TokenBucket:
    """Eviction token bucket (flowcontrol.NewTokenBucketRateLimiter).
    Injectable clock so the unit suite drives it without sleeps; a rate
    change (zone state transition) keeps the accumulated balance, capped
    at the new burst — upstream's SwapLimiter semantics."""

    def __init__(self, qps: float, burst: float = 1.0,
                 now: Callable[[], float] = time.monotonic):
        self._now = now
        self._qps = max(0.0, float(qps))
        self._burst = max(1.0, float(burst))
        self._tokens = self._burst
        self._last = now()

    @property
    def qps(self) -> float:
        return self._qps

    def set_rate(self, qps: float) -> None:
        self._refill()
        self._qps = max(0.0, float(qps))

    def _refill(self) -> None:
        t = self._now()
        self._tokens = min(self._burst,
                           self._tokens + (t - self._last) * self._qps)
        self._last = t

    def try_take(self) -> bool:
        """One eviction token, non-blocking. A zero-qps bucket (full
        disruption) never grants — its balance was spent or capped and
        refills at 0/s."""
        if self._qps <= 0.0:
            self._last = self._now()
            self._tokens = 0.0
            return False
        self._refill()
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


def intent_for(uid: str, node: str) -> str:
    """Deterministic eviction intent id: (pod, planned source node).
    Deterministic is what makes restart replay exactly-once WITHOUT any
    controller-local persistence — a restarted controller re-plans the
    same wave, mints the same ids, and the apiserver's WAL'd ledger
    answers the already-done ones with already=True."""
    return f"{uid}@{node}"


class RateLimitedEvictor:
    """Per-zone token-bucket eviction queues. Thread-safe: the lifecycle
    reconcile loop enqueues/cancels while tests (or the metrics surface)
    read counters."""

    def __init__(self, clientset, primary_qps: float = 2.0,
                 secondary_qps: float = 0.1,
                 unhealthy_threshold: float = 0.55,
                 burst: float = 1.0,
                 now: Callable[[], float] = time.monotonic):
        self.cs = clientset
        self.primary_qps = float(primary_qps)
        self.secondary_qps = float(secondary_qps)
        self.unhealthy_threshold = float(unhealthy_threshold)
        self._burst = float(burst)
        self._now = now
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._pending: Dict[str, deque] = {}   # zone -> deque[(node, uid)]
        # uid -> (zone, node): dedupe/cancel, and the zone a transport-
        # failure retry re-enqueues into (losing it would drain the retry
        # through the wrong bucket, bypassing a disrupted zone's brake).
        self._queued: Dict[str, Tuple[str, str]] = {}
        self.zone_states: Dict[str, str] = {}
        self.evictions_total = 0
        self.evictions_throttled_total = 0
        self.evictions_replayed = 0   # server answered already=True
        self.evictions_cancelled = 0  # taint lift / pod moved / pod gone
        self.eviction_errors = 0      # transient failures (retried next tick)
        self.evictions_budget_blocked = 0  # PDB 429s (requeued, retried)

    # -- zone disruption state machine --------------------------------------

    def set_zone_state(self, zone: str, unhealthy: int, total: int) -> str:
        """Fold one zone's health census into its eviction rate. Returns
        the state name (observability + tests). The reserved GC queue is
        not a zone: it never slows down, whatever a census claims."""
        if zone == GC_ZONE:
            return ZONE_NORMAL
        frac = (unhealthy / total) if total > 0 else 0.0
        if total > 0 and unhealthy >= total:
            state, qps = ZONE_FULL, 0.0
        elif frac > self.unhealthy_threshold:
            state, qps = ZONE_PARTIAL, self.secondary_qps
        else:
            state, qps = ZONE_NORMAL, self.primary_qps
        with self._lock:
            self.zone_states[zone] = state
            bucket = self._buckets.get(zone)
            if bucket is None:
                self._buckets[zone] = TokenBucket(
                    qps, burst=self._burst, now=self._now)
            elif bucket.qps != qps:
                bucket.set_rate(qps)
        return state

    # -- queue management ----------------------------------------------------

    def enqueue(self, zone: str, node: str, uid: str) -> bool:
        """Queue one pod for eviction off `node`. Deduplicated by uid —
        the reconcile loop re-plans every tick and must not stack
        duplicate work."""
        with self._lock:
            if uid in self._queued:
                return False
            self._queued[uid] = (zone, node)
            if zone not in self._buckets:
                self._buckets[zone] = TokenBucket(
                    self.primary_qps, burst=self._burst, now=self._now)
            self._pending.setdefault(zone, deque()).append((node, uid))
            return True

    def cancel_node(self, node: str) -> int:
        """Drop every pending eviction planned off `node` — the taint
        lifted (node heartbeats again) mid-wave, so its still-queued pods
        must NOT be evicted."""
        dropped = 0
        with self._lock:
            for zone, q in self._pending.items():
                kept = [(n, u) for (n, u) in q if n != node]
                dropped += len(q) - len(kept)
                self._pending[zone] = deque(kept)
            for uid in [u for u, (_z, n) in self._queued.items()
                        if n == node]:
                del self._queued[uid]
            self.evictions_cancelled += dropped
        return dropped

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._pending.values())

    # -- the eviction funnel -------------------------------------------------

    def run_once(self) -> int:
        """Drain each zone's queue as far as its token bucket allows.
        Returns evictions committed this pass. A zone with work but no
        token counts one throttle observation (the `_throttled_total`
        series the zone-outage chaos scenario asserts). Each zone's drain
        is bounded to the items pending at pass start: a transport-failed
        eviction re-enqueues at the tail and waits for the NEXT reconcile
        (retrying inside the same pass would spin tokens against a dead
        wire)."""
        done = 0
        with self._lock:
            budget = {z: len(q) for z, q in self._pending.items() if q}
        for zone, n in budget.items():
            for _ in range(n):
                with self._lock:
                    q = self._pending.get(zone)
                    if not q:
                        break
                    if not self._buckets[zone].try_take():
                        self.evictions_throttled_total += 1
                        break
                    node, uid = q.popleft()
                    self._queued.pop(uid, None)
                if self._evict_one(zone, node, uid):
                    done += 1
        return done

    def _evict_one(self, zone: str, node: str, uid: str) -> bool:
        """One rate-limit-granted eviction: deterministic intent, then the
        idempotent subresource. Every terminal server answer (evicted /
        already / pending / mismatch / gone) resolves this pod's work;
        only a transport failure re-queues it — into its ORIGINAL zone,
        so the retry still pays that zone's (possibly disrupted) rate."""
        from urllib.error import HTTPError

        intent = intent_for(uid, node)
        try:
            got = self.cs.evict_pod(uid, node, intent) or {}
        except HTTPError as e:
            if e.code == 404:
                self.evictions_cancelled += 1  # pod gone: nothing to evict
                return False
            if e.code == 409:
                # NodeMismatch (pod moved since the plan) or finalizer
                # parked — either way this plan is stale, not retryable.
                self.evictions_cancelled += 1
                return False
            if e.code == 429:
                # DisruptionBudget: committing this eviction would take a
                # workload below its PDB's minAvailable. NOT stale and NOT
                # an error — re-queue into the ORIGINAL zone and retry
                # after the workload controller has healed the slack.
                self.evictions_budget_blocked += 1
                self.enqueue(zone, node, uid)
                return False
            self.eviction_errors += 1
            return False
        except Exception:  # noqa: BLE001 - transport: retry next tick
            self.eviction_errors += 1
            self.enqueue(zone, node, uid)
            return False
        if got.get("already"):
            self.evictions_replayed += 1
            return False
        if got.get("evicted"):
            self.evictions_total += 1
            return True
        self.evictions_cancelled += 1  # pending=True: already unbound
        return False
