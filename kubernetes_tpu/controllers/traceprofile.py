"""Borg/Alibaba-style trace marginals for the workload controllers.

The cluster-trace literature (Borg 2015/2019, Alibaba v2018) agrees on a
small set of robust marginals rather than any replayable event log:
arrivals are well-modeled as Poisson (exponential interarrival at a
configured rate), job lifetimes are heavy-tailed (approximated here by an
exponential with a floor — most jobs short, a fat tail of long-runners),
and replica counts skew hard toward small jobs (the majority of Borg
allocs are <4 tasks) with a thin tail of wide gangs. This module encodes
exactly those marginals as a declarative, seeded profile: `specs()`
expands the distributions into a deterministic arrival schedule of
deployment + gang specs that the workload controller-manager feeds
through the REAL API surface (deployments/replicasets over the wire,
PodGroups + members for gangs). Determinism matters the same way it does
for `HollowProfile`: a chaos scenario replays the same workload from the
profile alone and can assert exact convergence counts.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import List


@dataclass(frozen=True)
class WorkloadProfile:
    """Declarative workload-arrival marginals (all draws seeded)."""

    deployments: int = 4
    gangs: int = 2
    # Poisson arrivals: exponential interarrival at this rate (per second).
    arrival_rate: float = 2.0
    # Exponential lifetime with a floor; <= 0 means workloads run forever.
    mean_lifetime_s: float = 0.0
    min_lifetime_s: float = 5.0
    # Replica-count marginal (Borg-style small-job skew).
    replica_choices: tuple = (1, 2, 3, 5, 8)
    replica_weights: tuple = (30, 25, 20, 15, 10)
    # Gang-width marginal.
    gang_sizes: tuple = (2, 4, 8)
    gang_weights: tuple = (50, 35, 15)
    # Per-replica cpu request marginal (milli-cores).
    cpu_milli_choices: tuple = (100, 250, 500)
    cpu_milli_weights: tuple = (60, 30, 10)
    # Rolling-update bounds stamped on every minted deployment.
    max_surge: int = 1
    max_unavailable: int = 1
    seed: int = 0
    name_prefix: str = "trace"

    def specs(self) -> List[dict]:
        """Expand the marginals into a deterministic arrival schedule:
        one dict per workload, sorted by arrival time. Deployments and
        gangs draw from ONE interleaved arrival process (they share the
        rate) but from per-field marginals."""
        rng = random.Random(self.seed or 0xB026)
        out: List[dict] = []
        t = 0.0
        kinds = (["deployment"] * self.deployments) + (["gang"] * self.gangs)
        rng.shuffle(kinds)
        dep_i = gang_i = 0
        for kind in kinds:
            t += rng.expovariate(max(1e-9, self.arrival_rate))
            if self.mean_lifetime_s > 0:
                life = max(self.min_lifetime_s,
                           rng.expovariate(1.0 / self.mean_lifetime_s))
            else:
                life = math.inf
            cpu = rng.choices(self.cpu_milli_choices,
                              self.cpu_milli_weights)[0]
            if kind == "deployment":
                out.append({
                    "kind": "deployment",
                    "name": f"{self.name_prefix}-dep-{dep_i}",
                    "arrival": t, "lifetime": life,
                    "replicas": rng.choices(self.replica_choices,
                                            self.replica_weights)[0],
                    "cpuMilli": cpu,
                    "maxSurge": self.max_surge,
                    "maxUnavailable": self.max_unavailable})
                dep_i += 1
            else:
                out.append({
                    "kind": "gang",
                    "name": f"{self.name_prefix}-gang-{gang_i}",
                    "arrival": t, "lifetime": life,
                    "size": rng.choices(self.gang_sizes,
                                        self.gang_weights)[0],
                    "cpuMilli": cpu})
                gang_i += 1
        return out
