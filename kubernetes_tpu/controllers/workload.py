"""Self-healing workload plane: ReplicaSet/Deployment + gang controllers
with HA leader election (replicaset.go / deployment_controller.go /
the fork's gang admission collapsed into native reconcile loops).

Three invariants carry the whole module, and the analyzer's
`reconcile-discipline` rule pins the first two in source:

1. **Deterministic pod names.** Every pod a controller mints is named by
   a pure function of (owner, revision, ordinal) — `replica_name` /
   `gang_member_name` — and its uid IS its name. Two controller-manager
   processes racing the same desired state therefore race toward the
   SAME creates.
2. **Create-409-is-success.** All pod creates leave through one seam,
   `_create_pod`, which treats 409 AlreadyExists as "the other actor (or
   my previous incarnation) already did this" — not an error. (1) + (2)
   together give exactly-once creates across kill9 failover with zero
   controller-local persistence, the same construction the eviction
   plane gets from deterministic intent ids + the WAL'd ledger.
3. **Voluntary deletes pay the PDB toll.** Scale-downs and rolling-
   update drains leave through `delete_pod_voluntary`, whose server-side
   precondition (429 DisruptionBudget) refuses any delete that would
   take a selector's BOUND count below minAvailable. A blocked delete is
   simply retried next tick, after self-healing has restored slack.

Leader election: both manager processes PUT-CAS the shared
`workload-controller-manager` lease every tick; the CAS loser runs
STANDBY (informers warm, reconcilers idle) and takes over inside the
lease TTL when the ACTIVE holder dies.
"""

from __future__ import annotations

import hashlib
import re
import threading
import time
from typing import Callable, Dict, List, Optional
from urllib.error import HTTPError

from ..api.resource import Resource
from ..api.types import Container, Pod, PodGroup
from .autoscaler import ClusterAutoscaler
from .traceprofile import WorkloadProfile

# Ownership travels as labels (ownerReferences flattened): a pod's
# controlling ReplicaSet, and — transitively — its Deployment.
OWNER_LABEL = "replicaset.kubernetes.io/name"
DEPLOY_LABEL = "deployment.kubernetes.io/name"
GANG_LABEL = "gang.kubernetes.io/name"
MANAGER_LEASE = "workload-controller-manager"

_MEMBER_RE = re.compile(r"^(?P<gang>.+)-r(?P<r>\d+)-m(?P<i>\d+)$")


def replica_name(rs: str, revision: int, ordinal: int) -> str:
    """Deterministic replica pod name: <rs>-<sha1(revision:ordinal)[:10]>.
    Pure in (rs, revision, ordinal), so any two reconcilers — or one
    reconciler before and after a kill9 — mint identical names for
    identical desired state; the create-409-is-success seam then
    collapses their races into exactly-once creates."""
    h = hashlib.sha1(f"{revision}:{ordinal}".encode()).hexdigest()[:10]
    return f"{rs}-{h}"


def gang_member_name(gang: str, incarnation: int, ordinal: int) -> str:
    """Deterministic gang member name (incarnation = whole-gang restart
    counter; parseable so a takeover re-derives the census from live
    pods alone)."""
    return f"{gang}-r{incarnation}-m{ordinal}"


def _create_pod(cs, pod: Pod) -> bool:
    """THE create seam: every controller pod create funnels through here.
    409 AlreadyExists means some actor already made this exact pod
    (deterministic names make the collision semantic, not accidental) —
    success, not error. Returns True only for a fresh create."""
    try:
        cs.create_pod(pod)
        return True
    except HTTPError as e:
        if e.code == 409:
            return False
        raise


def _template_pod(name: str, namespace: str, labels: Dict[str, str],
                  cpu_milli: int, pod_group: str = "") -> Pod:
    containers = []
    if cpu_milli:
        containers.append(Container(
            name="main", requests=Resource(milli_cpu=int(cpu_milli))))
    return Pod(name=name, namespace=namespace or "default", uid=name,
               labels=dict(labels), containers=containers,
               pod_group=pod_group)


class ReplicaSetController:
    """Reconcile `replicasets` wire objects against live pods.

    Desired: the replica_name set for (name, revision, replicas).
    Missing members are created (self-healing: a chaos-killed pod's name
    reappears in the want-set and is re-minted next tick); surplus
    members — revision skew after a rolling step, or a scale-down —
    drain via voluntary deletes, each subject to the server's PDB
    precondition."""

    def __init__(self, clientset):
        self.cs = clientset
        self.pods_created = 0
        self.creates_409 = 0
        self.pods_deleted = 0
        self.deletes_blocked = 0
        self.errors = 0

    def reconcile_once(self) -> None:
        for rs in list(self.cs.workloads["replicasets"].values()):
            try:
                self._reconcile_rs(rs)
            except Exception:  # noqa: BLE001 - transient: retry next tick
                self.errors += 1

    def _owned(self, name: str) -> Dict[str, Pod]:
        return {p.uid: p for p in self.cs.pods.values()
                if p.labels.get(OWNER_LABEL) == name
                and p.deletion_ts is None}

    def _reconcile_rs(self, rs: dict) -> None:
        name = rs["name"]
        ns = rs.get("namespace") or "default"
        revision = int(rs.get("revision") or 0)
        replicas = max(0, int(rs.get("replicas") or 0))
        owned = self._owned(name)
        want = {replica_name(name, revision, i) for i in range(replicas)}
        template = rs.get("template") or {}
        labels = dict(template.get("labels") or {})
        labels[OWNER_LABEL] = name
        if rs.get("deployment"):
            labels[DEPLOY_LABEL] = rs["deployment"]
        for pod_name in sorted(want - owned.keys()):
            pod = _template_pod(pod_name, ns, labels,
                                int(template.get("cpuMilli") or 0))
            if _create_pod(self.cs, pod):
                self.pods_created += 1
            else:
                self.creates_409 += 1
        for uid in sorted(owned.keys() - want):
            try:
                self.cs.delete_pod_voluntary(uid)
                self.pods_deleted += 1
            except HTTPError as e:
                if e.code == 429:
                    self.deletes_blocked += 1  # PDB: retry next tick
                elif e.code != 404:
                    raise

    def stats(self) -> dict:
        return {"pods_created": self.pods_created,
                "creates_409": self.creates_409,
                "pods_deleted": self.pods_deleted,
                "deletes_blocked": self.deletes_blocked,
                "errors": self.errors}


class DeploymentController:
    """Rolling updates: one ReplicaSet per (deployment, revision), scaled
    against each other under maxSurge/maxUnavailable.

    Per pass: the new-revision RS may grow to desired+maxSurge minus
    what older revisions still hold; older RSes shrink by at most the
    availability budget — BOUND pods above desired-maxUnavailable —
    so a rollout never dips a workload below its floor even before any
    PDB is consulted. Old RSes that reach zero with no owned pods are
    garbage-collected through the workload DELETE verb."""

    def __init__(self, clientset):
        self.cs = clientset
        self.rs_puts = 0
        self.rs_deleted = 0
        self.rollouts_completed = 0
        self.errors = 0
        self._done_revision: Dict[str, int] = {}

    def reconcile_once(self) -> None:
        deps = {d["name"] for d in
                self.cs.workloads["deployments"].values()}
        for rs in list(self.cs.workloads["replicasets"].values()):
            # Cascade: an RS whose owning deployment is gone (two-phase
            # expiry, or a reflector-lag re-PUT right after the delete)
            # drains to zero and is collected here — nothing else
            # iterates it anymore.
            if rs.get("deployment") and rs["deployment"] not in deps:
                try:
                    self._gc_orphan(rs)
                except Exception:  # noqa: BLE001 - retry next tick
                    self.errors += 1
        for dep in list(self.cs.workloads["deployments"].values()):
            try:
                self._reconcile_dep(dep)
            except Exception:  # noqa: BLE001 - transient: retry next tick
                self.errors += 1

    def _gc_orphan(self, rs: dict) -> None:
        if int(rs.get("replicas") or 0) != 0:
            self._put_rs(dict(rs, replicas=0))
        elif not any(p.labels.get(OWNER_LABEL) == rs["name"]
                     for p in self.cs.pods.values()):
            self.cs.delete_workload(
                "replicasets", rs.get("namespace") or "default",
                rs["name"])
            self.rs_deleted += 1

    def _rs_for(self, dep_name: str) -> List[dict]:
        return [rs for rs in self.cs.workloads["replicasets"].values()
                if rs.get("deployment") == dep_name]

    def _put_rs(self, rs: dict) -> None:
        self.cs.put_workload("replicasets", rs)
        self.rs_puts += 1

    def _reconcile_dep(self, dep: dict) -> None:
        name = dep["name"]
        ns = dep.get("namespace") or "default"
        desired = max(0, int(dep.get("replicas") or 0))
        revision = int(dep.get("revision") or 0)
        surge = max(0, int(dep.get("maxSurge", 1)))
        max_unavail = max(0, int(dep.get("maxUnavailable", 1)))
        new_name = f"{name}-{revision}"
        all_rs = self._rs_for(name)
        new_rs = next((r for r in all_rs if r["name"] == new_name), None)
        old_rs = [r for r in all_rs if r["name"] != new_name]
        old_total = sum(int(r.get("replicas") or 0) for r in old_rs)

        # Grow the new revision under the surge ceiling.
        allowed = desired + surge
        new_target = max(0, min(desired, allowed - old_total))
        if new_rs is None or int(new_rs.get("replicas") or 0) != new_target:
            self._put_rs({"name": new_name, "namespace": ns,
                          "deployment": name, "revision": revision,
                          "replicas": new_target,
                          "template": dict(dep.get("template") or {})})

        # Shrink old revisions by the availability budget: BOUND pods of
        # this deployment above the desired-maxUnavailable floor.
        available = sum(1 for p in self.cs.pods.values()
                        if p.labels.get(DEPLOY_LABEL) == name
                        and p.node_name and p.deletion_ts is None)
        budget = available - max(0, desired - max_unavail)
        for rs in sorted(old_rs, key=lambda r: r["name"]):
            cur = int(rs.get("replicas") or 0)
            if cur > 0 and budget > 0:
                step = min(cur, budget)
                budget -= step
                self._put_rs(dict(rs, replicas=cur - step))
            elif cur == 0 and not any(
                    p.labels.get(OWNER_LABEL) == rs["name"]
                    for p in self.cs.pods.values()):
                self.cs.delete_workload(
                    "replicasets", rs.get("namespace") or ns, rs["name"])
                self.rs_deleted += 1
        if (not old_rs and new_rs is not None
                and int(new_rs.get("replicas") or 0) == desired
                and self._done_revision.get(name) != revision):
            self._done_revision[name] = revision
            self.rollouts_completed += 1

    def stats(self) -> dict:
        return {"rs_puts": self.rs_puts, "rs_deleted": self.rs_deleted,
                "rollouts_completed": self.rollouts_completed,
                "errors": self.errors}


class GangController:
    """All-or-nothing gang lifecycle over the PodGroup surface.

    Each gang runs as incarnation `r`: members named
    `<gang>-r<r>-m<i>` with pod_group membership, minted through the
    same deterministic-name/409 seam as replicas. The protocol:

    - incomplete and never-seen-complete → still LAUNCHING: re-create
      missing members of the live incarnation (idempotent catch-up, the
      takeover path).
    - complete → record it; older-incarnation stragglers drain.
    - incomplete after having been observed complete → a member died:
      partial progress is worthless to a gang, so restart the WHOLE gang
      as incarnation r+1.

    The observed-complete damping (`_completed`) is what keeps reflector
    lag from spinning incarnations: a freshly-minted cohort that hasn't
    echoed back through the watch yet is "still launching", never
    "failed". Lost on failover, the new ACTIVE conservatively treats an
    incomplete gang as launching and converges by catch-up creates —
    exactly-once still holds because the names do not change.
    """

    def __init__(self, clientset):
        self.cs = clientset
        self.gangs: Dict[str, dict] = {}
        self._completed: Dict[str, int] = {}  # highest r SEEN complete
        self.pods_created = 0
        self.creates_409 = 0
        self.restarts = 0
        self.stragglers_deleted = 0
        self.errors = 0

    def set_gang(self, spec: dict) -> None:
        """Register/replace one gang spec: {name, size, minCount?,
        namespace?, cpuMilli?}."""
        self.gangs[spec["name"]] = dict(spec)

    def remove_gang(self, name: str) -> None:
        self.gangs.pop(name, None)
        self._completed.pop(name, None)

    def reconcile_once(self) -> None:
        for spec in list(self.gangs.values()):
            try:
                self._reconcile_gang(spec)
            except Exception:  # noqa: BLE001 - transient: retry next tick
                self.errors += 1

    def _ensure_group(self, spec: dict) -> None:
        ns = spec.get("namespace") or "default"
        if f"{ns}/{spec['name']}" in self.cs.pod_groups:
            return
        group = PodGroup(name=spec["name"], namespace=ns,
                         uid=f"pg-{spec['name']}",
                         min_count=int(spec.get("minCount")
                                       or spec.get("size") or 0))
        try:
            self.cs.create_pod_group(group)
        except HTTPError as e:
            if e.code != 409:  # someone (or my past self) won the race
                raise

    def _census(self, name: str) -> Dict[int, Dict[int, Pod]]:
        """Live members by incarnation -> ordinal, derived purely from
        deterministic names — survives any controller restart."""
        out: Dict[int, Dict[int, Pod]] = {}
        for p in self.cs.pods.values():
            if p.pod_group != name or p.deletion_ts is not None:
                continue
            m = _MEMBER_RE.match(p.name)
            if m is None or m.group("gang") != name:
                continue
            out.setdefault(int(m.group("r")), {})[int(m.group("i"))] = p
        return out

    def _mint(self, spec: dict, incarnation: int, ordinals) -> None:
        labels = {GANG_LABEL: spec["name"]}
        for i in sorted(ordinals):
            pod = _template_pod(
                gang_member_name(spec["name"], incarnation, i),
                spec.get("namespace") or "default", labels,
                int(spec.get("cpuMilli") or 0), pod_group=spec["name"])
            if _create_pod(self.cs, pod):
                self.pods_created += 1
            else:
                self.creates_409 += 1

    def _reconcile_gang(self, spec: dict) -> None:
        name, size = spec["name"], int(spec["size"])
        self._ensure_group(spec)
        cohorts = self._census(name)
        r_live = max(cohorts) if cohorts else 0
        live = cohorts.get(r_live, {})
        if len(live) >= size:
            self._completed[name] = max(self._completed.get(name, -1),
                                        r_live)
            # Stragglers of superseded incarnations drain voluntarily
            # (gangs carry no PDB; the verb stays uniform regardless).
            for r, members in cohorts.items():
                if r == r_live:
                    continue
                for p in members.values():
                    try:
                        self.cs.delete_pod_voluntary(p.uid)
                        self.stragglers_deleted += 1
                    except HTTPError as e:
                        if e.code not in (404, 429):
                            raise
            return
        if self._completed.get(name, -1) >= r_live:
            # Was whole at this (or a later) incarnation and now is not:
            # a member died. Partial gangs are worthless — restart whole.
            target = r_live + 1
            self.restarts += 1
            self._completed[name] = target - 1  # don't re-trip next tick
            self._mint(spec, target, range(size))
            return
        # Still launching r_live (or brand-new): catch-up creates only.
        self._mint(spec, r_live, set(range(size)) - live.keys())

    def stats(self) -> dict:
        return {"pods_created": self.pods_created,
                "creates_409": self.creates_409,
                "restarts": self.restarts,
                "stragglers_deleted": self.stragglers_deleted,
                "gangs": len(self.gangs), "errors": self.errors}


class WorkloadControllerManager:
    """Composes the workload reconcilers behind ONE HA lease.

    Every tick races `PUT-CAS /api/v1/leases/workload-controller-manager`;
    the winner runs ACTIVE (profile feed → deployments → replicasets →
    gangs → autoscaler), the loser idles STANDBY with warm informers.
    kill9 the ACTIVE and the standby's next CAS succeeds once the TTL
    lapses — takeover inside the lease TTL, and the deterministic-name
    construction makes its first ACTIVE pass converge exactly-once on
    whatever the dead incumbent half-finished."""

    def __init__(self, clientset, identity: str,
                 lease_ttl: float = 2.0, tick: float = 0.25,
                 autoscaler: Optional[ClusterAutoscaler] = None,
                 profile: Optional[WorkloadProfile] = None,
                 now: Callable[[], float] = time.monotonic):
        self.cs = clientset
        self.identity = identity
        self.lease_ttl = float(lease_ttl)
        self.tick = float(tick)
        self._now = now
        self.replicasets = ReplicaSetController(clientset)
        self.deployments = DeploymentController(clientset)
        self.gangs = GangController(clientset)
        self.autoscaler = autoscaler
        self.profile = profile
        self._specs = list(profile.specs()) if profile else []
        self._fed: Dict[str, dict] = {}
        self._expired: set = set()
        self._t0: Optional[float] = None
        self.active = False
        self.ticks = 0
        self.active_ticks = 0
        self.standby_ticks = 0
        self.takeovers = 0
        self.lease_errors = 0
        self.profile_fed = 0
        self.profile_expired = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the HA tick ---------------------------------------------------------

    def tick_once(self) -> None:
        self.ticks += 1
        try:
            got = self.cs.upsert_lease(MANAGER_LEASE, self.identity,
                                       self.lease_ttl)
        except Exception:  # noqa: BLE001 - leader churn mid-failover
            self.lease_errors += 1
            got = None
        if got is None:
            self.active = False
            self.standby_ticks += 1
            return
        if not self.active:
            self.takeovers += 1
            self.active = True
        self.active_ticks += 1
        self._feed_profile()
        self.deployments.reconcile_once()
        self.replicasets.reconcile_once()
        self.gangs.reconcile_once()
        if self.autoscaler is not None:
            self.autoscaler.reconcile_once()

    # -- trace-profile feed --------------------------------------------------

    def _feed_profile(self) -> None:
        if not self._specs:
            return
        if self._t0 is None:
            self._t0 = self._now()
        elapsed = self._now() - self._t0
        for spec in self._specs:
            name = spec["name"]
            if name not in self._fed and spec["arrival"] <= elapsed:
                self._admit(spec)
            elif (name in self._fed and name not in self._expired
                  and spec["arrival"] + spec["lifetime"] <= elapsed):
                self._retire(spec)

    def _admit(self, spec: dict) -> None:
        if spec["kind"] == "deployment":
            self.cs.put_workload("deployments", {
                "name": spec["name"], "namespace": "default",
                "replicas": spec["replicas"], "revision": 0,
                "maxSurge": spec["maxSurge"],
                "maxUnavailable": spec["maxUnavailable"],
                "template": {"labels": {"app": spec["name"]},
                             "cpuMilli": spec["cpuMilli"]}})
        else:
            self.gangs.set_gang({"name": spec["name"], "size": spec["size"],
                                 "cpuMilli": spec["cpuMilli"]})
        self._fed[spec["name"]] = spec
        self.profile_fed += 1

    def _retire(self, spec: dict) -> None:
        """Two-phase expiry. Deployments: scale to zero first (the
        reconcilers drain pods through the voluntary/PDB path), then
        delete the deployment + its ReplicaSets once nothing is owned.
        Gangs: members drain voluntarily, then the spec deregisters (the
        PodGroup record stays — the server has no delete verb for it,
        and an empty group schedules nothing)."""
        name = spec["name"]
        if spec["kind"] == "deployment":
            dep = self.cs.workloads["deployments"].get(f"default/{name}")
            if dep is None:
                self._expired.add(name)
                return
            if int(dep.get("replicas") or 0) != 0:
                self.cs.put_workload("deployments", dict(dep, replicas=0))
                return
            if any(p.labels.get(DEPLOY_LABEL) == name
                   for p in self.cs.pods.values()):
                return  # still draining
            for rs in self.deployments._rs_for(name):
                self.cs.delete_workload(
                    "replicasets", rs.get("namespace") or "default",
                    rs["name"])
            self.cs.delete_workload("deployments", "default", name)
        else:
            members = [p for p in self.cs.pods.values()
                       if p.pod_group == name and p.deletion_ts is None]
            if members:
                self.gangs.remove_gang(name)  # stop re-minting first
                for p in members:
                    try:
                        self.cs.delete_pod_voluntary(p.uid)
                    except HTTPError as e:
                        if e.code not in (404, 429):
                            raise
                return
            self.gangs.remove_gang(name)
        self._expired.add(name)
        self.profile_expired += 1

    # -- standing loop -------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="workload-manager", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick_once()
            if self._stop.wait(self.tick):
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        out = {"identity": self.identity, "active": self.active,
               "ticks": self.ticks, "active_ticks": self.active_ticks,
               "standby_ticks": self.standby_ticks,
               "takeovers": self.takeovers,
               "lease_errors": self.lease_errors,
               "profile_fed": self.profile_fed,
               "profile_expired": self.profile_expired,
               "replicasets": self.replicasets.stats(),
               "deployments": self.deployments.stats(),
               "gangs": self.gangs.stats()}
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        return out

    def metrics_text(self) -> str:
        rs, dep, g = (self.replicasets, self.deployments, self.gangs)
        series = [
            ("workload_manager_ticks_total", self.ticks),
            ("workload_manager_takeovers_total", self.takeovers),
            ("workload_manager_lease_errors_total", self.lease_errors),
            ("workload_replicaset_pods_created_total", rs.pods_created),
            ("workload_replicaset_creates_409_total", rs.creates_409),
            ("workload_replicaset_pods_deleted_total", rs.pods_deleted),
            ("workload_replicaset_deletes_blocked_total",
             rs.deletes_blocked),
            ("workload_deployment_rs_puts_total", dep.rs_puts),
            ("workload_deployment_rollouts_completed_total",
             dep.rollouts_completed),
            ("workload_gang_pods_created_total", g.pods_created),
            ("workload_gang_restarts_total", g.restarts),
        ]
        if self.autoscaler is not None:
            a = self.autoscaler
            series += [("workload_autoscaler_nodes_added_total",
                        a.nodes_added),
                       ("workload_autoscaler_nodes_removed_total",
                        a.nodes_removed)]
        out = []
        for name, v in series:
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {v}")
        out.append("# TYPE workload_manager_active gauge")
        out.append(f"workload_manager_active {int(self.active)}")
        return "\n".join(out) + "\n"
