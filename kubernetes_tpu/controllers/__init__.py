"""Native controller plane (kube-controller-manager analogue, PAPER.md L4).

Residents:

- node-lifecycle controller — lease/heartbeat-driven health, the
  taint-on-unready ladder, rate-limited zone-aware eviction, pod GC
  (docs/RESILIENCE.md § node lifecycle);
- workload controller-manager — ReplicaSet/Deployment reconcile +
  rolling updates, gang lifecycle over PodGroups, cluster autoscaler,
  Borg-style trace-profile feed, all behind one HA PUT-CAS lease
  (docs/RESILIENCE.md § workload controllers).

Both run as their own processes: ``python -m kubernetes_tpu.controllers
--mode {node-lifecycle,workload} --api-url ...`` against the real
apiserver via HTTPClientset.
"""

from .autoscaler import ClusterAutoscaler
from .evictor import RateLimitedEvictor, TokenBucket
from .node_lifecycle import NodeLifecycleController
from .traceprofile import WorkloadProfile
from .workload import (
    DeploymentController,
    GangController,
    ReplicaSetController,
    WorkloadControllerManager,
    gang_member_name,
    replica_name,
)

__all__ = [
    "ClusterAutoscaler",
    "DeploymentController",
    "GangController",
    "NodeLifecycleController",
    "RateLimitedEvictor",
    "ReplicaSetController",
    "TokenBucket",
    "WorkloadControllerManager",
    "WorkloadProfile",
    "gang_member_name",
    "replica_name",
]
