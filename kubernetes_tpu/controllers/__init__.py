"""Native controller plane (kube-controller-manager analogue, PAPER.md L4).

Residents:

- node-lifecycle controller — lease/heartbeat-driven health, the
  taint-on-unready ladder, rate-limited zone-aware eviction, pod GC
  (docs/RESILIENCE.md § node lifecycle);
- workload controller-manager — ReplicaSet/Deployment reconcile +
  rolling updates, gang lifecycle over PodGroups, cluster autoscaler,
  Borg-style trace-profile feed, all behind one HA PUT-CAS lease
  (docs/RESILIENCE.md § workload controllers);
- descheduler — drift-repair plane: pluggable strategies nominate
  misplaced bound pods, one dense what-if matrix (ops/whatif.py)
  rescores them with the scheduler's own arithmetic, and gang-whole
  hysteresis-gated moves drain through the PR-16 eviction funnel
  (docs/DESCHEDULE.md).

Each runs as its own process: ``python -m kubernetes_tpu.controllers
--mode {node-lifecycle,workload,deschedule} --api-url ...`` against the
real apiserver via HTTPClientset.
"""

from .autoscaler import ClusterAutoscaler
from .descheduler import (
    DeschedulerController,
    DuplicateReplicas,
    LowNodeUtilization,
    TaintViolation,
    clears_hysteresis,
)
from .evictor import RateLimitedEvictor, TokenBucket
from .node_lifecycle import NodeLifecycleController
from .traceprofile import WorkloadProfile
from .workload import (
    DeploymentController,
    GangController,
    ReplicaSetController,
    WorkloadControllerManager,
    gang_member_name,
    replica_name,
)

__all__ = [
    "ClusterAutoscaler",
    "DeploymentController",
    "DeschedulerController",
    "DuplicateReplicas",
    "GangController",
    "LowNodeUtilization",
    "NodeLifecycleController",
    "RateLimitedEvictor",
    "ReplicaSetController",
    "TaintViolation",
    "TokenBucket",
    "WorkloadControllerManager",
    "WorkloadProfile",
    "clears_hysteresis",
    "gang_member_name",
    "replica_name",
]
