"""Native controller plane (kube-controller-manager analogue, PAPER.md L4).

First resident: the node-lifecycle controller — lease/heartbeat-driven
health monitoring, taint-on-unready (NoSchedule -> NoExecute ladder),
rate-limited zone-aware eviction, and pod GC — run as its own process
(`python -m kubernetes_tpu.controllers --api-url ...`) against the real
apiserver via HTTPClientset. docs/RESILIENCE.md § node lifecycle.
"""

from .evictor import RateLimitedEvictor, TokenBucket
from .node_lifecycle import NodeLifecycleController

__all__ = ["NodeLifecycleController", "RateLimitedEvictor", "TokenBucket"]
