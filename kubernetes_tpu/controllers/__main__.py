"""Standalone node-lifecycle controller process:

    python -m kubernetes_tpu.controllers --api-url http://127.0.0.1:PORT \
        [--fallback URL ...] [--grace S] [--noexec-after S] [--tick S] \
        [--primary-qps Q] [--secondary-qps Q] [--unhealthy-threshold F] \
        [--metrics-port P]

Connects an HTTPClientset (reads may land on follower replicas via
--fallback; writes and the heartbeat-ages poll leader-route), prints the
ready line the spawn harness keys on (``node-lifecycle controller:
watching ...``), serves its own /metrics (`node_lifecycle_*` series) on
an ephemeral port, reconciles until SIGTERM/SIGINT, then prints one JSON
stats line.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..core.apiserver import HTTPClientset
from .node_lifecycle import NodeLifecycleController


def _serve_metrics(ctrl: NodeLifecycleController, port: int):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: D102 - silence request logs
            pass

        def do_GET(self):  # noqa: N802 - http.server API
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            data = ctrl.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes-tpu-controllers")
    ap.add_argument("--api-url", required=True,
                    help="apiserver base URL (reads; writes leader-route)")
    ap.add_argument("--fallback", action="append", default=[],
                    help="sibling replica URL for read-plane failover "
                         "(repeatable)")
    ap.add_argument("--grace", type=float, default=4.0,
                    help="heartbeat silence before Ready->Unknown")
    ap.add_argument("--noexec-after", type=float, default=2.0,
                    help="further silence before the NoExecute taint")
    ap.add_argument("--tick", type=float, default=0.5)
    ap.add_argument("--primary-qps", type=float, default=2.0)
    ap.add_argument("--secondary-qps", type=float, default=0.1)
    ap.add_argument("--unhealthy-threshold", type=float, default=0.55)
    ap.add_argument("--metrics-port", type=int, default=0)
    args = ap.parse_args(argv)

    cs = HTTPClientset(args.api_url, fallbacks=args.fallback)
    ctrl = NodeLifecycleController(
        cs, grace=args.grace, noexec_after=args.noexec_after,
        tick=args.tick, primary_qps=args.primary_qps,
        secondary_qps=args.secondary_qps,
        unhealthy_threshold=args.unhealthy_threshold)
    httpd = _serve_metrics(ctrl, args.metrics_port)
    mport = httpd.server_address[1]
    ctrl.start()
    # The ready line FIRST (spawn harnesses select()+readline on it).
    print(f"node-lifecycle controller: watching {args.api_url} "
          f"metrics on 127.0.0.1:{mport}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    ctrl.stop()
    httpd.shutdown()
    cs.close()
    print(json.dumps({"controller_stats": ctrl.stats()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
