"""Standalone controller processes:

    python -m kubernetes_tpu.controllers --mode node-lifecycle \
        --api-url http://127.0.0.1:PORT [--fallback URL ...] [--grace S] \
        [--noexec-after S] [--tick S] [--primary-qps Q] [--secondary-qps Q] \
        [--unhealthy-threshold F] [--metrics-port P]

    python -m kubernetes_tpu.controllers --mode workload \
        --api-url http://127.0.0.1:PORT [--fallback URL ...] \
        [--identity NAME] [--lease-ttl S] [--tick S] \
        [--autoscale --min-nodes N --max-nodes N] \
        [--trace-deployments N --trace-gangs N --trace-seed N ...] \
        [--metrics-port P]

    python -m kubernetes_tpu.controllers --mode deschedule \
        --api-url http://127.0.0.1:PORT [--fallback URL ...] \
        [--identity NAME] [--lease-ttl S] [--tick S] \
        [--hysteresis N] [--margin F] [--max-moves N] \
        [--deschedule-device] \
        [--primary-qps Q] [--secondary-qps Q] [--metrics-port P]

Every mode connects an HTTPClientset (reads may land on follower
replicas via --fallback; writes and the heartbeat-ages poll
leader-route), prints the ready line the spawn harness keys on, serves
its own /metrics on an ephemeral port, reconciles until SIGTERM/SIGINT,
then prints one JSON stats line. Two `--mode workload` (or `--mode
deschedule`) processes with distinct --identity race the shared lease:
one runs ACTIVE, the other STANDBY with warm informers, taking over
inside --lease-ttl of a kill9.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..core.apiserver import WORKLOAD_KINDS, HTTPClientset
from .autoscaler import ClusterAutoscaler
from .node_lifecycle import NodeLifecycleController
from .traceprofile import WorkloadProfile
from .workload import WorkloadControllerManager


def _serve_metrics(ctrl, port: int):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # noqa: D102 - silence request logs
            pass

        def do_GET(self):  # noqa: N802 - http.server API
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            data = ctrl.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes-tpu-controllers")
    ap.add_argument("--mode",
                    choices=("node-lifecycle", "workload", "deschedule"),
                    default="node-lifecycle")
    ap.add_argument("--api-url", required=True,
                    help="apiserver base URL (reads; writes leader-route)")
    ap.add_argument("--fallback", action="append", default=[],
                    help="sibling replica URL for read-plane failover "
                         "(repeatable)")
    ap.add_argument("--tick", type=float, default=None)
    ap.add_argument("--metrics-port", type=int, default=0)
    # node-lifecycle knobs
    ap.add_argument("--grace", type=float, default=4.0,
                    help="heartbeat silence before Ready->Unknown")
    ap.add_argument("--noexec-after", type=float, default=2.0,
                    help="further silence before the NoExecute taint")
    ap.add_argument("--primary-qps", type=float, default=2.0)
    ap.add_argument("--secondary-qps", type=float, default=0.1)
    ap.add_argument("--unhealthy-threshold", type=float, default=0.55)
    # workload-manager knobs
    ap.add_argument("--identity", default="workload-manager-0",
                    help="lease holder id (distinct per HA replica)")
    ap.add_argument("--lease-ttl", type=float, default=2.0)
    ap.add_argument("--autoscale", action="store_true")
    ap.add_argument("--min-nodes", type=int, default=0)
    ap.add_argument("--max-nodes", type=int, default=100)
    ap.add_argument("--scale-wave", type=int, default=2)
    ap.add_argument("--pending-age", type=float, default=2.0)
    ap.add_argument("--scale-cooldown", type=float, default=5.0)
    ap.add_argument("--trace-deployments", type=int, default=0,
                    help="feed a Borg-style trace profile: deployments")
    ap.add_argument("--trace-gangs", type=int, default=0)
    ap.add_argument("--trace-rate", type=float, default=2.0)
    ap.add_argument("--trace-lifetime", type=float, default=0.0)
    ap.add_argument("--trace-seed", type=int, default=0)
    # descheduler knobs
    ap.add_argument("--hysteresis", type=int, default=5,
                    help="minimum scored improvement a move must clear")
    ap.add_argument("--margin", type=float, default=0.10,
                    help="low-node-utilization: how far above the mean "
                         "cpu-request utilization a node must sit to "
                         "nominate movers")
    ap.add_argument("--max-moves", type=int, default=64,
                    help="eviction budget per reconcile tick")
    ap.add_argument("--deschedule-device", action="store_true",
                    help="dispatch the what-if matrix through the jitted "
                         "mirror instead of the host walker")
    args = ap.parse_args(argv)

    if args.mode == "deschedule":
        from .descheduler import DeschedulerController, default_strategies

        cs = HTTPClientset(args.api_url, fallbacks=args.fallback)
        ctrl = DeschedulerController(
            cs, identity=args.identity, lease_ttl=args.lease_ttl,
            tick=args.tick if args.tick is not None else 0.25,
            hysteresis=args.hysteresis,
            strategies=default_strategies(margin=args.margin),
            primary_qps=args.primary_qps, secondary_qps=args.secondary_qps,
            unhealthy_threshold=args.unhealthy_threshold,
            max_moves_per_tick=args.max_moves,
            device=args.deschedule_device)
        ready = (f"descheduler [{args.identity}]: "
                 f"watching {args.api_url}")
    elif args.mode == "node-lifecycle":
        cs = HTTPClientset(args.api_url, fallbacks=args.fallback)
        ctrl = NodeLifecycleController(
            cs, grace=args.grace, noexec_after=args.noexec_after,
            tick=args.tick if args.tick is not None else 0.5,
            primary_qps=args.primary_qps,
            secondary_qps=args.secondary_qps,
            unhealthy_threshold=args.unhealthy_threshold)
        ready = f"node-lifecycle controller: watching {args.api_url}"
    else:
        cs = HTTPClientset(args.api_url, fallbacks=args.fallback,
                           extra_kinds=WORKLOAD_KINDS)
        autoscaler = None
        if args.autoscale:
            autoscaler = ClusterAutoscaler(
                cs, min_nodes=args.min_nodes, max_nodes=args.max_nodes,
                wave=args.scale_wave, pending_age_s=args.pending_age,
                cooldown_s=args.scale_cooldown)
        profile = None
        if args.trace_deployments or args.trace_gangs:
            profile = WorkloadProfile(
                deployments=args.trace_deployments, gangs=args.trace_gangs,
                arrival_rate=args.trace_rate,
                mean_lifetime_s=args.trace_lifetime, seed=args.trace_seed)
        ctrl = WorkloadControllerManager(
            cs, identity=args.identity, lease_ttl=args.lease_ttl,
            tick=args.tick if args.tick is not None else 0.25,
            autoscaler=autoscaler, profile=profile)
        ready = (f"workload controller-manager [{args.identity}]: "
                 f"watching {args.api_url}")

    httpd = _serve_metrics(ctrl, args.metrics_port)
    mport = httpd.server_address[1]
    ctrl.start()
    # The ready line FIRST (spawn harnesses select()+readline on it).
    print(f"{ready} metrics on 127.0.0.1:{mport}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    ctrl.stop()
    httpd.shutdown()
    cs.close()
    print(json.dumps({"controller_stats": ctrl.stats()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
