"""Node-lifecycle controller: the first native reconcile loop
(node_lifecycle_controller.go collapsed to one standing loop).

Health is heartbeat freshness read off the apiserver's leader-local ages
surface (`GET /api/v1/nodes/heartbeats` — the node-status sink the hollow
plane already drives). A node silent past `grace` transitions
Ready -> Unknown and climbs the taint ladder: `node.kubernetes.io/
unreachable` NoSchedule immediately (the scheduler's existing taint
predicate stops NEW placements, and the MODIFIED fanout invalidates
score-hint rows with zero new device code), then NoExecute after
`noexec_after` more seconds of silence, at which point its bound pods
drain through the RateLimitedEvictor. A node that heartbeats again lifts
the ladder and cancels its still-pending evictions. Pods bound to a node
that no longer EXISTS are reaped by the same loop (pod GC).

Failover posture: ages are leader-local, so a freshly promoted apiserver
answers with an empty (or young) map — nodes absent from the map age from
this controller's own first-sight stamp, i.e. the fleet gets one full
grace period after any failover before anything is declared Unknown.
Evictions stay exactly-once regardless: intent ids are deterministic and
the ledger rides the replicated WAL.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..core.apiserver import UNREACHABLE_TAINT, node_from_wire, node_to_wire
from .evictor import GC_ZONE, ZONE_FULL, ZONE_PARTIAL, RateLimitedEvictor

ZONE_LABEL = "topology.kubernetes.io/zone"
# Deleted-node pod GC drains through the evictor's reserved GC_ZONE queue
# (always primary-rate). Unlabeled nodes census under zone "" — a REAL
# zone whose disruption states apply — which the reserved key can never
# collide with ("/" is illegal in a label value).

READY = "Ready"
UNKNOWN = "Unknown"


class NodeLifecycleController:
    def __init__(self, clientset, grace: float = 4.0,
                 noexec_after: float = 2.0, tick: float = 0.5,
                 primary_qps: float = 2.0, secondary_qps: float = 0.1,
                 unhealthy_threshold: float = 0.55,
                 eviction_burst: float = 1.0,
                 ages_fn: Optional[Callable[[], Dict[str, float]]] = None,
                 now: Callable[[], float] = time.monotonic):
        self.cs = clientset
        self.grace = float(grace)
        self.noexec_after = float(noexec_after)
        self.tick = float(tick)
        self._now = now
        self._ages = ages_fn or clientset.node_heartbeat_ages
        self.evictor = RateLimitedEvictor(
            clientset, primary_qps=primary_qps, secondary_qps=secondary_qps,
            unhealthy_threshold=unhealthy_threshold, burst=eviction_burst,
            now=now)
        self.node_health: Dict[str, str] = {}   # name -> Ready/Unknown
        self._first_seen: Dict[str, float] = {}  # age fallback (failover)
        self._unready_at: Dict[str, float] = {}  # Unknown since (our clock)
        self.reconciles = 0
        self.taints_noschedule = 0
        self.taints_noexecute = 0
        self.taints_lifted = 0
        self.pods_gc = 0
        self.age_poll_errors = 0
        self.taint_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- taint ladder --------------------------------------------------------

    @staticmethod
    def _our_effects(node) -> set:
        return {t.effect for t in node.taints if t.key == UNREACHABLE_TAINT}

    def _retaint(self, node, effects) -> bool:
        """PUT the node with exactly `effects` of OUR taint (every other
        taint preserved) — idempotent, driven off the informer cache so a
        settled ladder step never re-PUTs."""
        w = node_to_wire(node)
        taints = [t for t in w["taints"] if t["key"] != UNREACHABLE_TAINT]
        taints.extend({"key": UNREACHABLE_TAINT, "value": "",
                       "effect": e} for e in sorted(effects))
        w["taints"] = taints
        try:
            self.cs.update_node(node_from_wire(w))
            return True
        except Exception:  # noqa: BLE001 - transient: retried next tick
            self.taint_errors += 1
            return False

    # -- one reconcile pass --------------------------------------------------

    def reconcile_once(self) -> None:
        self.reconciles += 1
        try:
            ages = self._ages()
        except Exception:  # noqa: BLE001 - leader unreachable mid-failover
            self.age_poll_errors += 1
            return
        now = self._now()
        nodes = dict(self.cs.nodes)
        # Health census first: zone eviction rates must reflect THIS pass's
        # view before any eviction token is spent.
        zone_total: Dict[str, int] = {}
        zone_unhealthy: Dict[str, int] = {}
        unhealthy = []
        for name, node in nodes.items():
            age = ages.get(name)
            if age is None:
                # Not in the leader's map (fresh leader after failover, or
                # registered-elsewhere): age from OUR first sight — one
                # full grace period before judgment.
                age = now - self._first_seen.setdefault(name, now)
            zone = node.labels.get(ZONE_LABEL, "")
            zone_total[zone] = zone_total.get(zone, 0) + 1
            if age >= self.grace:
                self.node_health[name] = UNKNOWN
                zone_unhealthy[zone] = zone_unhealthy.get(zone, 0) + 1
                unhealthy.append((name, node, zone))
            else:
                if self.node_health.get(name) == UNKNOWN:
                    self._recover_node(name, node)
                self.node_health[name] = READY
                self._unready_at.pop(name, None)
        for zone, total in zone_total.items():
            self.evictor.set_zone_state(
                zone, zone_unhealthy.get(zone, 0), total)
        for name, node, zone in unhealthy:
            self._degrade_node(name, node, zone, now)
        self._gc_pods(nodes)
        self.evictor.run_once()
        # Forget state for nodes that left the cluster.
        for name in list(self.node_health):
            if name not in nodes:
                self.node_health.pop(name, None)
                self._unready_at.pop(name, None)
                self._first_seen.pop(name, None)

    def _degrade_node(self, name: str, node, zone: str, now: float) -> None:
        """Climb the taint ladder for one Unknown node and, once it holds
        NoExecute, queue its bound pods for rate-limited eviction."""
        since = self._unready_at.setdefault(name, now)
        have = self._our_effects(node)
        want = {"NoSchedule"}
        if now - since >= self.noexec_after:
            want = {"NoSchedule", "NoExecute"}
        if want != have:
            if not self._retaint(node, want):
                return
            if "NoExecute" in want and "NoExecute" not in have:
                self.taints_noexecute += 1
            elif "NoSchedule" not in have:
                self.taints_noschedule += 1
        if "NoExecute" in want:
            for pod in list(self.cs.pods.values()):
                if pod.node_name == name:
                    self.evictor.enqueue(zone, name, pod.uid)

    def _recover_node(self, name: str, node) -> None:
        """Heartbeats returned mid-ladder: lift our taints and cancel any
        eviction still queued off this node — taint-lift-mid-wave means
        those pods keep their placement."""
        self.evictor.cancel_node(name)
        if self._our_effects(node):
            if self._retaint(node, set()):
                self.taints_lifted += 1

    def _gc_pods(self, nodes: Dict[str, object]) -> None:
        """Pods bound to a node that no longer exists: reap through the
        same eviction funnel (rate-limited + intent-ledgered), so node
        deletion mid-wave cannot double-release anything either."""
        for pod in list(self.cs.pods.values()):
            if pod.node_name and pod.node_name not in nodes:
                if self.evictor.enqueue(GC_ZONE, pod.node_name, pod.uid):
                    self.pods_gc += 1

    # -- standing loop -------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="node-lifecycle", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.reconcile_once()
            if self._stop.wait(self.tick):
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        ev = self.evictor
        return {
            "reconciles": self.reconciles,
            "nodes_unknown": sum(1 for s in self.node_health.values()
                                 if s == UNKNOWN),
            "taints_noschedule": self.taints_noschedule,
            "taints_noexecute": self.taints_noexecute,
            "taints_lifted": self.taints_lifted,
            "pods_gc": self.pods_gc,
            "age_poll_errors": self.age_poll_errors,
            "taint_errors": self.taint_errors,
            "evictions": ev.evictions_total,
            "evictions_throttled": ev.evictions_throttled_total,
            "evictions_replayed": ev.evictions_replayed,
            "evictions_cancelled": ev.evictions_cancelled,
            "evictions_budget_blocked": ev.evictions_budget_blocked,
            "eviction_errors": ev.eviction_errors,
            "zone_states": dict(ev.zone_states),
        }

    def metrics_text(self) -> str:
        """Prometheus text: the `node_lifecycle_*` series the chaos
        acceptance asserts (evictions + throttle), plus ladder/GC/zone
        observability."""
        ev = self.evictor
        out = []
        for name, v in (
                ("node_lifecycle_evictions_total", ev.evictions_total),
                ("node_lifecycle_evictions_throttled_total",
                 ev.evictions_throttled_total),
                ("node_lifecycle_evictions_replayed_total",
                 ev.evictions_replayed),
                ("node_lifecycle_evictions_cancelled_total",
                 ev.evictions_cancelled),
                ("node_lifecycle_evictions_budget_blocked_total",
                 ev.evictions_budget_blocked),
                ("node_lifecycle_eviction_errors_total", ev.eviction_errors),
                ("node_lifecycle_taints_noschedule_total",
                 self.taints_noschedule),
                ("node_lifecycle_taints_noexecute_total",
                 self.taints_noexecute),
                ("node_lifecycle_taints_lifted_total", self.taints_lifted),
                ("node_lifecycle_pods_gc_total", self.pods_gc),
                ("node_lifecycle_reconciles_total", self.reconciles)):
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {v}")
        out.append("# TYPE node_lifecycle_nodes_unknown gauge")
        out.append("node_lifecycle_nodes_unknown %d"
                   % sum(1 for s in self.node_health.values()
                         if s == UNKNOWN))
        out.append("# TYPE node_lifecycle_zone_state gauge")
        level = {ZONE_PARTIAL: 1, ZONE_FULL: 2}
        for zone, state in sorted(ev.zone_states.items()):
            out.append('node_lifecycle_zone_state{zone="%s"} %d'
                       % (zone, level.get(state, 0)))
        return "\n".join(out) + "\n"
