"""Label sets and label selectors.

Re-implements the matching semantics of staging/src/k8s.io/apimachinery/pkg/labels
(Selector/Requirement) and apimachinery/pkg/apis/meta/v1 LabelSelector
(matchLabels + matchExpressions) — the predicate language every affinity /
spread / selector feature in the scheduler is written in.

The device path never evaluates these structures directly: selectors are
compiled per-cycle into matches over interned label-id tensors
(kubernetes_tpu/ops). This module is the host-side oracle semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence

# Operators — apimachinery/pkg/apis/meta/v1/types.go LabelSelectorOperator and
# pkg/labels selection.Operator.
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass(frozen=True)
class Requirement:
    """One selector requirement: key op values."""

    key: str
    operator: str
    values: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        op = self.operator
        if op == EXISTS:
            return has
        if op == DOES_NOT_EXIST:
            return not has
        if not has:
            return False
        v = labels[self.key]
        if op == IN:
            return v in self.values
        if op == NOT_IN:
            return v not in self.values
        if op in (GT, LT):
            # Gt/Lt: both sides must parse as integers
            # (apimachinery labels.Requirement.Matches).
            try:
                lhs = int(v)
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if op == GT else lhs < rhs
        raise ValueError(f"unknown operator {op!r}")


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions, all ANDed.

    A None selector matches nothing; an empty selector matches everything
    (metav1 LabelSelectorAsSelector semantics).
    """

    match_labels: tuple = ()  # tuple of (key, value) pairs, sorted
    match_expressions: tuple = ()  # tuple of Requirement

    @classmethod
    def of(
        cls,
        match_labels: Optional[Mapping[str, str]] = None,
        match_expressions: Optional[Sequence[Requirement]] = None,
    ) -> "LabelSelector":
        ml = tuple(sorted((match_labels or {}).items()))
        me = tuple(match_expressions or ())
        return cls(match_labels=ml, match_expressions=me)

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            if not req.matches(labels):
                return False
        return True

    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


def everything() -> LabelSelector:
    return LabelSelector()


def selector_from_map(m: Mapping[str, str]) -> LabelSelector:
    return LabelSelector.of(match_labels=m)
