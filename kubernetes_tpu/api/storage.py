"""Storage API objects the scheduler consumes.

Scheduling-relevant slices of core/v1 PersistentVolume / PersistentVolumeClaim
and storage.k8s.io/v1 StorageClass + CSINode (reference:
staging/src/k8s.io/api/core/v1/types.go, storage/v1/types.go) — the inputs to
the VolumeBinding / NodeVolumeLimits / VolumeZone / VolumeRestrictions
plugins (pkg/scheduler/framework/plugins/volumebinding, nodevolumelimits, ...).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .resource import to_int
from .types import NodeSelector, _next_uid

# volumeBindingMode (storage/v1/types.go)
IMMEDIATE = "Immediate"
WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# access modes
RWO = "ReadWriteOnce"
ROX = "ReadOnlyMany"
RWX = "ReadWriteMany"
RWOP = "ReadWriteOncePod"


@dataclass
class StorageClass:
    name: str = ""
    provisioner: str = ""
    volume_binding_mode: str = IMMEDIATE
    allowed_topologies: Optional[NodeSelector] = None


@dataclass
class PersistentVolume:
    name: str = ""
    uid: str = ""
    capacity: int = 0                    # bytes
    access_modes: Tuple[str, ...] = (RWO,)
    storage_class: str = ""
    node_affinity: Optional[NodeSelector] = None  # pv.spec.nodeAffinity.required
    labels: Dict[str, str] = field(default_factory=dict)
    claim_ref: str = ""                  # "ns/name" of bound PVC ("" = available)
    csi_driver: str = ""                 # spec.csi.driver ("" = non-CSI)

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("pv")

    @classmethod
    def of(cls, name: str, capacity, **kw) -> "PersistentVolume":
        return cls(name=name, capacity=to_int(capacity), **kw)


@dataclass
class PersistentVolumeClaim:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    request: int = 0                     # bytes
    access_modes: Tuple[str, ...] = (RWO,)
    storage_class: str = ""
    volume_name: str = ""                # bound PV ("" = pending)
    labels: Dict[str, str] = field(default_factory=dict)
    # bind-completed / selected-node markers (pv_controller interlock)
    annotations: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("pvc")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @classmethod
    def of(cls, name: str, request, **kw) -> "PersistentVolumeClaim":
        return cls(name=name, request=to_int(request), **kw)


@dataclass
class CSINode:
    """storage/v1 CSINode: per-node driver attach limits
    (nodevolumelimits/csi.go reads .spec.drivers[].allocatable.count)."""

    node_name: str = ""
    driver_limits: Dict[str, int] = field(default_factory=dict)  # driver -> max volumes
