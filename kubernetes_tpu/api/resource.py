"""Resource quantities and aggregate resource vectors.

Re-expresses the reference's resource model (staging/src/k8s.io/apimachinery
/pkg/api/resource and pkg/scheduler/framework/types.go `Resource` struct,
reference framework/types.go around NodeInfo) in a flat, vector-friendly form:
CPU is canonicalised to integer millicores, everything else to integer base
units (bytes / counts), so that node state can be mirrored onto fixed-width
device tensors without string math on the hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from decimal import Decimal
from typing import Dict, Iterable, Mapping, Optional

# Well-known resource names (reference: staging/src/k8s.io/api/core/v1/types.go
# ResourceCPU/ResourceMemory/... constants).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

HUGEPAGES_PREFIX = "hugepages-"
ATTACHABLE_VOLUMES_PREFIX = "attachable-volumes-"

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIXES = {
    "n": Decimal("1e-9"),
    "u": Decimal("1e-6"),
    "m": Decimal("1e-3"),
    "": Decimal(1),
    "k": Decimal("1e3"),
    "M": Decimal("1e6"),
    "G": Decimal("1e9"),
    "T": Decimal("1e12"),
    "P": Decimal("1e15"),
    "E": Decimal("1e18"),
}


def parse_quantity(value) -> Decimal:
    """Parse a Kubernetes quantity string ("100m", "1.5Gi", "2") to a Decimal.

    Mirrors apimachinery resource.Quantity parsing for the suffix set the
    scheduler actually encounters; exotic exponent forms ("12e6") included.
    """
    if isinstance(value, (int, float, Decimal)):
        return Decimal(str(value))
    s = value.strip()
    if not s:
        raise ValueError("empty quantity")
    for suf, mult in _BINARY_SUFFIXES.items():
        if s.endswith(suf):
            return Decimal(s[: -len(suf)]) * mult
    # longest decimal suffixes first (single-char)
    if s[-1] in _DECIMAL_SUFFIXES and not s[-1].isdigit():
        return Decimal(s[:-1]) * _DECIMAL_SUFFIXES[s[-1]]
    return Decimal(s)


def cpu_to_milli(value) -> int:
    """CPU quantity -> integer millicores (rounds up, as Quantity.MilliValue does)."""
    d = parse_quantity(value) * 1000
    return int(d.to_integral_value(rounding="ROUND_CEILING"))


def to_int(value) -> int:
    """Non-CPU quantity -> integer base units (rounds up)."""
    d = parse_quantity(value)
    return int(d.to_integral_value(rounding="ROUND_CEILING"))


def is_scalar_resource_name(name: str) -> bool:
    """Extended/scalar resources: anything that is not a first-class vector slot.

    Reference: pkg/apis/core/v1/helper/helpers.go IsScalarResourceName
    (extended, hugepages, attachable-volumes, native prefixed).
    """
    return name not in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)


@dataclass
class Resource:
    """Aggregate resource vector.

    Mirrors the reference scheduler's Resource struct
    (pkg/scheduler/framework/types.go: MilliCPU/Memory/EphemeralStorage/
    AllowedPodNumber/ScalarResources) — the unit system the Filter/Score
    kernels operate in.
    """

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar_resources: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_map(cls, m: Optional[Mapping[str, object]]) -> "Resource":
        r = cls()
        if not m:
            return r
        for name, q in m.items():
            r.set(name, q)
        return r

    def set(self, name: str, quantity) -> None:
        if name == CPU:
            self.milli_cpu = cpu_to_milli(quantity)
        elif name == MEMORY:
            self.memory = to_int(quantity)
        elif name == EPHEMERAL_STORAGE:
            self.ephemeral_storage = to_int(quantity)
        elif name == PODS:
            self.allowed_pod_number = to_int(quantity)
        else:
            self.scalar_resources[name] = to_int(quantity)

    def get(self, name: str) -> int:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        if name == EPHEMERAL_STORAGE:
            return self.ephemeral_storage
        if name == PODS:
            return self.allowed_pod_number
        return self.scalar_resources.get(name, 0)

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) - v

    def set_max(self, other: "Resource") -> None:
        """Component-wise max (used for init-container folding)."""
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        self.ephemeral_storage = max(self.ephemeral_storage, other.ephemeral_storage)
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = max(self.scalar_resources.get(k, 0), v)

    def clone(self) -> "Resource":
        return Resource(
            milli_cpu=self.milli_cpu,
            memory=self.memory,
            ephemeral_storage=self.ephemeral_storage,
            allowed_pod_number=self.allowed_pod_number,
            scalar_resources=dict(self.scalar_resources),
        )

    def is_zero(self) -> bool:
        return (
            self.milli_cpu == 0
            and self.memory == 0
            and self.ephemeral_storage == 0
            and all(v == 0 for v in self.scalar_resources.values())
        )

    def names(self) -> Iterable[str]:
        yield CPU
        yield MEMORY
        yield EPHEMERAL_STORAGE
        yield from self.scalar_resources.keys()
