"""Dynamic Resource Allocation (DRA) API objects — structured parameters.

Scheduling-relevant slices of resource.k8s.io/v1 (reference:
staging/src/k8s.io/dynamic-resource-allocation, 33.1k LoC;
plugins/dynamicresources/ 2152 LoC core): ResourceSlice publishes a node's
devices, ResourceClaim requests devices by class/selector, DeviceClass names
a device category. The reference's CEL device selectors are expressed here as
attribute equality maps (the dominant production shape); CEL itself is out of
scope for the scheduler's hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .types import _next_uid


@dataclass
class Device:
    name: str
    attributes: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """resource.k8s.io ResourceSlice: one node's devices for one driver."""

    node_name: str
    driver: str
    devices: List[Device] = field(default_factory=list)


@dataclass
class DeviceClass:
    """DeviceClass: a named device category; `selectors` are attribute
    equality requirements every matching device must satisfy."""

    name: str
    selectors: Dict[str, str] = field(default_factory=dict)


@dataclass
class DeviceRequest:
    """One request inside a claim (spec.devices.requests[*])."""

    name: str = "req"
    device_class: str = ""
    count: int = 1
    selectors: Dict[str, str] = field(default_factory=dict)


@dataclass
class AllocatedDevice:
    driver: str
    device: str

    def key(self) -> Tuple[str, str]:
        return (self.driver, self.device)


@dataclass
class ResourceClaim:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    requests: List[DeviceRequest] = field(default_factory=list)
    # status
    allocated_node: str = ""                      # "" = unallocated
    allocations: List[AllocatedDevice] = field(default_factory=list)
    reserved_for: List[str] = field(default_factory=list)  # pod uids

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("claim")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def allocated(self) -> bool:
        return bool(self.allocated_node)
