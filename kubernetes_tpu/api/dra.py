"""Dynamic Resource Allocation (DRA) API objects — structured parameters.

Scheduling-relevant slices of resource.k8s.io/v1 (reference:
staging/src/k8s.io/dynamic-resource-allocation, 33.1k LoC;
plugins/dynamicresources/ 2152 LoC core): ResourceSlice publishes a node's
devices, ResourceClaim requests devices by class/selector, DeviceClass names
a device category. The reference's CEL device selectors are expressed here as
attribute equality maps (the dominant production shape); CEL itself is out of
scope for the scheduler's hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .types import _next_uid


@dataclass
class Device:
    name: str
    attributes: Dict[str, str] = field(default_factory=dict)
    capacity: Dict[str, str] = field(default_factory=dict)
    # Node-allocatable resources this device CONSUMES when allocated
    # (nodeallocatabledynamicresources.go: DRA allocations that draw from
    # the node's cpu/memory budget), e.g. {"cpu": "2", "memory": "4Gi"}.
    consumes: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """resource.k8s.io ResourceSlice: one node's devices for one driver."""

    node_name: str
    driver: str
    devices: List[Device] = field(default_factory=list)


@dataclass
class DeviceClass:
    """DeviceClass: a named device category; `selectors` are attribute
    equality requirements every matching device must satisfy.
    `extended_resource_name` maps a v1 extended resource (e.g.
    example.com/gpu) onto this class: pods requesting it are satisfied via
    DRA when no device plugin advertises it
    (resource/v1 types.go:2427 ExtendedResourceName +
    extendeddynamicresources.go)."""

    name: str
    selectors: Dict[str, str] = field(default_factory=dict)
    extended_resource_name: str = ""


@dataclass
class DeviceRequest:
    """One request inside a claim (spec.devices.requests[*])."""

    name: str = "req"
    device_class: str = ""
    count: int = 1
    selectors: Dict[str, str] = field(default_factory=dict)
    # CEL-equivalent device selector (compile_device_expression below);
    # evaluated per candidate device in addition to the equality selectors.
    expression: str = ""


@dataclass
class AllocatedDevice:
    driver: str
    device: str

    def key(self) -> Tuple[str, str]:
        return (self.driver, self.device)


@dataclass
class ResourceClaim:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    requests: List[DeviceRequest] = field(default_factory=list)
    # status
    allocated_node: str = ""                      # "" = unallocated
    allocations: List[AllocatedDevice] = field(default_factory=list)
    reserved_for: List[str] = field(default_factory=list)  # pod uids

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("claim")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def allocated(self) -> bool:
        return bool(self.allocated_node)


# ---------------------------------------------------------------------------
# Device selection expressions — the structured-parameters CEL equivalent
# (staging dynamic-resource-allocation/cel; resource.k8s.io DeviceSelector
# `cel.expression`). A restricted Python-syntax expression evaluated per
# device with the same surface the reference exposes:
#
#     device.attributes["gpu.example.com/model"] == "a100"
#     device.capacity["memory"] >= 40 and device.driver == "gpu.example.com"
#
# The AST is validated against a whitelist (comparisons, boolean logic,
# arithmetic, subscripts on device.attributes/capacity, literals) — no
# calls, no imports, no dunder access. Parse once per request, evaluate per
# device (the reference compiles CEL programs the same way).
# ---------------------------------------------------------------------------

import ast as _ast

_ALLOWED_NODES = (
    _ast.Expression, _ast.BoolOp, _ast.And, _ast.Or, _ast.UnaryOp, _ast.Not,
    _ast.USub, _ast.Compare, _ast.Eq, _ast.NotEq, _ast.Lt, _ast.LtE, _ast.Gt,
    _ast.GtE, _ast.In, _ast.NotIn, _ast.BinOp, _ast.Add, _ast.Sub, _ast.Mult,
    _ast.Div, _ast.Mod, _ast.Constant, _ast.Name, _ast.Load, _ast.Attribute,
    _ast.Subscript, _ast.Index, _ast.Tuple, _ast.List,
)


class ExpressionError(ValueError):
    """Invalid or disallowed device selector expression."""


class _ConstCoercer(_ast.NodeTransformer):
    """Coerce quantity-shaped string literals ONCE at compile time (the
    reference's CEL environment types quantity constants the same way):
    `"40Gi"` in a comparison against `device.attributes[...]` /
    `device.capacity[...]` becomes the coerced numeric bound to an injected
    name, so runtime comparisons are plain int/float ops against the (also
    coerced) map values — the coerced value classes need no cross-type
    string equality, keeping their __eq__ consistent with their int/float
    __hash__ (ADVICE r5; regression in tests/test_dra.py
    test_quantity_hash_eq_consistency).

    Scope: ONLY direct comparator operands (and their tuple/list members,
    for `in`) of a Compare that involves one of the two quantity maps.
    Subscript KEYS (`device.attributes["8"]` looks up the string key) and
    comparisons against the plain-string fields (`device.name == "0"`)
    keep their literal strings. Known edge: a CHAINED comparison mixing a
    string field and a quantity map (`device.name == "8" ==
    device.attributes["c"]`) treats its string literals as quantities —
    CEL has no comparison chaining, so the quantity reading wins. Runs
    AFTER validation, so injected names cannot collide with user
    identifiers (only `device` is legal)."""

    def __init__(self):
        self.bindings = {}

    @staticmethod
    def _qty_map_operand(n) -> bool:
        # device.attributes[...] / device.capacity[...] — the maps whose
        # VALUES are quantity-coerced (_CoercingMap).
        return (isinstance(n, _ast.Subscript)
                and isinstance(n.value, _ast.Attribute)
                and n.value.attr in ("attributes", "capacity"))

    def _coerce_const(self, node):
        if isinstance(node, _ast.Constant) and isinstance(node.value, str):
            coerced = _CoercingMap._coerce(node.value)
            if not isinstance(coerced, str):
                name = f"_qty{len(self.bindings)}"
                self.bindings[name] = coerced
                return _ast.copy_location(
                    _ast.Name(id=name, ctx=_ast.Load()), node)
        elif isinstance(node, (_ast.Tuple, _ast.List)):
            node.elts = [self._coerce_const(e) for e in node.elts]
        return node

    def visit_Compare(self, node):
        self.generic_visit(node)  # nested compares inside operands first
        operands = [node.left] + list(node.comparators)
        if any(self._qty_map_operand(o) for o in operands):
            node.left = self._coerce_const(node.left)
            node.comparators = [self._coerce_const(c)
                                for c in node.comparators]
        return node


def compile_device_expression(expr: str):
    """Validate + compile a device selector expression. Returns a callable
    (device, driver) -> bool. Raises ExpressionError on disallowed syntax."""
    try:
        tree = _ast.parse(expr, mode="eval")
    except SyntaxError as e:
        raise ExpressionError(f"invalid expression: {e}") from e
    for node in _ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ExpressionError(
                f"disallowed syntax {type(node).__name__!r} in device expression")
        if isinstance(node, _ast.Name) and node.id != "device":
            raise ExpressionError(f"unknown identifier {node.id!r}")
        if isinstance(node, _ast.Attribute):
            if node.attr.startswith("__") or node.attr not in (
                    "attributes", "capacity", "driver", "name"):
                raise ExpressionError(f"unknown device field {node.attr!r}")
    coercer = _ConstCoercer()
    tree = _ast.fix_missing_locations(coercer.visit(tree))
    qty_consts = coercer.bindings
    code = compile(tree, "<device-selector>", "eval")

    class _DeviceView:
        __slots__ = ("attributes", "capacity", "driver", "name")

        def __init__(self, device, driver):
            # Coerced maps are memoized ON the device (the exception-driven
            # coercion chain costs more than the whole match when it runs
            # per evaluation), validated against the raw dicts' identities:
            # a slice update that REPLACES the attribute/capacity maps (the
            # supported mutation shape — spec maps are copy-on-write, never
            # edited in place) invalidates the memo automatically.
            raw_cap = getattr(device, "capacity", None)
            memo = device.__dict__.get("_coerced_memo")
            if (memo is None or memo[0] is not device.attributes
                    or memo[1] is not raw_cap):
                memo = device._coerced_memo = (
                    device.attributes, raw_cap,
                    _CoercingMap.coerced(device.attributes),
                    _CoercingMap.coerced(raw_cap or {}))
            self.attributes = memo[2]
            self.capacity = memo[3]
            self.driver = driver
            self.name = device.name

    def matcher(device, driver="") -> bool:
        try:
            env = {"device": _DeviceView(device, driver)}
            if qty_consts:
                env.update(qty_consts)
            return bool(eval(code, {"__builtins__": {}}, env))  # noqa: S307 - AST-whitelisted
        except Exception:
            # CEL runtime errors make the device non-matching (the reference
            # treats evaluation errors as "does not satisfy selector").
            return False

    return matcher


class _CoercingMap(dict):
    """Attribute/capacity map that compares numerically when both sides are
    numeric, with full QUANTITY semantics for suffixed strings — the typed
    CEL surface: device.capacity["memory"] >= 40 * 1024**3 holds for
    "40Gi" (apimachinery resource.Quantity comparisons in the reference's
    CEL environment)."""

    @classmethod
    def coerced(cls, raw: Dict[str, str]) -> "_CoercingMap":
        """Pre-coerce every value ONCE (the maps are per-device spec)."""
        out = cls()
        for k, v in raw.items():
            out[k] = cls._coerce(v)
        return out

    @staticmethod
    def _coerce(v):
        if isinstance(v, str):
            try:
                return _QtyInt(int(v))
            except ValueError:
                pass
            try:
                return _QtyFloat(float(v))
            except ValueError:
                pass
            try:
                from .resource import parse_quantity
                q = parse_quantity(v)
                iq = int(q)
                return _QtyInt(iq) if q == iq else _QtyFloat(float(q))
            except Exception:
                return v
        return v

    def __getitem__(self, key):
        return dict.get(self, key)


class _QtyMixin:
    """Coerced quantity values: EQUALITY is strictly numeric (inherited
    int/float __eq__/__hash__ — equal objects hash equal, so coerced values
    are safe set members / dict keys next to any other form; the ADVICE-r5
    hash/eq asymmetry is gone). The CEL surface still holds —
    device.capacity["mem"] == "40Gi" and == 40*1024**3 are both True —
    because expression string LITERALS are coerced once at compile time
    (_ConstCoercer) and the map values once per device (_CoercingMap), so
    both sides of every runtime comparison are already numeric. ORDERING
    operands keep the string coercion (`qty >= "32Gi"` for direct API
    users); ordering carries no hash contract."""

    __slots__ = ()

    def _other(self, other):
        if isinstance(other, str):
            return _CoercingMap._coerce(other)
        return other

    def __lt__(self, other):
        return super().__lt__(self._other(other))

    def __le__(self, other):
        return super().__le__(self._other(other))

    def __gt__(self, other):
        return super().__gt__(self._other(other))

    def __ge__(self, other):
        return super().__ge__(self._other(other))


class _QtyInt(_QtyMixin, int):
    pass


class _QtyFloat(_QtyMixin, float):
    pass
