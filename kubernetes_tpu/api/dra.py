"""Dynamic Resource Allocation (DRA) API objects — structured parameters.

Scheduling-relevant slices of resource.k8s.io/v1 (reference:
staging/src/k8s.io/dynamic-resource-allocation, 33.1k LoC;
plugins/dynamicresources/ 2152 LoC core): ResourceSlice publishes a node's
devices, ResourceClaim requests devices by class/selector, DeviceClass names
a device category. The reference's CEL device selectors are expressed here as
attribute equality maps (the dominant production shape); CEL itself is out of
scope for the scheduler's hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .types import _next_uid


@dataclass
class Device:
    name: str
    attributes: Dict[str, str] = field(default_factory=dict)
    capacity: Dict[str, str] = field(default_factory=dict)
    # Node-allocatable resources this device CONSUMES when allocated
    # (nodeallocatabledynamicresources.go: DRA allocations that draw from
    # the node's cpu/memory budget), e.g. {"cpu": "2", "memory": "4Gi"}.
    consumes: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """resource.k8s.io ResourceSlice: one node's devices for one driver."""

    node_name: str
    driver: str
    devices: List[Device] = field(default_factory=list)


@dataclass
class DeviceClass:
    """DeviceClass: a named device category; `selectors` are attribute
    equality requirements every matching device must satisfy.
    `extended_resource_name` maps a v1 extended resource (e.g.
    example.com/gpu) onto this class: pods requesting it are satisfied via
    DRA when no device plugin advertises it
    (resource/v1 types.go:2427 ExtendedResourceName +
    extendeddynamicresources.go)."""

    name: str
    selectors: Dict[str, str] = field(default_factory=dict)
    extended_resource_name: str = ""


@dataclass
class DeviceRequest:
    """One request inside a claim (spec.devices.requests[*])."""

    name: str = "req"
    device_class: str = ""
    count: int = 1
    selectors: Dict[str, str] = field(default_factory=dict)
    # CEL-equivalent device selector (compile_device_expression below);
    # evaluated per candidate device in addition to the equality selectors.
    expression: str = ""


@dataclass
class AllocatedDevice:
    driver: str
    device: str

    def key(self) -> Tuple[str, str]:
        return (self.driver, self.device)


@dataclass
class ResourceClaim:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    requests: List[DeviceRequest] = field(default_factory=list)
    # status
    allocated_node: str = ""                      # "" = unallocated
    allocations: List[AllocatedDevice] = field(default_factory=list)
    reserved_for: List[str] = field(default_factory=list)  # pod uids

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("claim")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def allocated(self) -> bool:
        return bool(self.allocated_node)


# ---------------------------------------------------------------------------
# Device selection expressions — the structured-parameters CEL equivalent
# (staging dynamic-resource-allocation/cel; resource.k8s.io DeviceSelector
# `cel.expression`). A restricted Python-syntax expression evaluated per
# device with the same surface the reference exposes:
#
#     device.attributes["gpu.example.com/model"] == "a100"
#     device.capacity["memory"] >= 40 and device.driver == "gpu.example.com"
#
# The AST is validated against a whitelist (comparisons, boolean logic,
# arithmetic, subscripts on device.attributes/capacity, literals) — no
# calls, no imports, no dunder access. Parse once per request, evaluate per
# device (the reference compiles CEL programs the same way).
# ---------------------------------------------------------------------------

import ast as _ast

_ALLOWED_NODES = (
    _ast.Expression, _ast.BoolOp, _ast.And, _ast.Or, _ast.UnaryOp, _ast.Not,
    _ast.USub, _ast.Compare, _ast.Eq, _ast.NotEq, _ast.Lt, _ast.LtE, _ast.Gt,
    _ast.GtE, _ast.In, _ast.NotIn, _ast.BinOp, _ast.Add, _ast.Sub, _ast.Mult,
    _ast.Div, _ast.Mod, _ast.Constant, _ast.Name, _ast.Load, _ast.Attribute,
    _ast.Subscript, _ast.Index, _ast.Tuple, _ast.List,
)


class ExpressionError(ValueError):
    """Invalid or disallowed device selector expression."""


def compile_device_expression(expr: str):
    """Validate + compile a device selector expression. Returns a callable
    (device, driver) -> bool. Raises ExpressionError on disallowed syntax."""
    try:
        tree = _ast.parse(expr, mode="eval")
    except SyntaxError as e:
        raise ExpressionError(f"invalid expression: {e}") from e
    for node in _ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ExpressionError(
                f"disallowed syntax {type(node).__name__!r} in device expression")
        if isinstance(node, _ast.Name) and node.id != "device":
            raise ExpressionError(f"unknown identifier {node.id!r}")
        if isinstance(node, _ast.Attribute):
            if node.attr.startswith("__") or node.attr not in (
                    "attributes", "capacity", "driver", "name"):
                raise ExpressionError(f"unknown device field {node.attr!r}")
    code = compile(tree, "<device-selector>", "eval")

    class _DeviceView:
        __slots__ = ("attributes", "capacity", "driver", "name")

        def __init__(self, device, driver):
            # Coerced maps are memoized ON the device (the exception-driven
            # coercion chain costs more than the whole match when it runs
            # per evaluation), validated against the raw dicts' identities:
            # a slice update that REPLACES the attribute/capacity maps (the
            # supported mutation shape — spec maps are copy-on-write, never
            # edited in place) invalidates the memo automatically.
            raw_cap = getattr(device, "capacity", None)
            memo = device.__dict__.get("_coerced_memo")
            if (memo is None or memo[0] is not device.attributes
                    or memo[1] is not raw_cap):
                memo = device._coerced_memo = (
                    device.attributes, raw_cap,
                    _CoercingMap.coerced(device.attributes),
                    _CoercingMap.coerced(raw_cap or {}))
            self.attributes = memo[2]
            self.capacity = memo[3]
            self.driver = driver
            self.name = device.name

    def matcher(device, driver="") -> bool:
        try:
            return bool(eval(code, {"__builtins__": {}},  # noqa: S307 - AST-whitelisted
                             {"device": _DeviceView(device, driver)}))
        except Exception:
            # CEL runtime errors make the device non-matching (the reference
            # treats evaluation errors as "does not satisfy selector").
            return False

    return matcher


class _CoercingMap(dict):
    """Attribute/capacity map that compares numerically when both sides are
    numeric, with full QUANTITY semantics for suffixed strings — the typed
    CEL surface: device.capacity["memory"] >= 40 * 1024**3 holds for
    "40Gi" (apimachinery resource.Quantity comparisons in the reference's
    CEL environment)."""

    @classmethod
    def coerced(cls, raw: Dict[str, str]) -> "_CoercingMap":
        """Pre-coerce every value ONCE (the maps are per-device spec)."""
        out = cls()
        for k, v in raw.items():
            out[k] = cls._coerce(v)
        return out

    @staticmethod
    def _coerce(v):
        if isinstance(v, str):
            try:
                return _QtyInt(int(v))
            except ValueError:
                pass
            try:
                return _QtyFloat(float(v))
            except ValueError:
                pass
            try:
                from .resource import parse_quantity
                q = parse_quantity(v)
                iq = int(q)
                return _QtyInt(iq) if q == iq else _QtyFloat(float(q))
            except Exception:
                return v
        return v

    def __getitem__(self, key):
        return dict.get(self, key)


class _QtyMixin:
    """Coerced quantity values compare against BOTH numbers and suffixed
    string literals: device.capacity["mem"] == "40Gi" and == 40*1024**3 both
    hold (the reference's CEL environment compares typed quantities; plain
    int coercion would make the string form silently False).

    HASH/EQ ASYMMETRY (ADVICE r5): _QtyInt(8) == "8" but hash(_QtyInt(8))
    != hash("8") — the int/float __hash__ is kept deliberately so numeric
    lookups work. Consequence: coerced quantity values must NEVER be used
    as set members or dict keys alongside their raw string forms; two
    "equal" members would occupy different hash buckets. Today they are
    only ever compared (CEL selector evaluation), never keyed."""

    __slots__ = ()

    def _other(self, other):
        if isinstance(other, str):
            return _CoercingMap._coerce(other)
        return other

    def __eq__(self, other):
        other = self._other(other)
        if isinstance(other, str):
            return False
        return super().__eq__(other)

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __lt__(self, other):
        return super().__lt__(self._other(other))

    def __le__(self, other):
        return super().__le__(self._other(other))

    def __gt__(self, other):
        return super().__gt__(self._other(other))

    def __ge__(self, other):
        return super().__ge__(self._other(other))


class _QtyInt(_QtyMixin, int):
    __hash__ = int.__hash__


class _QtyFloat(_QtyMixin, float):
    __hash__ = float.__hash__
