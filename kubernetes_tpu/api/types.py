"""The API object model — the subset of staging/src/k8s.io/api/core/v1 the
scheduler consumes, flattened into plain dataclasses.

This is deliberately NOT a full apimachinery port: no GVK/serialization/
deepcopy machinery. Objects are immutable-by-convention value carriers; the
scheduler cache keys everything by uid and the device mirror interns all
strings (kubernetes_tpu/ops/codebook.py).

Reference anchors (for parity checking):
- Pod/PodSpec/Container:    staging/src/k8s.io/api/core/v1/types.go
- Taint/Toleration:         same file; matching helpers in
                            staging/src/k8s.io/component-helpers/scheduling/corev1
- Affinity/NodeSelector:    same file; matching in component-helpers nodeaffinity
- TopologySpreadConstraint: same file (v1.TopologySpreadConstraint)
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .labels import DOES_NOT_EXIST, EXISTS, GT, IN, LT, NOT_IN, LabelSelector, Requirement
from .resource import Resource

_uid_counter = itertools.count(1)


def _next_uid(prefix: str) -> str:
    return f"{prefix}-{next(_uid_counter)}"


# ---------------------------------------------------------------------------
# Taints & tolerations
# ---------------------------------------------------------------------------

NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """component-helpers/scheduling/corev1/helpers.go ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        # Empty key with Exists matches all keys & values.
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        if self.operator in (TOLERATION_OP_EQUAL, ""):
            return self.value == taint.value
        return False


def find_matching_untolerated_taint(
    taints: Sequence[Taint],
    tolerations: Sequence[Toleration],
    effects: Tuple[str, ...] = (NO_SCHEDULE, NO_EXECUTE),
) -> Optional[Taint]:
    """FindMatchingUntoleratedTaint filtered to scheduling-relevant effects
    (reference tainttoleration/taint_toleration.go Filter)."""
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return taint
    return None


# ---------------------------------------------------------------------------
# Node affinity
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class NodeSelectorTerm:
    """matchExpressions AND matchFields, both ANDed within a term."""

    match_expressions: tuple = ()  # Requirement over node labels
    match_fields: tuple = ()  # Requirement over fields (metadata.name only)

    def matches(self, node: "Node") -> bool:
        if not self.match_expressions and not self.match_fields:
            # A term with no requirements matches nothing
            # (component-helpers nodeaffinity: nil-or-empty term => no match).
            return False
        for req in self.match_expressions:
            if not req.matches(node.labels):
                return False
        for req in self.match_fields:
            if not req.matches({"metadata.name": node.name}):
                return False
        return True


@dataclass(frozen=True)
class NodeSelector:
    """ORed list of terms (requiredDuringSchedulingIgnoredDuringExecution)."""

    terms: tuple = ()

    def matches(self, node: "Node") -> bool:
        return any(t.matches(node) for t in self.terms)


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: tuple = ()  # PreferredSchedulingTerm


# ---------------------------------------------------------------------------
# Pod affinity
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PodAffinityTerm:
    """v1.PodAffinityTerm: labelSelector over pods, in namespaces, grouped by
    topologyKey. namespace_selector selects namespaces by their labels."""

    label_selector: Optional[LabelSelector] = None
    namespaces: tuple = ()
    topology_key: str = ""
    namespace_selector: Optional[LabelSelector] = None
    match_label_keys: tuple = ()
    mismatch_label_keys: tuple = ()


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple = ()  # PodAffinityTerm
    preferred: tuple = ()  # WeightedPodAffinityTerm


@dataclass(frozen=True)
class PodAntiAffinity:
    required: tuple = ()
    preferred: tuple = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# Topology spread
# ---------------------------------------------------------------------------

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

HONOR = "Honor"
IGNORE = "Ignore"

LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    node_affinity_policy: str = HONOR
    node_taints_policy: str = IGNORE
    match_label_keys: tuple = ()


# ---------------------------------------------------------------------------
# Containers, ports, volumes (scheduling-relevant slices only)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: Resource = field(default_factory=Resource)
    limits: Resource = field(default_factory=Resource)
    ports: tuple = ()  # ContainerPort
    restart_policy: Optional[str] = None  # "Always" => sidecar init container


@dataclass(frozen=True)
class Volume:
    name: str = ""
    pvc_name: Optional[str] = None  # persistentVolumeClaim.claimName


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


@dataclass
class Pod:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    # spec
    node_name: str = ""  # assigned node ("" = pending)
    scheduler_name: str = "default-scheduler"
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Resource = field(default_factory=Resource)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduling_gates: List[str] = field(default_factory=list)
    # Gang scheduling (fork's GenericWorkload surface): pods naming a
    # PodGroup are scheduled all-or-nothing with their peers
    # (schedule_one_podgroup.go; membership via workload reference).
    pod_group: str = ""  # PodGroup name in the pod's namespace ("" = none)
    # DRA: names of ResourceClaims in the pod's namespace
    # (spec.resourceClaims; api/dra.py, plugins/dynamicresources.py).
    resource_claims: List[str] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    host_network: bool = False
    # status
    phase: str = "Pending"
    nominated_node_name: str = ""
    # bookkeeping
    creation_ts: float = 0.0
    resource_version: int = 0
    deletion_ts: Optional[float] = None
    # metadata.finalizers: a delete with finalizers present only sets
    # deletion_ts; the object persists until the finalizers are removed
    # (apimachinery graceful-deletion semantics; exercised by the
    # SchedulingDeletedPodsWithFinalizers perf workload).
    finalizers: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("pod")

    # -- derived -----------------------------------------------------------

    def resource_request(self) -> Resource:
        """Effective pod resource request.

        Reference semantics (k8s.io/component-helpers resource
        PodRequests, used at noderesources/fit.go PreFilter):
          total = sum(app containers) ; fold in init containers as
          max(total, each non-sidecar init container) with sidecar
          (restartPolicy=Always) init requests added to the running total;
          then add pod overhead.
        """
        cached = getattr(self, "_req_cache", None)
        if cached is not None:
            return cached
        total = Resource()
        for c in self.containers:
            total.add(c.requests)
        sidecar_sum = Resource()
        init_max = Resource()
        for ic in self.init_containers:
            if ic.restart_policy == "Always":
                sidecar_sum.add(ic.requests)
                # A sidecar's request persists; peak during init includes
                # previously started sidecars.
                peek = sidecar_sum.clone()
                init_max.set_max(peek)
            else:
                peek = sidecar_sum.clone()
                peek.add(ic.requests)
                init_max.set_max(peek)
        total.add(sidecar_sum)
        total.set_max(init_max)
        if self.overhead is not None:
            total.add(self.overhead)
        # Memoized: container requests are spec (immutable once created);
        # callers must not mutate the returned Resource (they clone()).
        self._req_cache = total
        return total

    def host_ports(self) -> List[ContainerPort]:
        cached = getattr(self, "_hp_cache", None)
        if cached is not None:
            return cached
        out = []
        for c in self.containers:
            for p in c.ports:
                if p.host_port > 0:
                    out.append(p)
        self._hp_cache = out  # container ports are immutable spec
        return out

    def __copy__(self) -> "Pod":
        # Hand-rolled shallow copy: the dataclass default routes through
        # copyreg._reconstruct, which is ~5x slower; binds copy every pod.
        new = object.__new__(Pod)
        new.__dict__.update(self.__dict__)
        return new

    def clone_from_template(self, name: str) -> "Pod":
        """Stamp a new pod from this template prototype: a fresh identity
        (name/uid/resourceVersion) over SHARED spec objects, plus a shared
        signature-memo holder so a workload of N template pods computes its
        scheduling signature once, not N times (Framework.sign_pod).

        Mirrors how the reference perf harness stamps pods from a
        `podTemplate` (scheduler_perf.go createPodsOp → template copy with a
        generated name). Invariant required of callers: spec objects (labels,
        containers, tolerations, affinity, ...) are never mutated in place —
        the same invariant Framework.sign_pod memoization relies on."""
        shared = self.__dict__.get("_sig_shared")
        if shared is None:
            shared = self._sig_shared = {}
            # Prime the derived-spec memos once so every clone inherits them
            # instead of recomputing per instance (resource folding is ~5µs
            # and runs twice per pod on the enqueue+assume path).
            self.resource_request()
            self.host_ports()
        new = object.__new__(Pod)
        new.__dict__.update(self.__dict__)
        new.name = name
        new.uid = _next_uid("pod")
        new.resource_version = 0
        return new

    def required_node_selector_matches(self, node: "Node") -> bool:
        """nodeSelector AND requiredDuringScheduling node affinity
        (component-helpers nodeaffinity GetRequiredNodeAffinity)."""
        for k, v in self.node_selector.items():
            if node.labels.get(k) != v:
                return False
        na = self.affinity.node_affinity if self.affinity else None
        if na and na.required is not None:
            if not na.required.matches(node):
                return False
        return True


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class ImageState:
    names: tuple = ()
    size_bytes: int = 0


@dataclass
class Node:
    name: str = ""
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    # spec
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    # status
    capacity: Resource = field(default_factory=Resource)
    allocatable: Resource = field(default_factory=Resource)
    images: List[ImageState] = field(default_factory=list)
    declared_features: Dict[str, bool] = field(default_factory=dict)
    ready: bool = True
    resource_version: int = 0

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("node")
        if not self.labels.get(LABEL_HOSTNAME):
            self.labels[LABEL_HOSTNAME] = self.name


@dataclass
class Namespace:
    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# PodGroup (gang scheduling — fork's GenericWorkload surface)
# ---------------------------------------------------------------------------


@dataclass
class PodGroup:
    """All-or-nothing scheduling unit (reference schedule_one_podgroup.go)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    min_count: int = 0  # minimum members that must schedule together
    priority: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    # spec.schedulingConstraints.topology[*].key — placement-based scheduling
    # groups candidate node subsets by these topology domains (the fork's
    # topology-aware placement; topology_placement.go:120 getTopologyKey uses
    # only the first key today, and so do we).
    topology_keys: tuple = ()
    # spec.parentCompositePodGroupName (scheduling/v1beta1): membership in a
    # CompositePodGroup hierarchy — the whole TREE schedules all-or-nothing
    # (workload_forest.go, schedule_one_podgroup.go composite paths).
    parent_name: str = ""

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("pg")


@dataclass
class CompositePodGroup:
    """scheduling/v1alpha3 CompositePodGroup: an interior node of the
    workload forest — its children (PodGroups or further CompositePodGroups,
    via their parent_name) schedule together as one atomic unit rooted at
    the outermost composite (kube_features.go CompositePodGroup gate)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    parent_name: str = ""  # parent CompositePodGroup ("" = root)
    priority: int = 0

    def __post_init__(self):
        if not self.uid:
            self.uid = _next_uid("cpg")
