"""Wire-codec CLI: `python -m kubernetes_tpu.wire --bench`.

Re-exports the codec seam (:mod:`kubernetes_tpu.core.wire`) and runs the
encode/decode micro-bench the docs/WIRE.md perf table quotes: MB/s and
bytes-per-event for the JSON plane vs the binary plane, over the event
shapes that dominate the control-plane wire — a full pod ADDED, the
shard filter's slim projection, a BOUND commit, a node ADDED, and a
seq+epoch-stamped WAL/ship frame.
"""

from __future__ import annotations

import json
import sys
import time

from .core.wire import (  # noqa: F401 - re-exported seam
    BINARY,
    JSON,
    MAGIC,
    VERSION,
    WELL_KNOWN,
    WIRE_MIME,
    WireError,
    WireItem,
    accept_codec,
    client_headers,
    decode,
    decode_binary,
    encode,
    encode_binary,
    jdumps,
    jloads,
    read_event,
    scan,
    wire_enabled,
)


def _shapes():
    from .core.apiserver import node_to_wire, pod_to_wire
    from .core.watchcache import slim_object
    from .testing.wrappers import make_node, make_pod

    pod = (make_pod().name("wire-bench-000123")
           .req({"cpu": "100m", "memory": "128Mi"})
           .labels({"app": "wire-bench"}).obj())
    node = (make_node().name("node-0123")
            .capacity({"cpu": 32, "memory": "256Gi", "pods": 110})
            .zone("zone-7").obj())
    pw = pod_to_wire(pod)
    full = {"type": "ADDED", "object": pw, "rv": 123456}
    return (
        ("pod_full", full),
        ("pod_slim", {"type": "MODIFIED", "object": slim_object(pw),
                      "rv": 123457}),
        ("bound", {"type": "BOUND",
                   "object": {"uid": pw["uid"], "nodeName": "node-0123"},
                   "rv": 123458}),
        ("node_full", {"type": "ADDED", "object": node_to_wire(node),
                       "rv": 77}),
        ("wal_frame", dict(full, kind="pods", seq=987654, epoch=3)),
    )


def bench(n: int = 20000) -> dict:
    out = {"events_per_shape": n, "shapes": {}}
    for name, obj in _shapes():
        row = {}
        for codec in (JSON, BINARY):
            data = encode(obj, codec)
            t0 = time.perf_counter()
            for _ in range(n):
                encode(obj, codec)
            t_enc = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(n):
                decode(data)
            t_dec = time.perf_counter() - t0
            mb = len(data) * n / 1e6
            row[codec] = {
                "bytes_per_event": len(data),
                "encode_mb_s": round(mb / t_enc, 1),
                "decode_mb_s": round(mb / t_dec, 1),
                "encode_us": round(1e6 * t_enc / n, 2),
                "decode_us": round(1e6 * t_dec / n, 2),
            }
        row["bytes_ratio"] = round(
            row[JSON]["bytes_per_event"] / row[BINARY]["bytes_per_event"], 2)
        out["shapes"][name] = row
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--bench" in argv:
        n = 20000
        if "--n" in argv:
            n = int(argv[argv.index("--n") + 1])
        print(json.dumps(bench(n), indent=2))
        return 0
    print("usage: python -m kubernetes_tpu.wire --bench [--n N]",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
