"""Wire-codec CLI: `python -m kubernetes_tpu.wire --bench`.

Re-exports the codec seam (:mod:`kubernetes_tpu.core.wire`) and runs the
encode/decode micro-bench the docs/WIRE.md perf table quotes: MB/s and
bytes-per-event for the JSON plane vs the binary plane, over the event
shapes that dominate the control-plane wire — a full pod ADDED, the
shard filter's slim projection, a BOUND commit, a node ADDED, and a
seq+epoch-stamped WAL/ship frame.
"""

from __future__ import annotations

import json
import sys
import time

from .core.wire import (  # noqa: F401 - re-exported seam
    BINARY,
    JSON,
    MAGIC,
    SESSION_MIME,
    VERSION,
    VERSION_SESSION,
    WELL_KNOWN,
    WIRE_MIME,
    DeltaBaseMismatch,
    SessionDecoder,
    SessionEncoder,
    WireError,
    WireItem,
    accept_codec,
    accept_session,
    apply_patch,
    client_headers,
    decode,
    decode_binary,
    diff_obj,
    encode,
    encode_binary,
    jdumps,
    jloads,
    read_event,
    scan,
    stream_headers,
    wire_enabled,
)


def _shapes():
    from .core.apiserver import node_to_wire, pod_to_wire
    from .core.watchcache import slim_object
    from .testing.wrappers import make_node, make_pod

    pod = (make_pod().name("wire-bench-000123")
           .req({"cpu": "100m", "memory": "128Mi"})
           .labels({"app": "wire-bench"}).obj())
    node = (make_node().name("node-0123")
            .capacity({"cpu": 32, "memory": "256Gi", "pods": 110})
            .zone("zone-7").obj())
    pw = pod_to_wire(pod)
    full = {"type": "ADDED", "object": pw, "rv": 123456}
    return (
        ("pod_full", full),
        ("pod_slim", {"type": "MODIFIED", "object": slim_object(pw),
                      "rv": 123457}),
        ("bound", {"type": "BOUND",
                   "object": {"uid": pw["uid"], "nodeName": "node-0123"},
                   "rv": 123458}),
        ("node_full", {"type": "ADDED", "object": node_to_wire(node),
                       "rv": 77}),
        ("wal_frame", dict(full, kind="pods", seq=987654, epoch=3)),
    )


def bench(n: int = 20000) -> dict:
    out = {"events_per_shape": n, "shapes": {}}
    for name, obj in _shapes():
        row = {}
        for codec in (JSON, BINARY):
            data = encode(obj, codec)
            t0 = time.perf_counter()
            for _ in range(n):
                encode(obj, codec)
            t_enc = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(n):
                decode(data)
            t_dec = time.perf_counter() - t0
            mb = len(data) * n / 1e6
            row[codec] = {
                "bytes_per_event": len(data),
                "encode_mb_s": round(mb / t_enc, 1),
                "decode_mb_s": round(mb / t_dec, 1),
                "encode_us": round(1e6 * t_enc / n, 2),
                "decode_us": round(1e6 * t_dec / n, 2),
            }
        row["bytes_ratio"] = round(
            row[JSON]["bytes_per_event"] / row[BINARY]["bytes_per_event"], 2)
        out["shapes"][name] = row
    return out


def _delta_corpora():
    """The three event classes that dominate MODIFIED churn at hollow
    scale: a node heartbeat touch, a capacity drift (hollow/plane.py
    `_drift_one` — allocatable.cpu step), and a BOUND commit. Each row is
    ``(name, base_wire_or_None, event)``; base None means the event has
    no delta twin (BOUND ships full — small already). The node is the
    hollow-profile wire shape (labels/taints/scalars — what
    hollow/profile.py node_wire actually registers at 50k-node scale),
    not a minimal fixture: the whole point of the delta plane is that
    frame size tracks the CHANGED fields, not the object, so the corpus
    must carry a realistically sized object."""
    from .core.apiserver import pod_to_wire
    from .testing.wrappers import make_pod

    nw = {
        "name": "node-0123", "uid": "node-0123",
        "labels": {
            "kubernetes.io/hostname": "node-0123",
            "topology.kubernetes.io/zone": "zone-7",
            "node.kubernetes.io/instance-type": "tpu-v4-8",
            "cloud.google.com/gke-nodepool": "tpu-pool-a",
        },
        "unschedulable": False,
        "allocatable": {"cpu": 32000, "memory": 274877906944,
                        "ephemeral": 107374182400, "pods": 110,
                        "scalar": {"tpu.google.com/v4": 4}},
        "taints": [{"key": "google.com/tpu", "value": "present",
                    "effect": "NoSchedule"}],
        "declaredFeatures": {},
    }
    pod = (make_pod().name("wire-bench-000123")
           .req({"cpu": "100m", "memory": "128Mi"})
           .labels({"app": "wire-bench"}).obj())
    pw = pod_to_wire(pod)
    hb = dict(nw, heartbeat=1723012345.25)
    drift = dict(nw, allocatable=dict(nw["allocatable"], cpu=31000))
    return (
        ("heartbeat", nw,
         {"type": "MODIFIED", "object": hb, "rv": 1001}),
        ("drift", nw,
         {"type": "MODIFIED", "object": drift, "rv": 1002}),
        ("bound", None,
         {"type": "BOUND",
          "object": {"uid": pw["uid"], "nodeName": "node-0123"},
          "rv": 1003}),
    )


def encode_ab(n: int = 20000) -> dict:
    """The PR-18 encode-path A/B: full-binary vs DELTA-on-a-session
    stream vs C-json, µs/event + bytes/event per corpus. Session numbers
    are steady-state (the table is primed with one frame first — per
    connection that cost is paid once). ``mint_us`` is the server-side
    diff cost, paid once per event and shared by every attached stream
    and the WAL; ``encode_us`` is the per-stream frame cost the guard
    test compares against full binary."""
    out = {"bench": "wire-delta-ab", "events_per_corpus": n, "corpora": {}}
    for name, base, event in _delta_corpora():
        row = {}
        for label, codec in (("json_full", JSON), ("binary_full", BINARY)):
            data = encode(event, codec)
            t0 = time.perf_counter()
            for _ in range(n):
                encode(event, codec)
            dt = time.perf_counter() - t0
            row[label] = {"bytes_per_event": len(data),
                          "encode_us": round(1e6 * dt / n, 2)}
        if base is not None:
            t0 = time.perf_counter()
            for _ in range(n):
                diff_obj(base, event["object"])
            mint = time.perf_counter() - t0
            wire_ev = {"type": "DELTA", "rv": event["rv"],
                       "key": "node-0123", "baseRv": event["rv"] - 1,
                       "patch": diff_obj(base, event["object"])}
        else:
            mint = 0.0
            wire_ev = event  # no delta twin: session full frame
        enc = SessionEncoder()
        enc.encode(wire_ev)  # prime: defines go out once per connection
        data = enc.encode(wire_ev)
        t0 = time.perf_counter()
        for _ in range(n):
            enc.encode(wire_ev)
        dt = time.perf_counter() - t0
        row["binary_delta"] = {
            "bytes_per_event": len(data),
            "encode_us": round(1e6 * dt / n, 2),
            "mint_us": round(1e6 * mint / n, 2)}
        row["delta_vs_full_bytes"] = round(
            row["binary_full"]["bytes_per_event"] / max(1, len(data)), 1)
        out["corpora"][name] = row
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--bench" in argv:
        n = 20000
        if "--n" in argv:
            n = int(argv[argv.index("--n") + 1])
        print(json.dumps(bench(n), indent=2))
        # The delta A/B emits ONE JSON line (CI parses it as a record).
        print(json.dumps(encode_ab(n), separators=(",", ":")))
        return 0
    print("usage: python -m kubernetes_tpu.wire --bench [--n N]",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
