"""Retry/backoff machinery + the device-path circuit breaker.

Re-expresses the slice of client-go's wait/backoff stack the scheduler
actually leans on (k8s.io/apimachinery/pkg/util/wait Backoff{Duration,
Factor, Jitter, Steps} and client-go rest/request.go retry-on-transient):
exponential backoff with deterministic seeded jitter, a retriable-error
taxonomy shared by every boundary (REST writes, async API dispatcher,
sidecar RPC), and a consecutive-failure circuit breaker that pins the
device scheduling path to the host Evaluator for a cool-down after
repeated kernel failures (docs/RESILIENCE.md).

Determinism: jitter comes from a `random.Random(seed)` owned by the
RetryConfig, never the global RNG — chaos tests (tests/test_faults.py)
replay identical delay sequences from identical seeds.
"""

from __future__ import annotations

import errno
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional


class TransientAPIError(Exception):
    """A retriable control-plane failure: the request may succeed if
    replayed (apiserver 5xx / timeout / reset mid-write). Fault injection
    (testing/faults.py) raises exactly this; real transports map their
    transient failures onto it or onto the stdlib types is_retriable
    recognizes."""


# OS-level errno values that signal a transient transport failure.
_TRANSIENT_ERRNOS = frozenset({
    errno.ECONNRESET, errno.ECONNREFUSED, errno.ECONNABORTED,
    errno.EPIPE, errno.ETIMEDOUT, errno.EAGAIN,
})


def is_retriable(exc: BaseException) -> bool:
    """The shared retriable-error taxonomy (client-go's IsConnectionReset /
    retryable-status-code checks collapsed to one predicate). Semantic
    errors (KeyError pod-not-found, ValueError, programming bugs) are NOT
    retriable — replaying them can only repeat the failure."""
    import http.client as _hc
    if isinstance(exc, TransientAPIError):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError)):
        # ConnectionResetError/BrokenPipeError/ConnectionRefusedError and
        # socket.timeout are subclasses.
        return True
    # urllib.error.HTTPError: retry server-side (5xx) failures — and 429
    # TooManyRequests, the flow-control shed (core/flowcontrol.py): the
    # request was REJECTED before any state changed, so a replay is safe
    # by construction, and retry_call honors its Retry-After.
    code = getattr(exc, "code", None)
    if isinstance(code, int):
        return code == 429 or code >= 500
    # urllib.error.URLError wraps the transport failure in .reason.
    reason = getattr(exc, "reason", None)
    if isinstance(reason, BaseException) and reason is not exc:
        return is_retriable(reason)
    if isinstance(exc, _hc.HTTPException):
        # RemoteDisconnected / BadStatusLine / IncompleteRead: the
        # connection died mid-exchange — a replay gets a fresh connection.
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


def retry_after_of(exc: BaseException) -> Optional[float]:
    """The server's Retry-After hint off a 429 (or 503) reply, in seconds;
    None when the reply carries no parseable hint. This is the ONE place
    the client stack parses the header — every retry loop on the shed
    surface routes through retry_call, which calls this (the
    ``shed-discipline`` analyzer rule pins the seam)."""
    if getattr(exc, "code", None) not in (429, 503):
        return None
    headers = getattr(exc, "headers", None)
    if headers is None:
        headers = getattr(exc, "hdrs", None)
    if headers is None:
        return None
    try:
        value = headers.get("Retry-After")
    except AttributeError:
        return None
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        return None


@dataclass
class RetryConfig:
    """wait.Backoff analogue. `max_attempts` counts total tries (1 = no
    retry). `jitter` is a +/- fraction of each delay; the seeded RNG makes
    the whole delay sequence reproducible."""

    initial_backoff: float = 0.01
    max_backoff: float = 1.0
    multiplier: float = 2.0
    jitter: float = 0.1
    max_attempts: int = 4
    seed: Optional[int] = 0
    retriable: Callable[[BaseException], bool] = field(default=is_retriable)
    # Ceiling for Retry-After-driven delays (a shed server names its own
    # horizon; a buggy or hostile header must not park a client forever).
    retry_after_cap: float = 30.0

    def delays(self) -> Iterator[float]:
        """The (max_attempts - 1) sleep durations between tries."""
        rng = random.Random(self.seed)
        d = self.initial_backoff
        for _ in range(max(0, self.max_attempts - 1)):
            j = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield max(0.0, min(self.max_backoff, d) * j)
            d *= self.multiplier


def retry_call(fn: Callable, config: Optional[RetryConfig] = None,
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep):
    """Run `fn()`; on a retriable failure, back off and replay, up to
    config.max_attempts total tries. Non-retriable exceptions (and the
    final retriable one) propagate. `on_retry(attempt_no, exc)` fires
    before each sleep — callers hang metrics/logging off it.

    A reply carrying ``Retry-After`` (the 429 flow-control shed,
    core/flowcontrol.py) overrides the exponential schedule with
    **decorrelated jitter anchored at the server's hint**: sleep uniformly
    in [hint, max(1.5*hint, 3*previous_sleep)], capped at
    ``retry_after_cap``. The hint is a floor (coming back sooner just gets
    shed again); the spread keeps a herd of shed clients from
    re-synchronizing into the next wave, and the 3x-previous growth backs a
    persistently-shed client off harder each round."""
    cfg = config or RetryConfig()
    attempt = 0
    delays = cfg.delays()
    # Decorrelated-jitter state, seeded independently of the exponential
    # schedule's RNG so adding a 429 mid-sequence never perturbs the
    # deterministic delay replay chaos tests assert on.
    rng = random.Random(None if cfg.seed is None else cfg.seed ^ 0x5EED)
    prev_ra_sleep = 0.0
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 - predicate decides
            attempt += 1
            if not cfg.retriable(e):
                raise
            try:
                delay = next(delays)
            except StopIteration:
                raise e from None
            ra = retry_after_of(e)
            if ra is not None:
                hi = max(ra * 1.5, prev_ra_sleep * 3.0)
                delay = min(cfg.retry_after_cap,
                            rng.uniform(ra, max(hi, ra + 1e-9)))
                prev_ra_sleep = delay
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)


class CircuitBreaker:
    """Consecutive-failure breaker for the device scheduling path.

    closed    — device path allowed; failures count.
    open      — after `failure_threshold` consecutive failures: device path
                pinned off for `cooldown` seconds (host Evaluator owns every
                cycle — the crash-proof degradation mode).
    half-open — cooldown elapsed: ONE probe is allowed; success closes the
                breaker, failure re-opens it for another cooldown.

    `clock` is injectable so chaos tests step time deterministically.
    """

    def __init__(self, failure_threshold: int = 3, cooldown: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown = cooldown
        self.clock = clock
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.open_count = 0  # times the breaker tripped (metrics)

    @property
    def state(self) -> str:
        if self.opened_at is None:
            return "closed"
        if self.clock() - self.opened_at >= self.cooldown:
            return "half-open"
        return "open"

    def allows(self) -> bool:
        """May the device path run this cycle? (closed or half-open probe)"""
        return self.state != "open"

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self.opened_at = None

    def record_failure(self) -> bool:
        """Count one failure; returns True when this failure OPENED (or
        re-opened) the breaker."""
        if self.state == "half-open":
            # Failed probe: restart the cool-down.
            self.opened_at = self.clock()
            self.open_count += 1
            return True
        self.consecutive_failures += 1
        if (self.opened_at is None
                and self.consecutive_failures >= self.failure_threshold):
            self.opened_at = self.clock()
            self.open_count += 1
            return True
        return False
