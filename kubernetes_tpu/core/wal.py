"""Durable apiserver store: write-ahead log + snapshot compaction.

Re-expresses the persistence seam the reference gets from etcd
(etcd3/store.go:284 — every apiserver write goes through the etcd3 store;
watch resumption is served from a resourceVersion-indexed history that
survives apiserver restarts): each committed write appends ONE JSON record
to an append-only log, a periodic snapshot compacts the log, and a restart
replays snapshot+WAL to recover pods/nodes/bindings AND the watch plane's
per-kind rv counters plus the boot **epoch** — so a reflector reconnecting
with its last resourceVersion is served the PR-1 ``RESUME`` path (replay of
exactly the missed events) instead of a full re-list, across a ``kill -9``
of the apiserver process.

Layout of ``data_dir``:

- ``meta.json``    — ``{"epoch": ..., "repl_epoch": N}`` written at first
  boot; the watch epoch a recovered server re-announces on SYNC/RESUME
  markers, which is what lets clients resume (PR 1's epoch guard rejects
  resumes against a server whose counters restarted; a recovered server's
  counters do NOT restart, so the SAME epoch is re-used deliberately).
  ``repl_epoch`` is the monotonic **replication fencing epoch**
  (kubernetes_tpu/replication/): bumped exactly once per follower
  promotion, stamped on every shipped WAL frame, and persisted here so a
  restarted replica can never ship or accept frames from a deposed
  leader's generation.
- ``snapshot.json`` — the latest compaction: full object state + the rv
  counters at the moment of the snapshot. Written atomically
  (tmp + ``os.replace``); the WAL is reset right after.
- ``wal.log``       — one record per committed write SINCE the snapshot:
  ``{"kind": "pods"|"nodes", "type": "ADDED"|..., "object": {...wire...},
  "rv": N}`` — identical in content to the watch event the write
  broadcast, so recovery can rebuild the watch backlog from the WAL tail
  and serve incremental resumes across the restart. Records are BINARY
  wire frames by default (core/wire.py: length-prefixed, interned keys,
  ~3x smaller than the JSON lines PR 9 shipped); replay sniffs each
  record's first byte, so an old JSON WAL — or a mixed file where a
  binary-default server appended to a JSON history — replays
  transparently, record by record. A binary WAL may persist a MODIFIED
  write as its **DELTA twin** (PR 18, docs/WIRE.md §DELTA: a field-path
  patch against the previous record's object state); recovery
  materializes each patch against the wire state it has replayed so far
  and QUARANTINES on a missing/mismatched base — a patch is never
  applied onto a divergent history. JSON WAL mode always stores full
  records (the compat plane is delta-free by construction). Session
  frames (VERSION_SESSION) never appear at rest: their intern table
  lives on one connection, so scan() treats one as a torn record.

Crash contract: records are framed (binary: magic + version + varint
length; JSON compat: ``json\\n`` lines) with a flush per record
(``fsync=True`` additionally fsyncs — survives power loss, not just
process death). A ``kill -9`` can leave at most one torn
(partial/invalid) final record; replay detects it — a length prefix that
outruns the file, an undecodable payload, a missing newline — discards
it, truncates the log back to the last good frame, and counts it in
``torn_records_discarded``: the write it belonged to never got a reply,
so the client's retry layer replays it against the recovered server (the
binding subresource is idempotent for same-node replays). The torn-tail
semantics are byte-for-byte identical across codecs (tests/test_wire.py
truncation fuzz).

Corruption in the MIDDLE of the log is a different failure class: the
binary WAL's version-2 frames carry a per-record CRC32 trailer, and a
complete record whose CRC mismatches quarantines recovery
(:class:`WALQuarantineError`) instead of truncating — every record after
the damage is an acked write that silent truncation would destroy.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from . import wire


class WALQuarantineError(RuntimeError):
    """Recovery refused: a record in the MIDDLE of the WAL failed its
    CRC32 (wire.CorruptFrameError) — bit rot, a bad disk, or a hostile
    edit. Unlike a torn tail (one unacked final write, safely truncated),
    silently truncating here would drop every intact record AFTER the
    damage: acked writes. The WAL file is left untouched as evidence;
    the operator repairs or restores from a replica/snapshot."""

    def __init__(self, path: str, offset: int, cause: Exception):
        super().__init__(
            f"WAL quarantined: corrupt record in {path} at byte offset "
            f"{offset} ({cause}); file left intact for inspection")
        self.path = path
        self.offset = offset


class DurableStore:
    """File mechanics for the apiserver's durability layer (core/apiserver.py
    owns the wire codec and store application; this class owns bytes)."""

    META = "meta.json"
    SNAP = "snapshot.json"
    WAL = "wal.log"

    def __init__(self, data_dir: str, fsync: bool = False,
                 snapshot_every: int = 2048, codec: Optional[str] = None):
        self.data_dir = data_dir
        self.fsync = fsync
        self.snapshot_every = snapshot_every
        # WAL record codec for NEW appends (replay always sniffs, so a
        # data dir written by any codec recovers under any default). The
        # binary default carries a per-record CRC32 trailer (version-2
        # frames, core/wire.py): a corrupt MIDDLE record quarantines
        # recovery instead of silently truncating acked writes away.
        self.codec = codec or (wire.BINARY_CRC if wire.wire_enabled()
                               else wire.JSON)
        os.makedirs(data_dir, exist_ok=True)
        self._wal_path = os.path.join(data_dir, self.WAL)
        self._wal_fh = None
        self._since_snapshot = 0
        # observability (surfaced by the apiserver's recovery log line)
        self.replayed_records = 0
        self.torn_records_discarded = 0
        self.crc_failures = 0  # corrupt middle records (quarantined boot)
        self.compactions = 0
        meta = self._read_json(self.META, {})
        self.epoch: Optional[str] = meta.get("epoch")
        # Replication fencing epoch (monotonic int, bumped per promotion).
        # 1 = the first leader generation of this data dir's history.
        self.repl_epoch: int = int(meta.get("repl_epoch", 1))
        # Persisted replication role: a DEPOSED leader (or a follower) must
        # never restart read-write — it would accept acked writes into a
        # forked history the real plane never sees.
        self.role: str = meta.get("role", "leader")
        self.leader_url: str = meta.get("leader_url", "")

    # -- small file helpers -------------------------------------------------

    def _read_json(self, name: str, default):
        # meta/snapshot stay JSON deliberately: they are the small,
        # low-rate, operator-inspectable files (the debug plane); only the
        # per-write WAL records ride the binary codec.
        try:
            with open(os.path.join(self.data_dir, name), "rb") as fh:
                return wire.jloads(fh.read())
        except (FileNotFoundError, ValueError):
            return default

    def _write_json_atomic(self, name: str, obj) -> None:
        path = os.path.join(self.data_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(wire.jdumps(obj))
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, path)

    # -- boot ---------------------------------------------------------------

    def _write_meta(self) -> None:
        self._write_json_atomic(self.META, {
            "epoch": self.epoch, "repl_epoch": self.repl_epoch,
            "role": self.role, "leader_url": self.leader_url})

    def init_epoch(self, epoch: str) -> None:
        """First boot of this data_dir: persist the freshly minted epoch so
        every future recovery re-announces it."""
        self.epoch = epoch
        self._write_meta()

    def set_repl_epoch(self, repl_epoch: int) -> None:
        """Persist a replication-epoch bump (promotion fencing) BEFORE the
        new leader accepts its first write: a promoted replica that crashes
        and recovers must come back in the generation it won, or its own
        stale-frame rejection breaks."""
        self.repl_epoch = int(repl_epoch)
        self._write_meta()

    def set_role(self, role: str, leader_url: str = "") -> None:
        """Persist a role transition (promotion / deposition) atomically
        with the current epochs: a deposed leader that restarts must come
        back fenced (follower, redirecting at the winner), never
        read-write into a forked history."""
        self.role = role
        self.leader_url = leader_url
        self._write_meta()

    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """Read (snapshot, wal_records) for recovery. Discards a torn final
        WAL record (truncating the file back to the last good frame) and
        opens the WAL for append. A record failing its CRC32 mid-log
        raises :class:`WALQuarantineError` — the file is left byte-for-
        byte intact (no truncation, no append handle) so the damage can
        be inspected or repaired; ``crc_failures`` is incremented first
        so repeated boots report deterministically."""
        snap = self._read_json(self.SNAP, None)
        records: List[dict] = []
        good_offset = 0
        try:
            with open(self._wal_path, "rb") as fh:
                buf = fh.read()
        except FileNotFoundError:
            buf = b""
        pos = 0
        while pos < len(buf):
            # Per-record codec sniff (core/wire.py): a binary frame, or a
            # JSON line from an old (or mixed) WAL. None = the tail from
            # here on is torn — an incomplete length-prefixed frame, an
            # undecodable payload, a missing newline — and untrusted.
            try:
                got = wire.scan(buf, pos)
            except wire.CorruptFrameError as e:
                self.crc_failures += 1
                raise WALQuarantineError(self._wal_path, pos, e) from e
            if got is None:
                self.torn_records_discarded += 1
                break
            rec, pos = got
            records.append(rec)
            good_offset = pos
        if good_offset < len(buf):
            with open(self._wal_path, "r+b") as fh:
                fh.truncate(good_offset)
        self.replayed_records = len(records)
        self._wal_fh = open(self._wal_path, "ab")
        self._since_snapshot = len(records)
        return snap, records

    # -- the write path -----------------------------------------------------

    def append(self, record) -> None:
        """Append one committed write (a dict, or a pre-encoded
        :class:`~.wire.WireItem` whose cached bytes are SHARED with the
        replication ship fanout — one binary encode serves the disk and
        every binary follower). Caller serializes (the apiserver's
        broadcast lock); a flush per record bounds loss to one torn
        frame."""
        if self._wal_fh is None:
            self._wal_fh = open(self._wal_path, "ab")
        if isinstance(record, wire.WireItem):
            data = record.bytes(self.codec)
        else:
            data = wire.encode(record, self.codec)
        self._wal_fh.write(data)
        self._wal_fh.flush()
        if self.fsync:
            os.fsync(self._wal_fh.fileno())
        self._since_snapshot += 1

    def should_compact(self) -> bool:
        return self._since_snapshot >= self.snapshot_every

    def write_snapshot(self, snap: dict) -> None:
        """Compaction: atomically persist the full state, then reset the WAL
        (its records are now folded into the snapshot)."""
        self._write_json_atomic(self.SNAP, snap)
        if self._wal_fh is not None:
            self._wal_fh.close()
        self._wal_fh = open(self._wal_path, "wb")
        self._since_snapshot = 0
        self.compactions += 1

    def close(self) -> None:
        if self._wal_fh is not None:
            self._wal_fh.close()
            self._wal_fh = None
