"""Plugin registry and default profile.

Mirrors pkg/scheduler/framework/plugins/registry.go (NewInTreeRegistry :46)
and the default plugin set + weights in
pkg/scheduler/apis/config/v1/default_plugins.go:32-60:
SchedulingGates, PrioritySort, NodeName, NodeUnschedulable, TaintToleration
w=3, NodeAffinity w=2, NodePorts, NodeResourcesFit w=1, PodTopologySpread w=2,
InterPodAffinity w=2, NodeResourcesBalancedAllocation w=1, ImageLocality w=1,
DefaultBinder (volume plugins arrive with the volume subsystem).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..plugins.basic import (
    DefaultBinder,
    ImageLocality,
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from ..plugins.interpodaffinity import InterPodAffinity
from ..plugins.noderesources import BalancedAllocation, Fit
from ..plugins.podtopologyspread import PodTopologySpread
from ..plugins.extras import (
    DeferredPodScheduling,
    GangScheduling,
    NodeDeclaredFeatures,
)
from ..plugins.dynamicresources import DynamicResources
from ..plugins.topologyaware import PodGroupPodsCount, TopologyPlacementGenerator
from ..plugins.preemption import DefaultPreemption
from ..plugins.volumes import (
    NodeVolumeLimits,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
)
from .framework import Framework

# name -> factory(handle, args) (plugins/registry.go NewInTreeRegistry)
IN_TREE_REGISTRY: Dict[str, Callable] = {
    "SchedulingGates": lambda h, **kw: SchedulingGates(),
    "PrioritySort": lambda h, **kw: PrioritySort(),
    "NodeName": lambda h, **kw: NodeName(),
    "NodeUnschedulable": lambda h, **kw: NodeUnschedulable(),
    "TaintToleration": lambda h, **kw: TaintToleration(),
    "NodeAffinity": lambda h, **kw: NodeAffinity(),
    "NodePorts": lambda h, **kw: NodePorts(),
    "NodeResourcesFit": lambda h, **kw: Fit(handle=h, **kw),
    "PodTopologySpread": lambda h, **kw: PodTopologySpread(handle=h, **kw),
    "InterPodAffinity": lambda h, **kw: InterPodAffinity(handle=h, **kw),
    "NodeResourcesBalancedAllocation": lambda h, **kw: BalancedAllocation(**kw),
    "ImageLocality": lambda h, **kw: ImageLocality(handle=h),
    "DefaultPreemption": lambda h, **kw: DefaultPreemption(handle=h, **kw),
    "VolumeRestrictions": lambda h, **kw: VolumeRestrictions(handle=h),
    "NodeVolumeLimits": lambda h, **kw: NodeVolumeLimits(handle=h),
    "VolumeBinding": lambda h, **kw: VolumeBinding(handle=h),
    "VolumeZone": lambda h, **kw: VolumeZone(handle=h),
    "NodeDeclaredFeatures": lambda h, **kw: NodeDeclaredFeatures(),
    "TopologyPlacementGenerator": lambda h, **kw: TopologyPlacementGenerator(handle=h),
    "PodGroupPodsCount": lambda h, **kw: PodGroupPodsCount(handle=h),
    "DynamicResources": lambda h, **kw: DynamicResources(handle=h),
    "DeferredPodScheduling": lambda h, **kw: DeferredPodScheduling(**kw),
    "GangScheduling": lambda h, **kw: GangScheduling(handle=h, **kw),
    "DefaultBinder": lambda h, **kw: DefaultBinder(handle=h),
}

# (plugin name, weight) — default_plugins.go:32-60 ordering and weights.
DEFAULT_PLUGINS: Tuple[Tuple[str, int], ...] = (
    ("SchedulingGates", 0),
    ("PrioritySort", 0),
    ("NodeName", 0),
    ("NodeUnschedulable", 0),
    ("TaintToleration", 3),
    ("NodeAffinity", 2),
    ("NodePorts", 0),
    ("NodeResourcesFit", 1),
    ("VolumeRestrictions", 0),
    ("NodeVolumeLimits", 0),
    ("VolumeBinding", 0),
    ("VolumeZone", 0),
    ("PodTopologySpread", 2),
    ("InterPodAffinity", 2),
    ("DefaultPreemption", 0),
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("DefaultBinder", 0),
)


def build_framework(
    handle,
    profile_name: str = "default-scheduler",
    plugins: Sequence[Tuple[str, int]] = DEFAULT_PLUGINS,
    plugin_args: Optional[Dict[str, dict]] = None,
) -> Framework:
    plugin_args = plugin_args or {}
    instances = []
    for name, weight in plugins:
        factory = IN_TREE_REGISTRY[name]
        instances.append((factory(handle, **plugin_args.get(name, {})), weight))
    fw = Framework(profile_name=profile_name, plugins=instances)
    # Late-bind plugins that dispatch back into the framework (preemption's
    # dry runs re-enter RunFilterPlugins — reference wires this through
    # framework.Handle; here a post-construction hook avoids the cycle).
    for p, _ in instances:
        hook = getattr(p, "set_framework", None)
        if hook is not None:
            hook(fw)
    return fw


def _gated_extras(handle) -> Tuple[Tuple[str, int], ...]:
    """Feature-gated default-profile additions (the reference wires gated
    plugins into the default set at registry build time): NodeDeclaredFeatures
    rides the NodeDeclaredFeatures gate (fork plugin, default on) — disabling
    the gate removes the plugin, which is what the
    NodeDeclaredFeaturesDisabled perf variants toggle."""
    extras: Tuple[Tuple[str, int], ...] = ()
    gates = getattr(handle, "gates", None)
    if gates is not None:
        try:
            if gates.enabled("NodeDeclaredFeatures"):
                extras += (("NodeDeclaredFeatures", 0),)
        except ValueError:
            pass
    return extras


def default_profiles(handle) -> Dict[str, Framework]:
    return {"default-scheduler": build_framework(
        handle, plugins=DEFAULT_PLUGINS + _gated_extras(handle))}


# DEFAULT_PLUGINS + the gang/placement set (GenericWorkload-gated in the
# reference: gangscheduling.go, topology_placement.go, podgroup_pods_count.go;
# NodeResourcesFit already implements PlacementScore).
GANG_PLACEMENT_PLUGINS: Tuple[Tuple[str, int], ...] = DEFAULT_PLUGINS + (
    ("GangScheduling", 0),
    ("TopologyPlacementGenerator", 0),
    ("PodGroupPodsCount", 1),
)


def gang_placement_profiles(handle) -> Dict[str, Framework]:
    return {"default-scheduler": build_framework(
        handle, plugins=GANG_PLACEMENT_PLUGINS + _gated_extras(handle))}


def fit_only_profiles(handle) -> Dict[str, Framework]:
    """The BASELINE.json config[0] profile: NodeResourcesFit-only + binder."""
    plugins = (
        ("PrioritySort", 0),
        ("NodeResourcesFit", 1),
        ("DefaultBinder", 0),
    )
    return {"default-scheduler": build_framework(handle, plugins=plugins)}
