"""NodeInfo / PodInfo — the per-node aggregate the scheduler filters against.

Re-expresses pkg/scheduler/framework/types.go (NodeInfo struct at types.go:173):
each node carries its pod list, the summed `requested` resource vector,
host-port usage, and affinity-relevant pod sublists, plus a monotonically
increasing `generation` that drives incremental snapshotting
(backend/cache/cache.go:206 UpdateSnapshot).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api.resource import Resource
from ..api.types import Node, Pod

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


@dataclass
class PodInfo:
    """Wraps a Pod with precomputed scheduling-relevant views
    (reference framework/types.go PodInfo: cached affinity terms, request)."""

    pod: Pod
    required_affinity_terms: tuple = ()
    required_anti_affinity_terms: tuple = ()
    preferred_affinity_terms: tuple = ()
    preferred_anti_affinity_terms: tuple = ()
    cached_request: Optional[Resource] = None

    @classmethod
    def of(cls, pod: Pod) -> "PodInfo":
        aff = pod.affinity
        req_aff = req_anti = pref_aff = pref_anti = ()
        if aff is not None:
            if aff.pod_affinity is not None:
                req_aff = tuple(aff.pod_affinity.required)
                pref_aff = tuple(aff.pod_affinity.preferred)
            if aff.pod_anti_affinity is not None:
                req_anti = tuple(aff.pod_anti_affinity.required)
                pref_anti = tuple(aff.pod_anti_affinity.preferred)
        return cls(
            pod=pod,
            required_affinity_terms=req_aff,
            required_anti_affinity_terms=req_anti,
            preferred_affinity_terms=pref_aff,
            preferred_anti_affinity_terms=pref_anti,
            cached_request=pod.resource_request(),
        )

    @property
    def request(self) -> Resource:
        if self.cached_request is None:
            self.cached_request = self.pod.resource_request()
        return self.cached_request


class NodeInfo:
    """Aggregated node state. Mutable; every mutation bumps `generation`."""

    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "pods_with_required_anti_affinity",
        "requested",
        "non_zero_requested",
        "allocatable",
        "used_ports",
        "pvc_ref_counts",
        "image_states",
        "generation",
    )

    # Default requests for the "non-zero" aggregate used by scoring
    # (reference framework/types.go DefaultMilliCPURequest/DefaultMemoryRequest).
    DEFAULT_MILLI_CPU = 100
    DEFAULT_MEMORY = 200 * 1024 * 1024

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = node
        self.pods: List[PodInfo] = []
        self.pods_with_affinity: List[PodInfo] = []
        self.pods_with_required_anti_affinity: List[PodInfo] = []
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = node.allocatable.clone() if node else Resource()
        # (protocol, host_ip, port) tuples
        self.used_ports: Set[Tuple[str, str, int]] = set()
        self.pvc_ref_counts: Dict[str, int] = {}
        self.image_states: Dict[str, int] = {}  # image name -> size bytes
        if node:
            for img in node.images:
                for name in img.names:
                    self.image_states[name] = img.size_bytes
        self.generation = next_generation()

    # -- mutations ---------------------------------------------------------

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = node.allocatable.clone()
        self.image_states = {}
        for img in node.images:
            for name in img.names:
                self.image_states[name] = img.size_bytes
        self.generation = next_generation()

    def add_pod(self, pi: PodInfo) -> None:
        self.pods.append(pi)
        if pi.required_affinity_terms or pi.preferred_affinity_terms \
                or pi.required_anti_affinity_terms or pi.preferred_anti_affinity_terms:
            self.pods_with_affinity.append(pi)
        if pi.required_anti_affinity_terms:
            self.pods_with_required_anti_affinity.append(pi)
        req = pi.request
        self.requested.add(req)
        self.non_zero_requested.milli_cpu += req.milli_cpu or self.DEFAULT_MILLI_CPU
        self.non_zero_requested.memory += req.memory or self.DEFAULT_MEMORY
        for p in pi.pod.host_ports():
            self.used_ports.add((p.protocol, p.host_ip, p.host_port))
        for v in pi.pod.volumes:
            if v.pvc_name:
                key = f"{pi.pod.namespace}/{v.pvc_name}"
                self.pvc_ref_counts[key] = self.pvc_ref_counts.get(key, 0) + 1
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        for i, pi in enumerate(self.pods):
            if pi.pod.uid == pod.uid:
                self.pods.pop(i)
                self.pods_with_affinity = [p for p in self.pods_with_affinity if p.pod.uid != pod.uid]
                self.pods_with_required_anti_affinity = [
                    p for p in self.pods_with_required_anti_affinity if p.pod.uid != pod.uid
                ]
                req = pi.request
                self.requested.sub(req)
                self.non_zero_requested.milli_cpu -= req.milli_cpu or self.DEFAULT_MILLI_CPU
                self.non_zero_requested.memory -= req.memory or self.DEFAULT_MEMORY
                for p in pi.pod.host_ports():
                    self.used_ports.discard((p.protocol, p.host_ip, p.host_port))
                for v in pi.pod.volumes:
                    if v.pvc_name:
                        key = f"{pi.pod.namespace}/{v.pvc_name}"
                        n = self.pvc_ref_counts.get(key, 0) - 1
                        if n <= 0:
                            self.pvc_ref_counts.pop(key, None)
                        else:
                            self.pvc_ref_counts[key] = n
                self.generation = next_generation()
                return True
        return False

    # -- views -------------------------------------------------------------

    @property
    def name(self) -> str:
        return self.node.name if self.node else ""

    def snapshot_clone(self) -> "NodeInfo":
        """Clone for an immutable per-cycle snapshot. Pod lists are shared
        copy-on-write style: list objects are copied, PodInfo entries shared."""
        c = NodeInfo.__new__(NodeInfo)
        c.node = self.node
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        c.requested = self.requested.clone()
        c.non_zero_requested = self.non_zero_requested.clone()
        c.allocatable = self.allocatable.clone()
        c.used_ports = set(self.used_ports)
        c.pvc_ref_counts = dict(self.pvc_ref_counts)
        c.image_states = dict(self.image_states)
        c.generation = self.generation
        return c
