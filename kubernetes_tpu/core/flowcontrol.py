"""API priority & fairness: overload-protected admission for the apiserver.

Re-expresses the reference's request-admission layer
(``staging/src/k8s.io/apiserver/pkg/server/filters/priority-and-fairness.go``
over ``util/flowcontrol``): every mutating request is classified into a
**flow** inside a **priority level**, and each level admits through
bounded-concurrency **shuffle-sharded fair queues** — so one adversarial
tenant hammering creates/binds degrades *its own* lane, never the whole
write plane, and never the control traffic failover depends on.

The three levels the apiserver ships with (:func:`default_levels`):

- ``exempt`` — replication ship/ack, lease CAS, leader announcements,
  peer-topology injection: the traffic *promotion itself* depends on.
  Never queued, never shed — a tenant flood must not be able to convoy a
  lease renewal behind its own backlog (the failover-starvation incident
  class this module exists for).
- ``system`` — node lifecycle writes (registration, heartbeats, drift,
  churn): the kubelet/hollow plane. One shared flow, bounded seats.
- ``workload`` — pod creates/binds/deletes, flow-keyed **by namespace**.
  This is where tenants meet: shuffle-sharded queue assignment keeps a
  flood tenant's backlog in *its* hand of queues, weighted round-robin
  dequeue serves the remaining flows proportionally, and a full queue
  sheds with **429 + Retry-After** — loudly, never a silent drop, and
  never while holding the server's ``_write_lock`` (the shed path runs
  entirely before admission; the ``shed-discipline`` analyzer rule pins
  this).

Locking: one controller-private lock. ``admit`` blocks (outside that
lock) on a per-request event until a seat frees or ``max_wait`` elapses —
timeout is a shed too, with the same 429 contract. ``release`` hands the
freed seat to the next flow picked by smooth weighted round-robin across
the level's non-empty queues.

Client half: :mod:`kubernetes_tpu.core.backoff` recognizes 429 as
retriable and honors ``Retry-After`` with decorrelated jitter, so shed
clients back off past the server's horizon instead of re-synchronizing
into a retry storm (docs/RESILIENCE.md § overload & fairness).
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

EXEMPT = "exempt"
SYSTEM = "system"
WORKLOAD = "workload"


def _flow_hash(level: str, flow: str) -> int:
    """Stable 64-bit flow hash (level-scoped, process-independent): the
    shuffle-shard dealer draws from it, so a flow lands in the same hand
    on every replica."""
    digest = hashlib.blake2b(f"{level}/{flow}".encode(),
                             digest_size=8).digest()
    return int.from_bytes(digest, "big")


def shuffle_shard_hand(level: str, flow: str, queues: int,
                       hand_size: int) -> List[int]:
    """Deal ``hand_size`` DISTINCT queue indices for ``flow`` (the
    reference's shuffle-sharding dealer, shufflesharding/dealer.go): draw
    successive modulo digits off the flow hash, each selecting from the
    queues not yet dealt. Two flows share a whole hand only with
    probability ~(hand/queues)^hand — the isolation bound the unit suite
    asserts."""
    hand_size = max(1, min(hand_size, queues))
    h = _flow_hash(level, flow)
    remaining = list(range(queues))
    hand: List[int] = []
    for i in range(hand_size):
        d = h % (queues - i)
        h //= (queues - i)
        hand.append(remaining.pop(d))
    return hand


class _Waiter:
    """One queued request: the event its handler thread parks on, plus the
    flow key the WRR dequeue weighs it by."""

    __slots__ = ("event", "flow", "seated", "cancelled")

    def __init__(self, flow: str):
        self.event = threading.Event()
        self.flow = flow
        self.seated = False
        self.cancelled = False


class Ticket:
    """Proof of admission; hand back via :meth:`FlowController.release`.
    Exempt tickets hold no seat (release is a no-op for them)."""

    __slots__ = ("level", "seated")

    def __init__(self, level: "PriorityLevel", seated: bool):
        self.level = level
        self.seated = seated


class PriorityLevel:
    """One bounded-concurrency lane: ``seats`` concurrent dispatches,
    ``queues`` fair queues of ``queue_length`` each, shuffle-shard hand
    size ``hand_size``. ``queues=0`` marks the exempt lane (no seats, no
    queues, no shedding — ever)."""

    def __init__(self, name: str, seats: int = 8, queues: int = 8,
                 queue_length: int = 16, hand_size: int = 2,
                 max_wait: float = 1.0,
                 flow_weights: Optional[Dict[str, float]] = None):
        self.name = name
        self.seats = max(1, seats)
        self.queue_length = max(1, queue_length)
        self.hand_size = hand_size
        self.max_wait = max_wait
        self.flow_weights = dict(flow_weights or {})
        self.exempt = queues <= 0
        self._queues: List[deque] = [deque() for _ in range(max(0, queues))]
        # Smooth-WRR credit per queue: each dequeue round adds the head
        # flow's weight to every non-empty queue, serves the max-credit
        # queue, and charges it the round's total — long-run service is
        # proportional to weight (the property the unit suite measures).
        self._credit: List[float] = [0.0] * max(0, queues)
        self.seats_in_use = 0
        # Counters (apiserver_flowcontrol_*_total{priority_level}).
        self.dispatched = 0   # requests that got a seat (or exempt pass)
        self.queued = 0       # requests that waited in a queue first
        self.rejected = 0     # requests shed (queue full / wait timeout)

    def weight_of(self, flow: str) -> float:
        return max(1e-6, float(self.flow_weights.get(flow, 1.0)))

    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues)

    # -- internals (caller holds the controller lock) -----------------------

    def _enqueue(self, flow: str) -> Optional[_Waiter]:
        """Queue one request into the shortest queue of its shuffle-shard
        hand; None when every queue in the hand is full (shed)."""
        hand = shuffle_shard_hand(self.name, flow, len(self._queues),
                                  self.hand_size)
        qidx = min(hand, key=lambda i: (len(self._queues[i]), i))
        if len(self._queues[qidx]) >= self.queue_length:
            return None
        w = _Waiter(flow)
        self._queues[qidx].append(w)
        return w

    def _dispatch_next(self) -> None:
        """Hand freed seats to queued work: smooth weighted round-robin
        across non-empty queues, weighed by each queue's HEAD flow."""
        while self.seats_in_use < self.seats:
            nonempty = [i for i, q in enumerate(self._queues) if q]
            if not nonempty:
                return
            total = 0.0
            for i in nonempty:
                w = self.weight_of(self._queues[i][0].flow)
                self._credit[i] += w
                total += w
            best = max(nonempty, key=lambda i: (self._credit[i], -i))
            self._credit[best] -= total
            waiter = self._queues[best].popleft()
            if waiter.cancelled:
                continue  # timed out while queued; its thread already shed
            waiter.seated = True
            self.seats_in_use += 1
            self.dispatched += 1
            waiter.event.set()


class FlowController:
    """The admission gate the apiserver's mutating verbs pass through.

    Thread-safe behind its OWN lock — by contract (and the
    ``shed-discipline`` analyzer rule) it is never entered while the
    server's ``_write_lock`` is held: classification, queuing, and the
    shed decision all happen strictly before the write plane."""

    def __init__(self, levels: Optional[Dict[str, PriorityLevel]] = None):
        self.levels: Dict[str, PriorityLevel] = levels or default_levels()
        self._lock = threading.Lock()

    # -- classification -----------------------------------------------------

    def classify(self, method: str, path: str,
                 namespace: str = "") -> Tuple[str, str]:
        """(priority level, flow key) for one mutating request.

        Exempt: the control traffic failover depends on — replication
        ship/ack + peer/leader announcements (``/replication/*``) and
        lease CAS (``/api/v1/leases/*``, shard + leader leases). System:
        node lifecycle (registration/heartbeats/drift/churn — the
        kubelet/hollow plane, one shared flow). Workload: everything
        pod-shaped, flow-keyed by tenant namespace."""
        if path.startswith("/replication/") or \
                path.startswith("/api/v1/leases"):
            return EXEMPT, "control"
        if path.startswith("/api/v1/nodes"):
            return SYSTEM, "nodes"
        return WORKLOAD, namespace or "default"

    # -- admission ----------------------------------------------------------

    def admit(self, level_name: str, flow: str) -> Optional[Ticket]:
        """Admit one request into ``level_name`` under flow ``flow``.

        Returns a :class:`Ticket` (release it in a finally), or None when
        the request is SHED — the caller answers 429 with a Retry-After
        header and must not have touched the write lock. Blocks (outside
        the controller lock) up to the level's ``max_wait`` while queued."""
        lvl = self.levels[level_name]
        with self._lock:
            if lvl.exempt:
                lvl.dispatched += 1
                return Ticket(lvl, seated=False)
            if lvl.seats_in_use < lvl.seats and lvl.queue_depth() == 0:
                # Fast path: free seat, nothing ahead of us.
                lvl.seats_in_use += 1
                lvl.dispatched += 1
                return Ticket(lvl, seated=True)
            waiter = lvl._enqueue(flow)
            if waiter is None:
                lvl.rejected += 1
                return None
            lvl.queued += 1
        if waiter.event.wait(lvl.max_wait):
            return Ticket(lvl, seated=True)
        with self._lock:
            if waiter.seated:
                # Seated between the timeout and this lock: keep the seat.
                return Ticket(lvl, seated=True)
            waiter.cancelled = True  # lazily skipped by _dispatch_next
            lvl.rejected += 1
            return None

    def release(self, ticket: Optional[Ticket]) -> None:
        """Free the admitted request's seat and dispatch queued work."""
        if ticket is None or not ticket.seated:
            return
        with self._lock:
            ticket.level.seats_in_use -= 1
            ticket.level._dispatch_next()

    def count_exempt(self) -> None:
        """Account one exempt-lane dispatch that bypassed admit() entirely
        (the replication endpoints answer before classification)."""
        with self._lock:
            self.levels[EXEMPT].dispatched += 1

    # -- live re-weighting (/flow admin endpoint) ---------------------------

    def weights(self) -> Dict[str, Dict[str, float]]:
        """Per-level flow weights (the /flow GET surface)."""
        with self._lock:
            return {name: dict(lvl.flow_weights)
                    for name, lvl in self.levels.items()}

    def set_weights(self, level_name: str,
                    weights: Dict[str, float]) -> Dict[str, float]:
        """Re-weight flows inside one priority level, live, under THIS
        controller's lock — never the server's write lock (the /flow POST
        surface; lets operators starve down a flood tenant mid-storm).
        The exempt lane takes no weights by design (it has no queues).
        Raises KeyError for an unknown level, ValueError for the exempt
        lane or a non-positive weight."""
        with self._lock:
            lvl = self.levels[level_name]
            if lvl.exempt:
                raise ValueError("exempt lane is not re-weightable")
            staged = {}
            for flow, w in weights.items():
                w = float(w)
                if w <= 0:
                    raise ValueError(f"weight for {flow!r} must be > 0")
                staged[str(flow)] = w
            lvl.flow_weights.update(staged)
            return dict(lvl.flow_weights)

    def retry_after(self, level_name: str) -> int:
        """The Retry-After seconds a shed reply carries: at least the
        level's queue-wait horizon, scaled up when the backlog is deep —
        a shed client must come back AFTER the current wave drains, and
        the client's decorrelated jitter (core/backoff.py) keeps the
        returning herd spread out."""
        lvl = self.levels[level_name]
        with self._lock:
            depth = lvl.queue_depth()
        capacity = max(1, len(lvl._queues) * lvl.queue_length)
        import math
        return max(1, int(math.ceil(lvl.max_wait * (1.0 + depth / capacity))))

    # -- observability ------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-level counters + gauges for /metrics exposition."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, lvl in self.levels.items():
                out[name] = {
                    "dispatched": lvl.dispatched,
                    "queued": lvl.queued,
                    "rejected": lvl.rejected,
                    "seats": lvl.seats_in_use,
                    "queue_depth": lvl.queue_depth(),
                }
        return out


def _level_from_env(name: str, default: PriorityLevel) -> PriorityLevel:
    """Optional sizing override: ``TPU_SCHED_APF_<LEVEL>`` =
    "seats,queues,queue_length,hand_size,max_wait". The chaos harness
    tightens lanes through this seam (OS-process apiservers take no
    constructor args); malformed specs keep the default. The exempt lane
    deliberately has NO override — nothing may make it sheddable."""
    import os
    spec = os.environ.get(f"TPU_SCHED_APF_{name.upper()}", "")
    if not spec:
        return default
    try:
        seats, queues, qlen, hand, max_wait = spec.split(",")
        return PriorityLevel(name, seats=int(seats), queues=int(queues),
                             queue_length=int(qlen), hand_size=int(hand),
                             max_wait=float(max_wait))
    except (ValueError, TypeError):
        return default


def default_levels() -> Dict[str, PriorityLevel]:
    """The apiserver's stock lanes. Workload sizing rationale: the write
    plane is one lock, so a handful of seats saturates it; 8 queues x 16
    with a 2-wide hand bounds any single flow to 2 queues' worth of
    backlog (32 requests) while leaving 6+ queues for everyone else —
    a flood saturates its own hand and sheds, well-behaved tenants keep
    landing in mostly-empty queues."""
    return {
        EXEMPT: PriorityLevel(EXEMPT, queues=0),
        SYSTEM: _level_from_env(SYSTEM, PriorityLevel(
            SYSTEM, seats=4, queues=4, queue_length=64,
            hand_size=1, max_wait=2.0)),
        WORKLOAD: _level_from_env(WORKLOAD, PriorityLevel(
            WORKLOAD, seats=8, queues=8, queue_length=16,
            hand_size=2, max_wait=1.0)),
    }
