"""Fake control plane: an in-process pod/node store with watch-style fanout.

Plays the role of client-go fake.Clientset + informers in the reference's unit
layer (SURVEY.md §4.2): scheduler event handlers subscribe, API writes (bind,
create, delete) synchronously fan out to them — the process-boundary analogue
of apiserver watch streams collapsed to function calls.
"""

from __future__ import annotations

import copy
import itertools
from typing import Callable, Dict, List, Optional

from ..api.storage import CSINode, PersistentVolume, PersistentVolumeClaim, StorageClass
from ..api.types import CompositePodGroup, Namespace, Node, Pod, PodGroup


class FakeClientset:
    def __init__(self):
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.namespaces: Dict[str, Namespace] = {"default": Namespace(name="default")}
        self.pod_groups: Dict[str, PodGroup] = {}  # "ns/name" -> group
        self.composite_pod_groups: Dict[str, CompositePodGroup] = {}
        self.pvs: Dict[str, PersistentVolume] = {}
        self.pvcs: Dict[str, PersistentVolumeClaim] = {}  # "ns/name" -> pvc
        self.storage_classes: Dict[str, StorageClass] = {}
        self.csi_nodes: Dict[str, CSINode] = {}
        self.resource_slices: Dict[str, List] = {}   # node -> [ResourceSlice]
        self.resource_claims: Dict[str, object] = {}  # "ns/name" -> ResourceClaim
        self.device_classes: Dict[str, object] = {}
        self.bindings: Dict[str, str] = {}  # pod uid -> node name
        self._pod_handlers: List = []
        self._node_handlers: List = []
        self._namespace_handlers: List = []
        self._pod_group_handlers: List = []
        self._storage_handlers: List = []
        self._pv_controller = None
        # Monotonic resourceVersion. itertools.count is C-implemented and
        # GIL-atomic: a concurrent client thread (perf harness creators, the
        # threaded watch transport) can write while the scheduling loop
        # binds, without ever minting duplicate versions.
        self._rv_counter = itertools.count(1)
        # Shard leases (shard/leases.py): the in-process analogue of the
        # apiserver's /api/v1/leases surface. `lease_now` is injectable so
        # lease-expiry tests need no real sleeps.
        self.leases: Dict[str, dict] = {}
        import time as _time
        self.lease_now: Callable[[], float] = _time.monotonic

    # -- informer-ish registration ----------------------------------------

    def on_pod_event(self, handler: Callable[[str, Optional[Pod], Pod], None]) -> None:
        """handler(kind, old, new) with kind in add/update/delete."""
        self._pod_handlers.append(handler)

    def on_node_event(self, handler: Callable[[str, Optional[Node], Node], None]) -> None:
        self._node_handlers.append(handler)

    def on_namespace_event(self, handler: Callable[[Namespace], None]) -> None:
        self._namespace_handlers.append(handler)
        for ns in self.namespaces.values():  # replay existing (informer list)
            handler(ns)

    def on_pod_group_event(self, handler: Callable[[PodGroup], None]) -> None:
        self._pod_group_handlers.append(handler)
        for g in self.pod_groups.values():
            handler(g)

    def on_storage_event(self, handler: Callable[[str, object], None]) -> None:
        """handler(kind, obj) for PV/PVC/StorageClass/CSINode/DRA writes —
        the informer feed behind the Storage/Add queueing hints."""
        self._storage_handlers.append(handler)

    def _fire_storage(self, kind: str, obj) -> None:
        for h in self._storage_handlers:
            h(kind, obj)

    # -- writes ------------------------------------------------------------

    def create_node(self, node: Node) -> Node:
        node.resource_version = next(self._rv_counter)
        self.nodes[node.name] = node
        for h in self._node_handlers:
            h("add", None, node)
        return node

    def update_node(self, node: Node) -> Node:
        old = self.nodes.get(node.name)
        node.resource_version = next(self._rv_counter)
        self.nodes[node.name] = node
        for h in self._node_handlers:
            h("update", old, node)
        return node

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node is not None:
            for h in self._node_handlers:
                h("delete", node, node)

    def create_namespace(self, ns: Namespace) -> Namespace:
        self.namespaces[ns.name] = ns
        for h in self._namespace_handlers:
            h(ns)
        return ns

    def create_pod_group(self, group: PodGroup) -> PodGroup:
        self.pod_groups[f"{group.namespace}/{group.name}"] = group
        for h in self._pod_group_handlers:
            h(group)
        return group

    def create_composite_pod_group(self, cpg: CompositePodGroup) -> CompositePodGroup:
        """CompositePodGroup informer feed — delivered through the same
        pod-group handler channel (handlers type-switch)."""
        self.composite_pod_groups[f"{cpg.namespace}/{cpg.name}"] = cpg
        for h in self._pod_group_handlers:
            h(cpg)
        return cpg

    # -- storage (PV controller surface the volume plugins consume) --------

    def create_pv(self, pv: PersistentVolume) -> PersistentVolume:
        self.pvs[pv.name] = pv
        self._fire_storage("pv", pv)
        return pv

    def create_pvc(self, pvc: PersistentVolumeClaim) -> PersistentVolumeClaim:
        self.pvcs[pvc.key] = pvc
        self._fire_storage("pvc", pvc)
        return pvc

    def create_storage_class(self, sc: StorageClass) -> StorageClass:
        self.storage_classes[sc.name] = sc
        self._fire_storage("storage_class", sc)
        return sc

    def create_csi_node(self, cn: CSINode) -> CSINode:
        self.csi_nodes[cn.node_name] = cn
        # Version the CSINode SET (not just its size): replacing a node's
        # driver_limits must invalidate limited-driver caches.
        self.csi_nodes_rv = getattr(self, "csi_nodes_rv", 0) + 1
        self._fire_storage("csi_node", cn)
        return cn

    def create_resource_slice(self, sl) -> object:
        self.resource_slices.setdefault(sl.node_name, []).append(sl)
        if any(getattr(d, "consumes", None) for d in sl.devices):
            # Node-allocatable-consuming devices: their allocation math is
            # outside the device kernel's aux model (eligibility checks this).
            self.has_consuming_devices = True
        self._fire_storage("resource_slice", sl)
        return sl

    def create_resource_claim(self, claim) -> object:
        self.resource_claims[claim.key] = claim
        self.resource_claims_rv = getattr(self, "resource_claims_rv", 0) + 1
        self._fire_storage("resource_claim", claim)
        return claim

    def bump_resource_claims_rv(self) -> None:
        """Out-of-band claim mutations (controller-side allocation) must
        invalidate in-use caches keyed on the claims revision."""
        self.resource_claims_rv = getattr(self, "resource_claims_rv", 0) + 1

    def create_device_class(self, dc) -> object:
        self.device_classes[dc.name] = dc
        self._fire_storage("device_class", dc)
        return dc

    def attach_pv_controller(self, ctrl) -> None:
        """Register the PV controller (core/pv_controller.py) so PreBind's
        provisioning path rides the real control loop."""
        self._pv_controller = ctrl

    def bind_volume(self, pvc: PersistentVolumeClaim, pv_name: str, node_name: str) -> None:
        """VolumeBinding PreBind writes (binder.go BindPodVolumes): bind the
        claim to the decided PV, or — for WaitForFirstConsumer provisioning —
        write the volume.kubernetes.io/selected-node annotation and let the
        PV controller provision (pv_controller.py). Without an attached
        controller, provisioning is simulated inline (unit-test shape)."""
        if pv_name:
            pv = self.pvs[pv_name]
            pv.claim_ref = pvc.key
            pvc.volume_name = pv_name
            pvc.annotations["pv.kubernetes.io/bind-completed"] = "true"
            return
        from ..core.pv_controller import SELECTED_NODE
        pvc.annotations[SELECTED_NODE] = node_name
        if self._pv_controller is not None:
            self._pv_controller.provision(pvc, node_name)
            return
        from ..api.types import NodeSelector, NodeSelectorTerm
        from ..api.labels import IN, Requirement
        provisioned = PersistentVolume(
            name=f"pvc-{pvc.uid}", capacity=pvc.request,
            access_modes=pvc.access_modes, storage_class=pvc.storage_class,
            node_affinity=NodeSelector(terms=(NodeSelectorTerm(
                match_fields=(Requirement("metadata.name", IN, (node_name,)),)),)),
            claim_ref=pvc.key)
        self.pvs[provisioned.name] = provisioned
        pvc.volume_name = provisioned.name

    def create_pod(self, pod: Pod) -> Pod:
        pod.resource_version = next(self._rv_counter)
        self.pods[pod.uid] = pod
        for h in self._pod_handlers:
            h("add", None, pod)
        return pod

    def update_pod(self, pod: Pod) -> Pod:
        old = self.pods.get(pod.uid)
        pod.resource_version = next(self._rv_counter)
        # An update may carry an in-place spec change on the SAME object
        # (clients mutate-and-republish): drop every derived-spec memo,
        # including the template-shared signature holder — the object's spec
        # may have diverged from its template. This is the API-boundary
        # analogue of the old resourceVersion-keyed memo invalidation.
        d = pod.__dict__
        d.pop("_sig_cache", None)
        d.pop("_sig_shared", None)
        d.pop("_req_cache", None)
        d.pop("_hp_cache", None)
        self.pods[pod.uid] = pod
        for h in self._pod_handlers:
            h("update", old, pod)
        return pod

    def delete_pod(self, pod: Pod) -> None:
        p = self.pods.get(pod.uid)
        if p is None:
            return
        if p.finalizers:
            # Graceful deletion: finalizers park the object with a
            # deletionTimestamp; watchers see an update, not a delete, and
            # repeated deletes cannot complete it — only finalizer removal
            # can (pkg/registry/core/pod strategy + apimachinery finalizers).
            if p.deletion_ts is None:
                import time as _t
                p.deletion_ts = _t.time()
                p.resource_version = next(self._rv_counter)
                for h in self._pod_handlers:
                    h("update", p, p)
            return
        self.pods.pop(pod.uid, None)
        for h in self._pod_handlers:
            h("delete", p, p)

    def remove_pod_finalizers(self, pod: Pod) -> None:
        """Clear finalizers; if a delete is pending, it completes now."""
        p = self.pods.get(pod.uid)
        if p is None:
            return
        p.finalizers = []
        if p.deletion_ts is not None:
            self.pods.pop(p.uid, None)
            for h in self._pod_handlers:
                h("delete", p, p)

    def bind(self, pod: Pod, node_name: str) -> None:
        """POST pods/{name}/binding (DefaultBinder target)."""
        stored = self.pods.get(pod.uid)
        if stored is None:
            raise KeyError(f"pod {pod.namespace}/{pod.name} not found")
        old = stored
        new = copy.copy(stored)
        new.node_name = node_name
        new.resource_version = next(self._rv_counter)
        self.pods[pod.uid] = new
        self.bindings[pod.uid] = node_name
        for h in self._pod_handlers:
            h("update", old, new)

    def patch_pod_status(self, pod: Pod, nominated_node_name: str = "", phase: str = "") -> None:
        stored = self.pods.get(pod.uid)
        if stored is None:
            return
        if nominated_node_name:
            stored.nominated_node_name = nominated_node_name
        if phase:
            stored.phase = phase

    # -- shard leases (apiserver /api/v1/leases parity) ---------------------

    def _lease_wire(self, name: str, rec: dict, now: float) -> dict:
        age = now - rec["renew"]
        return {"name": name, "holder": rec["holder"],
                "leaseDurationSeconds": rec["duration"],
                "ageSeconds": round(age, 3),
                "transitions": rec["transitions"],
                "expired": (not rec["holder"]) or age >= rec["duration"]}

    def list_leases(self) -> List[dict]:
        now = self.lease_now()
        return [self._lease_wire(n, r, now)
                for n, r in sorted(self.leases.items())]

    def upsert_lease(self, name: str, holder: str,
                     duration: float) -> Optional[dict]:
        """Acquire-or-renew under CAS semantics (same contract as the
        apiserver's PUT /api/v1/leases/<name>): a held, unexpired lease only
        renews for its current holder; anyone else gets None."""
        now = self.lease_now()
        rec = self.leases.get(name)
        if (rec is not None and rec["holder"] and rec["holder"] != holder
                and now - rec["renew"] < rec["duration"]):
            return None
        if rec is None:
            rec = {"holder": "", "duration": float(duration),
                   "renew": now, "transitions": 0}
            self.leases[name] = rec
        if rec["holder"] != holder:
            rec["transitions"] += 1
        rec["holder"] = holder
        rec["duration"] = float(duration)
        rec["renew"] = now
        return self._lease_wire(name, rec, now)


class RetryingClientset:
    """Write-path retry decorator over any clientset (client-go's
    rest/request.go retry + wait.Backoff, collapsed to the verbs the
    scheduler writes). Transient failures — connection resets, timeouts,
    5xx, injected ``TransientAPIError`` — are replayed with exponential
    backoff + seeded jitter; semantic errors (pod not found, validation)
    propagate on the first try. Reads, listers, and informer registration
    delegate untouched, so the wrapper is drop-in wherever a clientset is
    (``TPUScheduler(clientset=RetryingClientset(HTTPClientset(url)))``).

    ``retries_total`` counts replayed calls; ``give_ups`` counts calls
    that exhausted the budget (the final exception propagates — the async
    dispatcher's error inbox / drain_errors owns what happens next)."""

    _WRITE_VERBS = frozenset({
        "create_pod", "update_pod", "delete_pod", "bind", "patch_pod_status",
        "create_node", "update_node", "delete_node",
        "create_namespace", "create_pod_group", "create_composite_pod_group",
        "create_pv", "create_pvc", "create_storage_class", "create_csi_node",
        "create_resource_slice", "create_resource_claim",
        "create_device_class", "bind_volume", "remove_pod_finalizers",
        # Safe to replay blindly: the eviction subresource is idempotent by
        # intent id (the server's WAL'd ledger answers a replay with
        # already=True instead of double-evicting).
        "evict_pod",
    })

    def __init__(self, inner, retry=None):
        from .backoff import RetryConfig, retry_call
        self._inner = inner
        self._retry_cfg = retry or RetryConfig()
        self._retry_call = retry_call
        self.retries_total = 0
        self.give_ups = 0

    def _on_retry(self, _attempt: int, _exc: BaseException) -> None:
        self.retries_total += 1

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in RetryingClientset._WRITE_VERBS and callable(attr):
            def retried(*args, _attr=attr, _verb=name, **kwargs):
                state = {"retried": False}

                def on_retry(attempt, exc):
                    state["retried"] = True
                    self._on_retry(attempt, exc)

                try:
                    return self._retry_call(
                        lambda: _attr(*args, **kwargs),
                        config=self._retry_cfg, on_retry=on_retry)
                except BaseException as e:
                    if (state["retried"] and getattr(e, "code", None) == 409
                            and _verb.startswith("create_")):
                        # AlreadyExists on a create REPLAY: the earlier
                        # attempt landed before its reply was lost — the
                        # write is durable, which is what the caller wanted.
                        # A 409 on the FIRST try is a genuine conflict and
                        # raises. bind is deliberately excluded: the server
                        # answers a same-node bind replay 200, so a bind 409
                        # is ALWAYS a real conflict (another scheduler won
                        # the pod) and must reach the conflict-requeue path.
                        return None
                    if self._retry_cfg.retriable(e):
                        self.give_ups += 1  # budget exhausted, still failing
                    raise
            return retried
        return attr
