"""Binary wire codec for the control plane — the ONE codec seam.

Every hot wire surface (WAL records on disk, the replication ship stream,
snapshot bootstrap pages, watch events incl. slim projections, bulk
binding envelopes, paged LIST pages) routes its encode/decode through this
module; the `wire-discipline` analyzer rule forbids raw ``json.dumps`` /
``json.loads`` on those surfaces anywhere else. The reference serves
protobuf/CBOR alongside JSON for exactly this reason (apimachinery runtime
codecs, SURVEY §1 L2); here the compact plane is a dependency-free binary
format and JSON remains the debug/compat plane forever.

Frame format (docs/WIRE.md):

- ``MAGIC (0xBF)  VERSION (1 byte)  varint payload_len  payload`` — a
  reader can always tell binary from JSON by the first byte (JSON lines on
  these surfaces start with ``{``; 0xBF is also not valid UTF-8 lead byte
  for JSON text). The length prefix gives WAL replay and stream reads the
  exact torn-frame semantics of newline-framed JSON: an incomplete or
  undecodable final frame is discarded and truncated away.
- The payload is ONE self-describing value:
  - one byte ``0x00..0xBE`` — the small int itself (rv deltas, ports,
    priorities, request milli-values);
  - ``0xC0`` None, ``0xC1`` True, ``0xC2`` False;
  - ``0xC3`` int: zigzag varint;
  - ``0xC4`` float: 8-byte IEEE-754 big-endian;
  - ``0xC6`` string define: varint byte-length + UTF-8 — and the string
    joins the intern table at the next free index;
  - ``0xC7`` string ref: varint index into the intern table;
  - ``0xC8`` list: varint count + items;
  - ``0xC9`` dict: varint count + (string key, value) pairs;
  - ``0xCA`` bytes: varint length + raw passthrough (already-encoded
    payloads ride untouched — JSON has no analogue, so the JSON codec
    refuses them).

Intern table: the wire on these surfaces is dominated by repeated dict
keys, kinds, namespaces, and node names. The table is seeded with the
protocol's WELL-KNOWN strings (bound to the VERSION byte — extending the
list bumps the version) and grows per frame: the first occurrence of any
other string is a define (same cost as inline), every later occurrence in
the SAME frame is a 2-3 byte ref. The table RESETS at every frame — so a
frame is self-contained, encode results are shareable across streams and
safe to replay after any prefix of the log is truncated away.

Negotiation (Accept:-style): a client that speaks binary sends
``Accept: application/x-tpu-wire``; a willing server answers binary
(``Content-Type: application/x-tpu-wire``) on success replies and data
streams — error bodies stay JSON always (the debug plane). Anything else
falls back to JSON on both sides. ``TPU_SCHED_WIRE=json`` pins a process
(client offers and server answers) to JSON — the A/B and interop lever.

PR 18 — the delta wire plane (docs/WIRE.md §DELTA):

- DELTA records: a MODIFIED event whose receiver holds the object's
  prior wire copy ships as ``{"type": "DELTA", "rv", "key", "baseRv",
  "patch"}`` — a field-path patch (:func:`diff_obj` / :func:`apply_patch`)
  against that cached base. Any base/rv mismatch falls back to a full
  object (re-list client-side, snapshot resync follower-side,
  :class:`DeltaBaseMismatch`) — never a silent divergence.
- Session streams: a watch/ship stream may negotiate
  ``application/x-tpu-wire+session`` — version-3 frames whose intern
  table PERSISTS across frames for the life of the response body
  (:class:`SessionEncoder` / :class:`SessionDecoder`), so node names,
  label keys and zone strings are sent once per connection. Session
  frames never touch disk: the WAL stays self-contained v2 frames, and
  ``scan`` treats a v3 frame at rest as torn data.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

WIRE_MIME = "application/x-tpu-wire"
# Session-stream offer/answer: same payload grammar, but the stream's
# intern table persists across frames (version-3 frames). WIRE_MIME is a
# prefix, so every existing `WIRE_MIME in header` negotiation/learning
# site sees a session peer as a binary peer — exactly right.
SESSION_MIME = "application/x-tpu-wire+session"
JSON_MIME = "application/json"

MAGIC = 0xBF
VERSION = 1
# Version-2 frame: identical payload encoding, but the frame carries a
# trailing 4-byte big-endian CRC32 over the payload — the WAL's
# at-rest plane. A COMPLETE frame whose CRC mismatches is corruption in
# the middle of the log (CorruptFrameError), distinct from a torn tail
# (scan returns None and the recovery truncates). Streams keep VERSION
# (the transport already detects torn frames by framing alone).
VERSION_CRC = 2
# Version-3 frame: identical payload grammar, but the intern table is
# the STREAM's, not the frame's — defines accumulate across frames for
# the life of one negotiated response body (SessionEncoder/Decoder).
# Never written at rest: scan() treats a v3 frame in a WAL as torn data,
# and read_event() refuses one on a stream that didn't negotiate it.
VERSION_SESSION = 3

BINARY = "binary"
# WAL at-rest codec: version-2 CRC frames. Same payload bytes as BINARY,
# so WireItem caches the two independently and a binary ship stream
# never sees a CRC frame.
BINARY_CRC = "binary+crc"
JSON = "json"

# Well-known strings, seeded into every frame's intern table (indexes
# 0..N-1). ORDER IS THE WIRE CONTRACT: append only, and bump VERSION when
# you do — a reader keys its seed table off the frame's version byte.
WELL_KNOWN: Tuple[str, ...] = (
    # event / frame envelope
    "type", "object", "rv", "kind", "seq", "epoch", "tctx",
    "ADDED", "MODIFIED", "DELETED", "BOUND", "STATUS", "LEASE",
    "SYNC", "RESUME", "BOOKMARK", "FAILOVER", "TOO_OLD", "PAGE", "HB",
    "SNAP_META", "SNAP_END", "pods", "nodes", "leases",
    # pod wire
    "name", "namespace", "uid", "nodeName", "schedulerName",
    "nominatedNodeName", "labels", "annotations", "priority", "podGroup",
    "deletionTs", "finalizers", "requests", "cpu", "memory", "ephemeral",
    "scalar", "hostPorts", "port", "protocol", "hostIP", "tolerations",
    "key", "operator", "value", "effect", "nodeSelector", "affinity",
    "topologySpread", "maxSkew", "topologyKey", "whenUnsatisfiable",
    "labelSelector", "minDomains", "nodeAffinityPolicy", "nodeTaintsPolicy",
    "schedulingGates", "volumes", "pvc", "resourceClaims", "slim", "phase",
    "Pending", "Running", "default", "default-scheduler", "TCP",
    # selectors / affinity terms
    "matchLabels", "matchExpressions", "matchFields", "values", "op",
    "required", "preferred", "weight", "term", "namespaces",
    "namespaceSelector", "nodeAffinity", "podAffinity", "podAntiAffinity",
    # node wire
    "allocatable", "capacity", "taints", "unschedulable",
    "declaredFeatures", "NoSchedule", "zone", "topology.kubernetes.io/zone",
    # lease / replication / paging envelopes
    "holder", "duration", "transitions", "renew", "leaseDurationSeconds",
    "ageSeconds", "expired", "leader", "role", "follower", "repl",
    "listRv", "continue", "error", "code", "node", "bound", "created",
    "alreadyExists", "names", "k", "e",
)
_WK_INDEX: Dict[str, int] = {s: i for i, s in enumerate(WELL_KNOWN)}
_WK_N = len(WELL_KNOWN)

_TAG_NONE = 0xC0
_TAG_TRUE = 0xC1
_TAG_FALSE = 0xC2
_TAG_INT = 0xC3
_TAG_FLOAT = 0xC4
_TAG_STR_DEF = 0xC6
_TAG_STR_REF = 0xC7
_TAG_LIST = 0xC8
_TAG_DICT = 0xC9
_TAG_BYTES = 0xCA
_SMALL_INT_MAX = 0xBE  # 0x00..0xBE inline; 0xBF is the frame MAGIC


class WireError(ValueError):
    """Corrupt or truncated binary frame (the torn-record signal)."""


class CorruptFrameError(WireError):
    """A COMPLETE version-2 frame whose payload fails its CRC32 — bit
    rot (or a hostile edit) in the MIDDLE of a WAL, not a torn tail.
    Recovery must quarantine, never silently truncate: every record
    after the corrupt one is intact and would be lost."""


class DeltaBaseMismatch(WireError):
    """A DELTA record named a base (key@baseRv) the receiver does not
    hold — the full-object fallback signal, NEVER a silent apply onto
    the wrong base. A client re-lists; a follower snapshot-resyncs; an
    unhandled site inherits WireError's torn-stream handling (reconnect),
    which also converges on a full copy."""


# ---------------------------------------------------------------------------
# JSON compat plane — the module-local seam the analyzer rule points at
# ---------------------------------------------------------------------------


def jdumps(obj: Any) -> str:
    """Compact JSON text — the debug/compat encode every non-binary wire
    path routes through (one call site class for the analyzer rule)."""
    return json.dumps(obj, separators=(",", ":"))


def jloads(data) -> Any:
    """JSON decode (str or bytes) — the compat-plane twin of jdumps."""
    return json.loads(data)


# ---------------------------------------------------------------------------
# binary encode
# ---------------------------------------------------------------------------


def _append_varint(buf: bytearray, n: int) -> None:
    while n > 0x7F:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n)


def _ref_bytes(idx: int) -> bytes:
    b = bytearray((_TAG_STR_REF,))
    _append_varint(b, idx)
    return bytes(b)


# Vectorized fast path (PR 18): every WELL_KNOWN string's ref encoding is
# precomputed ONCE at import — the hot dict-key case is a single dict get
# + one buffer extend, no varint loop, no second lookup. Intern tables
# (per frame, or per session stream) hold ready ref BYTES the same way:
# the define pays the varint once, every later occurrence is an extend.
_WK_REF: Dict[str, bytes] = {s: _ref_bytes(i) for i, s in
                             enumerate(WELL_KNOWN)}


def _encode_value(buf: bytearray, obj: Any, interns: Dict[str, bytes],
                  pack_double=struct.Struct(">d").pack,
                  wk_ref=_WK_REF) -> None:
    # Dispatch ordered by wire frequency: strings (dict keys dominate
    # every surface), ints (rv/seq/milli-values), dicts, lists — the
    # exact `type is` checks also keep bool (an int subclass) falling
    # through to its own branch below.
    t = type(obj)
    if t is str:
        r = wk_ref.get(obj) or interns.get(obj)
        if r is not None:
            buf += r
        else:
            _intern_define(buf, obj, interns)
    elif t is int:
        if 0 <= obj <= _SMALL_INT_MAX:
            buf.append(obj)
        else:
            buf.append(_TAG_INT)
            # zigzag over arbitrary-precision ints (Python has no 64-bit
            # wrap to lean on): non-negatives go even, negatives odd
            _append_varint(buf, (obj << 1) if obj >= 0 else ((-obj) << 1) - 1)
    elif t is dict:
        buf.append(_TAG_DICT)
        _append_varint(buf, len(obj))
        enc = _encode_value
        for k, v in obj.items():
            if type(k) is not str:
                raise TypeError(f"wire dict keys must be str, got {type(k)}")
            r = wk_ref.get(k) or interns.get(k)
            if r is not None:
                buf += r
            else:
                _intern_define(buf, k, interns)
            enc(buf, v, interns)
    elif t is list or t is tuple:
        buf.append(_TAG_LIST)
        _append_varint(buf, len(obj))
        enc = _encode_value
        for item in obj:
            enc(buf, item, interns)
    elif obj is None:
        buf.append(_TAG_NONE)
    elif obj is True:
        buf.append(_TAG_TRUE)
    elif obj is False:
        buf.append(_TAG_FALSE)
    elif t is float:
        buf.append(_TAG_FLOAT)
        buf += pack_double(obj)
    elif t is bytes:
        buf.append(_TAG_BYTES)
        _append_varint(buf, len(obj))
        buf += obj
    elif isinstance(obj, (int, float, str, dict, list, tuple, bytes)):
        # subclasses (IntEnum etc.): normalize through the base type
        base = (int if isinstance(obj, int) else
                float if isinstance(obj, float) else
                str if isinstance(obj, str) else
                bytes if isinstance(obj, bytes) else
                dict if isinstance(obj, dict) else list)
        _encode_value(buf, base(obj), interns)
    else:
        raise TypeError(f"not wire-encodable: {type(obj)}")


def _intern_define(buf: bytearray, s: str,
                   interns: Dict[str, bytes]) -> None:
    """First occurrence of a non-well-known string: define it, and record
    its READY ref bytes for every later occurrence in this table's scope
    (one frame, or one session stream)."""
    interns[s] = _ref_bytes(_WK_N + len(interns))
    raw = s.encode()
    buf.append(_TAG_STR_DEF)
    _append_varint(buf, len(raw))
    buf += raw


def _encode_str(buf: bytearray, s: str, interns: Dict[str, bytes]) -> None:
    r = _WK_REF.get(s) or interns.get(s)
    if r is not None:
        buf += r
        return
    _intern_define(buf, s, interns)


def encode_binary(obj: Any, crc: bool = False) -> bytes:
    """One framed binary record: MAGIC VERSION varint(len) payload.
    With ``crc`` the frame is version 2 and a 4-byte big-endian CRC32
    over the payload trails it (the WAL at-rest format)."""
    payload = bytearray()
    _encode_value(payload, obj, {})
    head = bytearray((MAGIC, VERSION_CRC if crc else VERSION))
    _append_varint(head, len(payload))
    if crc:
        # one join per frame — no payload recopy into the header buffer
        return b"".join((head, payload,
                         zlib.crc32(payload).to_bytes(4, "big")))
    return b"".join((head, payload))


# ---------------------------------------------------------------------------
# delta patches (DELTA records, docs/WIRE.md §DELTA)
# ---------------------------------------------------------------------------

# A patch is a list of ops over string field paths:
#   [[path..., ], value]  — set (missing intermediate dicts are created)
#   [[path...]]           — delete (a missing key/path is a no-op)
# Paths are lists of str keys; non-dict values (lists included) replace
# wholesale. Ops are idempotent, so a replay across a list/watch overlap
# converges instead of corrupting the base.

_DIFF_MAX_OPS = 12


def diff_obj(old: Any, new: Any,
             max_ops: int = _DIFF_MAX_OPS) -> Optional[list]:
    """Field-path patch turning ``old`` into ``new``, or None when a
    delta is not worth shipping (no dict base, or more than ``max_ops``
    leaf changes — at that point the full object is cheaper and
    self-describing). ``apply_patch(old, diff_obj(old, new)) == new``
    holds value- and type-exactly (bool vs int never conflated)."""
    if type(old) is not dict or type(new) is not dict:
        return None
    ops: list = []
    if not _diff_into(ops, [], old, new, max_ops):
        return None
    return ops


def _diff_into(ops: list, path: list, old: dict, new: dict,
               max_ops: int) -> bool:
    for k in old:
        if k not in new:
            if type(k) is not str or len(ops) >= max_ops:
                return False
            ops.append([path + [k]])
    for k, nv in new.items():
        if type(k) is not str:
            return False
        ov = old.get(k, _MISSING)
        if ov is nv:
            continue
        if type(ov) is dict and type(nv) is dict:
            if not _diff_into(ops, path + [k], ov, nv, max_ops):
                return False
            continue
        # type-exact equality: True == 1 (bool ⊂ int) must still diff
        if type(ov) is type(nv) and ov == nv:
            continue
        if len(ops) >= max_ops:
            return False
        ops.append([path + [k], nv])
    return True


_MISSING = object()


def apply_patch(base: dict, patch: list) -> dict:
    """Apply a DELTA patch COPY-ON-WRITE: returns a new object tree and
    never mutates ``base`` — watch caches and clientsets hand the same
    dict to many readers, so an in-place apply would be a data race.
    Only the dicts along each op's path are copied."""
    if type(base) is not dict:
        raise WireError("delta base is not a dict")
    out = dict(base)
    for op in patch:
        path = op[0]
        if not path:
            raise WireError("empty delta path")
        node = out
        dead = False
        for k in path[:-1]:
            child = node.get(k)
            if type(child) is not dict:
                if len(op) == 1:
                    dead = True  # delete under a vanished path: no-op
                    break
                child = {}
            else:
                child = dict(child)
            node[k] = child
            node = child
        if dead:
            continue
        if len(op) == 1:
            node.pop(path[-1], None)
        else:
            node[path[-1]] = op[1]
    return out


# ---------------------------------------------------------------------------
# session streams (version-3 frames, per-connection intern state)
# ---------------------------------------------------------------------------


class SessionEncoder:
    """Per-stream encoder state: ONE intern table for the life of a
    negotiated watch/ship response body. Lives on the stream's consumer
    thread (where encode_stream_item runs) and must NEVER be touched
    under the broadcast lock — the analyzer's delta-base-under-cache-lock
    rule pins that. Any encode exception poisons the stream (the caller
    drops the connection); both sides then start over with fresh state,
    which is the session reset contract."""

    __slots__ = ("interns", "frames")

    def __init__(self):
        self.interns: Dict[str, bytes] = {}
        self.frames = 0

    def encode(self, obj: Any) -> bytes:
        payload = bytearray()
        _encode_value(payload, obj, self.interns)
        head = bytearray((MAGIC, VERSION_SESSION))
        _append_varint(head, len(payload))
        self.frames += 1
        return b"".join((head, payload))


class SessionDecoder:
    """Receiver half: the dynamic intern list persists across version-3
    frames. A ref into state this decoder never saw (a stream spliced
    across reconnects, a stale decoder reused after promotion) raises
    WireError — the stream is torn, the client reconnects with fresh
    state and the server re-defines everything: no silent misreads."""

    __slots__ = ("dyn",)

    def __init__(self):
        self.dyn: List[str] = []


def _read_varint(buf, pos: int) -> Tuple[int, int]:
    n = 0
    shift = 0
    ln = len(buf)
    while True:
        if pos >= ln:
            raise WireError("varint past end")
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:
            raise WireError("varint too long")


def _decode_value(buf, pos: int, dyn: List[str], wk=WELL_KNOWN, wk_n=_WK_N,
                  unpack_double=struct.Struct(">d").unpack_from):
    """Hot decode loop. Truncation surfaces as IndexError (byte indexing
    past the end) — the public entry points convert it to WireError; the
    fast path pays no explicit bounds checks. Varints are read inline:
    nearly every count/index/ref on this wire fits one byte."""
    tag = buf[pos]
    pos += 1
    if tag <= _SMALL_INT_MAX:
        return tag, pos
    if tag == _TAG_STR_REF:
        idx = buf[pos]
        pos += 1
        if idx & 0x80:
            idx, pos = _read_varint_cont(buf, pos, idx)
        if idx < wk_n:
            return wk[idx], pos
        try:
            return dyn[idx - wk_n], pos
        except IndexError:
            raise WireError(f"intern ref {idx} undefined") from None
    if tag == _TAG_DICT:
        n = buf[pos]
        pos += 1
        if n & 0x80:
            n, pos = _read_varint_cont(buf, pos, n)
        d = {}
        dec = _decode_value
        for _ in range(n):
            k, pos = dec(buf, pos, dyn)
            if type(k) is not str:
                raise WireError("non-str dict key")
            d[k], pos = dec(buf, pos, dyn)
        return d, pos
    if tag == _TAG_STR_DEF:
        n = buf[pos]
        pos += 1
        if n & 0x80:
            n, pos = _read_varint_cont(buf, pos, n)
        end = pos + n
        if end > len(buf):
            raise WireError("string past end")
        try:
            s = bytes(buf[pos:end]).decode()
        except UnicodeDecodeError as e:
            raise WireError("bad utf-8") from e
        dyn.append(s)
        return s, end
    if tag == _TAG_LIST:
        n = buf[pos]
        pos += 1
        if n & 0x80:
            n, pos = _read_varint_cont(buf, pos, n)
        out = []
        append = out.append
        dec = _decode_value
        for _ in range(n):
            v, pos = dec(buf, pos, dyn)
            append(v)
        return out, pos
    if tag == _TAG_INT:
        z, pos = _read_varint(buf, pos)
        return (z >> 1) ^ -(z & 1), pos
    if tag == _TAG_FLOAT:
        if pos + 8 > len(buf):
            raise WireError("float past end")
        return unpack_double(buf, pos)[0], pos + 8
    if tag == _TAG_NONE:
        return None, pos
    if tag == _TAG_TRUE:
        return True, pos
    if tag == _TAG_FALSE:
        return False, pos
    if tag == _TAG_BYTES:
        n, pos = _read_varint(buf, pos)
        end = pos + n
        if end > len(buf):
            raise WireError("bytes past end")
        return bytes(buf[pos:end]), end
    raise WireError(f"unknown tag 0x{tag:02x}")


def _read_varint_cont(buf, pos: int, first: int) -> Tuple[int, int]:
    """Continue a varint whose first byte had the continuation bit set."""
    n = first & 0x7F
    shift = 7
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:
            raise WireError("varint too long")


def decode_binary(data) -> Any:
    """Decode ONE complete binary frame (header included)."""
    got = scan(data, 0)
    if got is None:
        raise WireError("incomplete frame")
    obj, end = got
    if end != len(data):
        raise WireError("trailing bytes after frame")
    return obj


def scan(buf, pos: int) -> Optional[Tuple[Any, int]]:
    """Parse one record (binary frame OR ``{...}\\n`` JSON line) at ``pos``
    in ``buf``. Returns ``(obj, next_pos)``, or None when everything from
    ``pos`` on is torn — incomplete or undecodable — and must be truncated
    away (the WAL replay contract, identical for both codecs). A COMPLETE
    version-2 frame failing its CRC raises :class:`CorruptFrameError`
    instead: that is damage in the middle of the log, not a torn tail."""
    ln = len(buf)
    if pos >= ln:
        return None
    first = buf[pos]
    if first == MAGIC:
        if pos + 2 > ln:
            return None
        if buf[pos + 1] == VERSION_CRC:
            return _scan_crc(buf, pos, ln)
        try:
            # version byte reserved: an unknown version in a terminated
            # frame is torn data
            if buf[pos + 1] != VERSION:
                return None
            n, p = _read_varint(buf, pos + 2)
            if p + n > ln:
                return None
            obj, end = _decode_value(buf[p:p + n], 0, [])
            if end != n:
                return None
            return obj, p + n
        except (WireError, IndexError):
            return None
    # JSON line plane (old WALs / JSON peers)
    nl = buf.find(b"\n", pos) if isinstance(buf, (bytes, bytearray)) else -1
    if nl < 0:
        return None
    try:
        return json.loads(bytes(buf[pos:nl])), nl + 1
    except ValueError:
        return None


def _scan_crc(buf, pos: int, ln: int) -> Optional[Tuple[Any, int]]:
    """One version-2 (CRC-trailed) frame at ``pos``. Incomplete bytes —
    length varint, payload, or the CRC trailer itself running past the
    buffer — are a torn tail (None, truncate). A complete frame is
    integrity-checked BEFORE any payload decode; a mismatch (or a decode
    failure inside a CRC-verified payload, which can only mean writer
    corruption) raises CorruptFrameError. Header bytes ride outside the
    CRC: damage there is caught by framing (bad magic/version/varint)
    and resolves as torn data, the one case this plane cannot tell from
    a genuine tail."""
    try:
        n, p = _read_varint(buf, pos + 2)
    except WireError:
        return None  # length varint runs past the buffer: torn tail
    end = p + n + 4
    if end > ln:
        return None  # payload or CRC trailer incomplete: torn tail
    payload = bytes(buf[p:p + n])
    want = int.from_bytes(bytes(buf[p + n:end]), "big")
    got = zlib.crc32(payload)
    if got != want:
        raise CorruptFrameError(
            f"crc mismatch in frame at offset {pos}: "
            f"stored 0x{want:08x}, computed 0x{got:08x}")
    try:
        obj, used = _decode_value(payload, 0, [])
        if used != n:
            raise WireError("trailing bytes in frame")
    except (WireError, IndexError) as e:
        raise CorruptFrameError(
            f"undecodable payload in crc-verified frame at offset "
            f"{pos}: {e}") from e
    return obj, end


def decode(data) -> Any:
    """Sniff-decode one complete record, either codec (bodies, frames)."""
    if data and data[0] == MAGIC:
        return decode_binary(data)
    return json.loads(data)


# ---------------------------------------------------------------------------
# the negotiated seam
# ---------------------------------------------------------------------------


def encode(obj: Any, codec: str = JSON) -> bytes:
    """One wire record in the given codec: a binary frame (optionally
    CRC-trailed — the WAL at-rest form), or the JSON plane's
    ``{...}\\n`` line."""
    if codec == BINARY:
        return encode_binary(obj)
    if codec == BINARY_CRC:
        return encode_binary(obj, crc=True)
    return (jdumps(obj) + "\n").encode()


def wire_enabled() -> bool:
    """Process-wide binary-plane gate: ``TPU_SCHED_WIRE=json`` pins this
    process (offers AND answers) to the JSON compat plane."""
    return os.environ.get("TPU_SCHED_WIRE", BINARY).lower() != JSON


def accept_codec(accept_header: Optional[str]) -> str:
    """Server side of the negotiation: binary iff the client offered
    ``Accept: application/x-tpu-wire`` and this server is willing."""
    if accept_header and WIRE_MIME in accept_header and wire_enabled():
        return BINARY
    return JSON


def client_headers() -> Dict[str, str]:
    """Client side of the negotiation: the Accept offer (empty when this
    process is pinned to JSON)."""
    if wire_enabled():
        return {"Accept": WIRE_MIME}
    return {}


def stream_headers() -> Dict[str, str]:
    """Accept offer for long-lived streams (watch, replication tail):
    session frames preferred, plain binary as the fallback. Builds on
    client_headers so a JSON-pinned process (env var, or a test
    monkeypatching client_headers) offers neither."""
    h = client_headers()
    if h.get("Accept") == WIRE_MIME:
        return {"Accept": f"{SESSION_MIME}, {WIRE_MIME}"}
    return h


def accept_session(accept_header: Optional[str]) -> bool:
    """Server side of the session negotiation: True iff the client
    offered session frames and this server is willing. A True answer
    also implies the peer applies DELTA records (the session offer is
    the delta-capability signal — one negotiation, one capability set)."""
    return bool(accept_header and SESSION_MIME in accept_header
                and wire_enabled())


def mime_for(codec: str, session: bool = False) -> str:
    if codec != BINARY:
        return JSON_MIME
    return SESSION_MIME if session else WIRE_MIME


def codec_of_mime(content_type: Optional[str]) -> str:
    return BINARY if (content_type and WIRE_MIME in content_type) else JSON


def session_of_mime(content_type: Optional[str]) -> bool:
    """Client side of the session answer: did the server commit to
    session frames on this response body?"""
    return bool(content_type and SESSION_MIME in content_type)


# ---------------------------------------------------------------------------
# stream reading (watch / ship / paged LIST / snapshot bootstrap)
# ---------------------------------------------------------------------------


def read_event(fp, session: Optional[SessionDecoder] = None
               ) -> Optional[Tuple[Any, int, str]]:
    """Read one record off a stream (file-like, e.g. an HTTPResponse):
    ``(obj, wire_bytes, codec)``, or None at EOF. Sniffs PER RECORD, so a
    stream whose peer switches codec mid-flight (a binary follower tailing
    through a JSON leader's promotion) keeps decoding. A version-3 frame
    decodes against ``session`` (the stream's SessionDecoder) and is
    refused when the stream never negotiated one. Raises
    :class:`WireError` on a frame torn mid-stream — the caller's
    reconnect/re-list handling owns what happens next (exactly what a torn
    JSON line did via json.JSONDecodeError)."""
    first = fp.read(1)
    if not first:
        return None
    if first[0] == MAGIC:
        head = fp.read(1)
        if not head:
            raise WireError("stream torn in frame header")
        if head[0] not in (VERSION, VERSION_CRC, VERSION_SESSION):
            raise WireError(f"unknown wire version {head[0]}")
        crc_trailer = head[0] == VERSION_CRC
        if head[0] == VERSION_SESSION:
            if session is None:
                raise WireError("session frame on a non-session stream")
            dyn = session.dyn
        else:
            dyn = []
        n = 0
        shift = 0
        nbytes = 2
        while True:
            b = fp.read(1)
            if not b:
                raise WireError("stream torn in frame length")
            nbytes += 1
            n |= (b[0] & 0x7F) << shift
            if not b[0] & 0x80:
                break
            shift += 7
            if shift > 70:
                raise WireError("varint too long")
        payload = fp.read(n)
        while len(payload) < n:
            more = fp.read(n - len(payload))
            if not more:
                raise WireError("stream torn in frame payload")
            payload += more
        if crc_trailer:
            # A v2 frame on a stream (a peer relaying WAL bytes as-is):
            # verify, then decode — same contract as at rest.
            trailer = fp.read(4)
            while len(trailer) < 4:
                more = fp.read(4 - len(trailer))
                if not more:
                    raise WireError("stream torn in frame crc")
                trailer += more
            nbytes += 4
            if zlib.crc32(payload) != int.from_bytes(trailer, "big"):
                raise CorruptFrameError("crc mismatch in streamed frame")
        try:
            obj, end = _decode_value(payload, 0, dyn)
        except IndexError:
            raise WireError("frame truncated") from None
        if end != n:
            raise WireError("trailing bytes in frame")
        return obj, nbytes + n, BINARY
    line = first + fp.readline()
    return json.loads(line), len(line), JSON


# ---------------------------------------------------------------------------
# encode-once-per-codec carrier
# ---------------------------------------------------------------------------


# One process-wide lock for first-encode misses: encodes are
# GIL-serialized anyway, so serializing the misses costs nothing — but
# it turns N racing encodes of one shared item into one encode + N-1
# cache hits. Never taken on a hit.
_first_encode_lock = threading.Lock()


class WireItem:
    """One wire record with its encodings cached per codec: the watch
    fanout, the resume ring, and the replication backlog hold WireItems so
    an event is encoded ONCE per codec — not once per attached stream, and
    the WAL append shares the binary bytes with every binary follower.
    First-encode misses take a module-level lock (double-checked): N
    consumer threads draining fan-out queues in lock-step used to all
    miss together and each pay the full encode — pure duplicated work,
    since the encodes are GIL-serialized anyway. Cache hits never touch
    the lock.

    ``delta`` (PR 18) is the record's DELTA twin — the same event as a
    field-path patch against the receiver's cached base, minted once in
    the watch cache where the prior wire object was already in hand. It
    rides only to receivers that negotiated the capability: the WAL
    (``BINARY_CRC`` — recovery materializes it) and session streams
    (``session_bytes``). Plain binary and JSON peers always get the full
    object — an unknown peer can never be handed a patch it cannot
    apply."""

    __slots__ = ("obj", "_enc", "delta")

    def __init__(self, obj: Any, enc: Optional[Dict[str, bytes]] = None,
                 delta: Any = None):
        self.obj = obj
        self._enc = enc if enc is not None else {}
        self.delta = delta

    def bytes(self, codec: str = JSON) -> bytes:
        b = self._enc.get(codec)
        if b is None:
            with _first_encode_lock:
                return self._encode_miss(codec)
        return b

    def _encode_miss(self, codec: str) -> bytes:
        b = self._enc.get(codec)
        if b is not None:  # lost the race: the winner already cached it
            return b
        if self.delta is None:
            # v1 and v2 frames carry the IDENTICAL payload — v2 just
            # swaps the version byte and appends a CRC32 trailer. A WAL
            # frame is encoded as BINARY_CRC under the commit lock
            # before any ship stream asks for BINARY, so derive the
            # sibling by re-framing the cached payload instead of
            # re-encoding it: a slice (+ a C-speed crc32 in the other
            # direction) versus a full tree walk. (With a delta twin
            # the v2 bytes hold the PATCH, not the object: no
            # derivation.)
            if codec == BINARY and BINARY_CRC in self._enc:
                twin = self._enc[BINARY_CRC]
                b = self._enc[BINARY] = (
                    bytes((MAGIC, VERSION)) + twin[2:-4])
                return b
            if codec == BINARY_CRC and BINARY in self._enc:
                twin = self._enc[BINARY]
                p = 2
                while twin[p] & 0x80:
                    p += 1
                payload = twin[p + 1:]
                b = self._enc[BINARY_CRC] = (
                    bytes((MAGIC, VERSION_CRC)) + twin[2:]
                    + zlib.crc32(payload).to_bytes(4, "big"))
                return b
        obj = (self.delta if (self.delta is not None
                              and codec == BINARY_CRC) else self.obj)
        b = self._enc[codec] = encode(obj, codec)
        return b

    def session_bytes(self, enc: SessionEncoder) -> bytes:
        """Per-stream encode (consumer thread only) of the DELTA twin in
        this stream's session frames; never cached — session bytes are
        valid on exactly one connection. An item with NO twin returns the
        CACHED plain v1 frame instead (v1 and v3 frames legally
        interleave on a session stream): at fan-out, a per-stream
        session re-encode of a full record costs N× the encode the
        shared `WireItem` bytes already paid for — exactly the
        regression the once-per-codec cache exists to prevent."""
        if self.delta is None:
            return self.bytes(BINARY)
        return enc.encode(self.delta)
