"""Binary wire codec for the control plane — the ONE codec seam.

Every hot wire surface (WAL records on disk, the replication ship stream,
snapshot bootstrap pages, watch events incl. slim projections, bulk
binding envelopes, paged LIST pages) routes its encode/decode through this
module; the `wire-discipline` analyzer rule forbids raw ``json.dumps`` /
``json.loads`` on those surfaces anywhere else. The reference serves
protobuf/CBOR alongside JSON for exactly this reason (apimachinery runtime
codecs, SURVEY §1 L2); here the compact plane is a dependency-free binary
format and JSON remains the debug/compat plane forever.

Frame format (docs/WIRE.md):

- ``MAGIC (0xBF)  VERSION (1 byte)  varint payload_len  payload`` — a
  reader can always tell binary from JSON by the first byte (JSON lines on
  these surfaces start with ``{``; 0xBF is also not valid UTF-8 lead byte
  for JSON text). The length prefix gives WAL replay and stream reads the
  exact torn-frame semantics of newline-framed JSON: an incomplete or
  undecodable final frame is discarded and truncated away.
- The payload is ONE self-describing value:
  - one byte ``0x00..0xBE`` — the small int itself (rv deltas, ports,
    priorities, request milli-values);
  - ``0xC0`` None, ``0xC1`` True, ``0xC2`` False;
  - ``0xC3`` int: zigzag varint;
  - ``0xC4`` float: 8-byte IEEE-754 big-endian;
  - ``0xC6`` string define: varint byte-length + UTF-8 — and the string
    joins the intern table at the next free index;
  - ``0xC7`` string ref: varint index into the intern table;
  - ``0xC8`` list: varint count + items;
  - ``0xC9`` dict: varint count + (string key, value) pairs;
  - ``0xCA`` bytes: varint length + raw passthrough (already-encoded
    payloads ride untouched — JSON has no analogue, so the JSON codec
    refuses them).

Intern table: the wire on these surfaces is dominated by repeated dict
keys, kinds, namespaces, and node names. The table is seeded with the
protocol's WELL-KNOWN strings (bound to the VERSION byte — extending the
list bumps the version) and grows per frame: the first occurrence of any
other string is a define (same cost as inline), every later occurrence in
the SAME frame is a 2-3 byte ref. The table RESETS at every frame — so a
frame is self-contained, encode results are shareable across streams and
safe to replay after any prefix of the log is truncated away.

Negotiation (Accept:-style): a client that speaks binary sends
``Accept: application/x-tpu-wire``; a willing server answers binary
(``Content-Type: application/x-tpu-wire``) on success replies and data
streams — error bodies stay JSON always (the debug plane). Anything else
falls back to JSON on both sides. ``TPU_SCHED_WIRE=json`` pins a process
(client offers and server answers) to JSON — the A/B and interop lever.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

WIRE_MIME = "application/x-tpu-wire"
JSON_MIME = "application/json"

MAGIC = 0xBF
VERSION = 1
# Version-2 frame: identical payload encoding, but the frame carries a
# trailing 4-byte big-endian CRC32 over the payload — the WAL's
# at-rest plane. A COMPLETE frame whose CRC mismatches is corruption in
# the middle of the log (CorruptFrameError), distinct from a torn tail
# (scan returns None and the recovery truncates). Streams keep VERSION
# (the transport already detects torn frames by framing alone).
VERSION_CRC = 2

BINARY = "binary"
# WAL at-rest codec: version-2 CRC frames. Same payload bytes as BINARY,
# so WireItem caches the two independently and a binary ship stream
# never sees a CRC frame.
BINARY_CRC = "binary+crc"
JSON = "json"

# Well-known strings, seeded into every frame's intern table (indexes
# 0..N-1). ORDER IS THE WIRE CONTRACT: append only, and bump VERSION when
# you do — a reader keys its seed table off the frame's version byte.
WELL_KNOWN: Tuple[str, ...] = (
    # event / frame envelope
    "type", "object", "rv", "kind", "seq", "epoch", "tctx",
    "ADDED", "MODIFIED", "DELETED", "BOUND", "STATUS", "LEASE",
    "SYNC", "RESUME", "BOOKMARK", "FAILOVER", "TOO_OLD", "PAGE", "HB",
    "SNAP_META", "SNAP_END", "pods", "nodes", "leases",
    # pod wire
    "name", "namespace", "uid", "nodeName", "schedulerName",
    "nominatedNodeName", "labels", "annotations", "priority", "podGroup",
    "deletionTs", "finalizers", "requests", "cpu", "memory", "ephemeral",
    "scalar", "hostPorts", "port", "protocol", "hostIP", "tolerations",
    "key", "operator", "value", "effect", "nodeSelector", "affinity",
    "topologySpread", "maxSkew", "topologyKey", "whenUnsatisfiable",
    "labelSelector", "minDomains", "nodeAffinityPolicy", "nodeTaintsPolicy",
    "schedulingGates", "volumes", "pvc", "resourceClaims", "slim", "phase",
    "Pending", "Running", "default", "default-scheduler", "TCP",
    # selectors / affinity terms
    "matchLabels", "matchExpressions", "matchFields", "values", "op",
    "required", "preferred", "weight", "term", "namespaces",
    "namespaceSelector", "nodeAffinity", "podAffinity", "podAntiAffinity",
    # node wire
    "allocatable", "capacity", "taints", "unschedulable",
    "declaredFeatures", "NoSchedule", "zone", "topology.kubernetes.io/zone",
    # lease / replication / paging envelopes
    "holder", "duration", "transitions", "renew", "leaseDurationSeconds",
    "ageSeconds", "expired", "leader", "role", "follower", "repl",
    "listRv", "continue", "error", "code", "node", "bound", "created",
    "alreadyExists", "names", "k", "e",
)
_WK_INDEX: Dict[str, int] = {s: i for i, s in enumerate(WELL_KNOWN)}
_WK_N = len(WELL_KNOWN)

_TAG_NONE = 0xC0
_TAG_TRUE = 0xC1
_TAG_FALSE = 0xC2
_TAG_INT = 0xC3
_TAG_FLOAT = 0xC4
_TAG_STR_DEF = 0xC6
_TAG_STR_REF = 0xC7
_TAG_LIST = 0xC8
_TAG_DICT = 0xC9
_TAG_BYTES = 0xCA
_SMALL_INT_MAX = 0xBE  # 0x00..0xBE inline; 0xBF is the frame MAGIC


class WireError(ValueError):
    """Corrupt or truncated binary frame (the torn-record signal)."""


class CorruptFrameError(WireError):
    """A COMPLETE version-2 frame whose payload fails its CRC32 — bit
    rot (or a hostile edit) in the MIDDLE of a WAL, not a torn tail.
    Recovery must quarantine, never silently truncate: every record
    after the corrupt one is intact and would be lost."""


# ---------------------------------------------------------------------------
# JSON compat plane — the module-local seam the analyzer rule points at
# ---------------------------------------------------------------------------


def jdumps(obj: Any) -> str:
    """Compact JSON text — the debug/compat encode every non-binary wire
    path routes through (one call site class for the analyzer rule)."""
    return json.dumps(obj, separators=(",", ":"))


def jloads(data) -> Any:
    """JSON decode (str or bytes) — the compat-plane twin of jdumps."""
    return json.loads(data)


# ---------------------------------------------------------------------------
# binary encode
# ---------------------------------------------------------------------------


def _append_varint(buf: bytearray, n: int) -> None:
    while n > 0x7F:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n)


def _encode_value(buf: bytearray, obj: Any, interns: Dict[str, int],
                  pack_double=struct.Struct(">d").pack) -> None:
    # bool before int: bool is an int subclass but must round-trip as bool
    if obj is None:
        buf.append(_TAG_NONE)
    elif obj is True:
        buf.append(_TAG_TRUE)
    elif obj is False:
        buf.append(_TAG_FALSE)
    elif type(obj) is int:
        if 0 <= obj <= _SMALL_INT_MAX:
            buf.append(obj)
        else:
            buf.append(_TAG_INT)
            # zigzag over arbitrary-precision ints (Python has no 64-bit
            # wrap to lean on): non-negatives go even, negatives odd
            _append_varint(buf, (obj << 1) if obj >= 0 else ((-obj) << 1) - 1)
    elif type(obj) is str:
        _encode_str(buf, obj, interns)
    elif type(obj) is dict:
        buf.append(_TAG_DICT)
        _append_varint(buf, len(obj))
        for k, v in obj.items():
            if type(k) is not str:
                raise TypeError(f"wire dict keys must be str, got {type(k)}")
            _encode_str(buf, k, interns)
            _encode_value(buf, v, interns)
    elif type(obj) is list or type(obj) is tuple:
        buf.append(_TAG_LIST)
        _append_varint(buf, len(obj))
        for item in obj:
            _encode_value(buf, item, interns)
    elif type(obj) is float:
        buf.append(_TAG_FLOAT)
        buf += pack_double(obj)
    elif type(obj) is bytes:
        buf.append(_TAG_BYTES)
        _append_varint(buf, len(obj))
        buf += obj
    elif isinstance(obj, (int, float, str, dict, list, tuple, bytes)):
        # subclasses (IntEnum etc.): normalize through the base type
        base = (int if isinstance(obj, int) else
                float if isinstance(obj, float) else
                str if isinstance(obj, str) else
                bytes if isinstance(obj, bytes) else
                dict if isinstance(obj, dict) else list)
        _encode_value(buf, base(obj), interns)
    else:
        raise TypeError(f"not wire-encodable: {type(obj)}")


def _encode_str(buf: bytearray, s: str, interns: Dict[str, int]) -> None:
    idx = _WK_INDEX.get(s)
    if idx is None:
        idx = interns.get(s)
    if idx is not None:
        buf.append(_TAG_STR_REF)
        _append_varint(buf, idx)
        return
    interns[s] = _WK_N + len(interns)
    raw = s.encode()
    buf.append(_TAG_STR_DEF)
    _append_varint(buf, len(raw))
    buf += raw


def encode_binary(obj: Any, crc: bool = False) -> bytes:
    """One framed binary record: MAGIC VERSION varint(len) payload.
    With ``crc`` the frame is version 2 and a 4-byte big-endian CRC32
    over the payload trails it (the WAL at-rest format)."""
    payload = bytearray()
    _encode_value(payload, obj, {})
    frame = bytearray((MAGIC, VERSION_CRC if crc else VERSION))
    _append_varint(frame, len(payload))
    frame += payload
    if crc:
        frame += zlib.crc32(payload).to_bytes(4, "big")
    return bytes(frame)


# ---------------------------------------------------------------------------
# binary decode
# ---------------------------------------------------------------------------


def _read_varint(buf, pos: int) -> Tuple[int, int]:
    n = 0
    shift = 0
    ln = len(buf)
    while True:
        if pos >= ln:
            raise WireError("varint past end")
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:
            raise WireError("varint too long")


def _decode_value(buf, pos: int, dyn: List[str], wk=WELL_KNOWN, wk_n=_WK_N,
                  unpack_double=struct.Struct(">d").unpack_from):
    """Hot decode loop. Truncation surfaces as IndexError (byte indexing
    past the end) — the public entry points convert it to WireError; the
    fast path pays no explicit bounds checks. Varints are read inline:
    nearly every count/index/ref on this wire fits one byte."""
    tag = buf[pos]
    pos += 1
    if tag <= _SMALL_INT_MAX:
        return tag, pos
    if tag == _TAG_STR_REF:
        idx = buf[pos]
        pos += 1
        if idx & 0x80:
            idx, pos = _read_varint_cont(buf, pos, idx)
        if idx < wk_n:
            return wk[idx], pos
        try:
            return dyn[idx - wk_n], pos
        except IndexError:
            raise WireError(f"intern ref {idx} undefined") from None
    if tag == _TAG_DICT:
        n = buf[pos]
        pos += 1
        if n & 0x80:
            n, pos = _read_varint_cont(buf, pos, n)
        d = {}
        dec = _decode_value
        for _ in range(n):
            k, pos = dec(buf, pos, dyn)
            if type(k) is not str:
                raise WireError("non-str dict key")
            d[k], pos = dec(buf, pos, dyn)
        return d, pos
    if tag == _TAG_STR_DEF:
        n = buf[pos]
        pos += 1
        if n & 0x80:
            n, pos = _read_varint_cont(buf, pos, n)
        end = pos + n
        if end > len(buf):
            raise WireError("string past end")
        try:
            s = bytes(buf[pos:end]).decode()
        except UnicodeDecodeError as e:
            raise WireError("bad utf-8") from e
        dyn.append(s)
        return s, end
    if tag == _TAG_LIST:
        n = buf[pos]
        pos += 1
        if n & 0x80:
            n, pos = _read_varint_cont(buf, pos, n)
        out = []
        append = out.append
        dec = _decode_value
        for _ in range(n):
            v, pos = dec(buf, pos, dyn)
            append(v)
        return out, pos
    if tag == _TAG_INT:
        z, pos = _read_varint(buf, pos)
        return (z >> 1) ^ -(z & 1), pos
    if tag == _TAG_FLOAT:
        if pos + 8 > len(buf):
            raise WireError("float past end")
        return unpack_double(buf, pos)[0], pos + 8
    if tag == _TAG_NONE:
        return None, pos
    if tag == _TAG_TRUE:
        return True, pos
    if tag == _TAG_FALSE:
        return False, pos
    if tag == _TAG_BYTES:
        n, pos = _read_varint(buf, pos)
        end = pos + n
        if end > len(buf):
            raise WireError("bytes past end")
        return bytes(buf[pos:end]), end
    raise WireError(f"unknown tag 0x{tag:02x}")


def _read_varint_cont(buf, pos: int, first: int) -> Tuple[int, int]:
    """Continue a varint whose first byte had the continuation bit set."""
    n = first & 0x7F
    shift = 7
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 70:
            raise WireError("varint too long")


def decode_binary(data) -> Any:
    """Decode ONE complete binary frame (header included)."""
    got = scan(data, 0)
    if got is None:
        raise WireError("incomplete frame")
    obj, end = got
    if end != len(data):
        raise WireError("trailing bytes after frame")
    return obj


def scan(buf, pos: int) -> Optional[Tuple[Any, int]]:
    """Parse one record (binary frame OR ``{...}\\n`` JSON line) at ``pos``
    in ``buf``. Returns ``(obj, next_pos)``, or None when everything from
    ``pos`` on is torn — incomplete or undecodable — and must be truncated
    away (the WAL replay contract, identical for both codecs). A COMPLETE
    version-2 frame failing its CRC raises :class:`CorruptFrameError`
    instead: that is damage in the middle of the log, not a torn tail."""
    ln = len(buf)
    if pos >= ln:
        return None
    first = buf[pos]
    if first == MAGIC:
        if pos + 2 > ln:
            return None
        if buf[pos + 1] == VERSION_CRC:
            return _scan_crc(buf, pos, ln)
        try:
            # version byte reserved: an unknown version in a terminated
            # frame is torn data
            if buf[pos + 1] != VERSION:
                return None
            n, p = _read_varint(buf, pos + 2)
            if p + n > ln:
                return None
            obj, end = _decode_value(buf[p:p + n], 0, [])
            if end != n:
                return None
            return obj, p + n
        except (WireError, IndexError):
            return None
    # JSON line plane (old WALs / JSON peers)
    nl = buf.find(b"\n", pos) if isinstance(buf, (bytes, bytearray)) else -1
    if nl < 0:
        return None
    try:
        return json.loads(bytes(buf[pos:nl])), nl + 1
    except ValueError:
        return None


def _scan_crc(buf, pos: int, ln: int) -> Optional[Tuple[Any, int]]:
    """One version-2 (CRC-trailed) frame at ``pos``. Incomplete bytes —
    length varint, payload, or the CRC trailer itself running past the
    buffer — are a torn tail (None, truncate). A complete frame is
    integrity-checked BEFORE any payload decode; a mismatch (or a decode
    failure inside a CRC-verified payload, which can only mean writer
    corruption) raises CorruptFrameError. Header bytes ride outside the
    CRC: damage there is caught by framing (bad magic/version/varint)
    and resolves as torn data, the one case this plane cannot tell from
    a genuine tail."""
    try:
        n, p = _read_varint(buf, pos + 2)
    except WireError:
        return None  # length varint runs past the buffer: torn tail
    end = p + n + 4
    if end > ln:
        return None  # payload or CRC trailer incomplete: torn tail
    payload = bytes(buf[p:p + n])
    want = int.from_bytes(bytes(buf[p + n:end]), "big")
    got = zlib.crc32(payload)
    if got != want:
        raise CorruptFrameError(
            f"crc mismatch in frame at offset {pos}: "
            f"stored 0x{want:08x}, computed 0x{got:08x}")
    try:
        obj, used = _decode_value(payload, 0, [])
        if used != n:
            raise WireError("trailing bytes in frame")
    except (WireError, IndexError) as e:
        raise CorruptFrameError(
            f"undecodable payload in crc-verified frame at offset "
            f"{pos}: {e}") from e
    return obj, end


def decode(data) -> Any:
    """Sniff-decode one complete record, either codec (bodies, frames)."""
    if data and data[0] == MAGIC:
        return decode_binary(data)
    return json.loads(data)


# ---------------------------------------------------------------------------
# the negotiated seam
# ---------------------------------------------------------------------------


def encode(obj: Any, codec: str = JSON) -> bytes:
    """One wire record in the given codec: a binary frame (optionally
    CRC-trailed — the WAL at-rest form), or the JSON plane's
    ``{...}\\n`` line."""
    if codec == BINARY:
        return encode_binary(obj)
    if codec == BINARY_CRC:
        return encode_binary(obj, crc=True)
    return (jdumps(obj) + "\n").encode()


def wire_enabled() -> bool:
    """Process-wide binary-plane gate: ``TPU_SCHED_WIRE=json`` pins this
    process (offers AND answers) to the JSON compat plane."""
    return os.environ.get("TPU_SCHED_WIRE", BINARY).lower() != JSON


def accept_codec(accept_header: Optional[str]) -> str:
    """Server side of the negotiation: binary iff the client offered
    ``Accept: application/x-tpu-wire`` and this server is willing."""
    if accept_header and WIRE_MIME in accept_header and wire_enabled():
        return BINARY
    return JSON


def client_headers() -> Dict[str, str]:
    """Client side of the negotiation: the Accept offer (empty when this
    process is pinned to JSON)."""
    if wire_enabled():
        return {"Accept": WIRE_MIME}
    return {}


def mime_for(codec: str) -> str:
    return WIRE_MIME if codec == BINARY else JSON_MIME


def codec_of_mime(content_type: Optional[str]) -> str:
    return BINARY if (content_type and WIRE_MIME in content_type) else JSON


# ---------------------------------------------------------------------------
# stream reading (watch / ship / paged LIST / snapshot bootstrap)
# ---------------------------------------------------------------------------


def read_event(fp) -> Optional[Tuple[Any, int, str]]:
    """Read one record off a stream (file-like, e.g. an HTTPResponse):
    ``(obj, wire_bytes, codec)``, or None at EOF. Sniffs PER RECORD, so a
    stream whose peer switches codec mid-flight (a binary follower tailing
    through a JSON leader's promotion) keeps decoding. Raises
    :class:`WireError` on a frame torn mid-stream — the caller's
    reconnect/re-list handling owns what happens next (exactly what a torn
    JSON line did via json.JSONDecodeError)."""
    first = fp.read(1)
    if not first:
        return None
    if first[0] == MAGIC:
        head = fp.read(1)
        if not head:
            raise WireError("stream torn in frame header")
        if head[0] not in (VERSION, VERSION_CRC):
            raise WireError(f"unknown wire version {head[0]}")
        crc_trailer = head[0] == VERSION_CRC
        n = 0
        shift = 0
        nbytes = 2
        while True:
            b = fp.read(1)
            if not b:
                raise WireError("stream torn in frame length")
            nbytes += 1
            n |= (b[0] & 0x7F) << shift
            if not b[0] & 0x80:
                break
            shift += 7
            if shift > 70:
                raise WireError("varint too long")
        payload = fp.read(n)
        while len(payload) < n:
            more = fp.read(n - len(payload))
            if not more:
                raise WireError("stream torn in frame payload")
            payload += more
        if crc_trailer:
            # A v2 frame on a stream (a peer relaying WAL bytes as-is):
            # verify, then decode — same contract as at rest.
            trailer = fp.read(4)
            while len(trailer) < 4:
                more = fp.read(4 - len(trailer))
                if not more:
                    raise WireError("stream torn in frame crc")
                trailer += more
            nbytes += 4
            if zlib.crc32(payload) != int.from_bytes(trailer, "big"):
                raise CorruptFrameError("crc mismatch in streamed frame")
        try:
            obj, end = _decode_value(payload, 0, [])
        except IndexError:
            raise WireError("frame truncated") from None
        if end != n:
            raise WireError("trailing bytes in frame")
        return obj, nbytes + n, BINARY
    line = first + fp.readline()
    return json.loads(line), len(line), JSON


# ---------------------------------------------------------------------------
# encode-once-per-codec carrier
# ---------------------------------------------------------------------------


class WireItem:
    """One wire record with its encodings cached per codec: the watch
    fanout, the resume ring, and the replication backlog hold WireItems so
    an event is encoded ONCE per codec — not once per attached stream, and
    the WAL append shares the binary bytes with every binary follower.
    Benignly racy: two stream threads may both encode the first time; the
    encodes are identical and one wins."""

    __slots__ = ("obj", "_enc")

    def __init__(self, obj: Any, enc: Optional[Dict[str, bytes]] = None):
        self.obj = obj
        self._enc = enc if enc is not None else {}

    def bytes(self, codec: str = JSON) -> bytes:
        b = self._enc.get(codec)
        if b is None:
            b = self._enc[codec] = encode(self.obj, codec)
        return b
