"""Scheduler extenders: the webhook extension surface.

Re-expresses pkg/scheduler/extender.go (HTTPExtender :44; verbs filter /
prioritize / bind / preempt :46-49) and the extender wiring in
schedule_one.go:894 findNodesThatPassExtenders and :989-1048 extender scoring.

Transport is pluggable: production uses HTTP POST of JSON args (urllib),
tests inject an in-process callable — the same seam the reference's
fake_extender.go uses (SURVEY.md §4.2).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod
from ..core.framework import NodeScore, Status
from ..core.node_info import NodeInfo

MAX_EXTENDER_PRIORITY = 10  # extender/v1 MaxExtenderPriority


def http_transport(url_prefix: str, timeout: float = 5.0):
    def call(verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{url_prefix.rstrip('/')}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    return call


@dataclass
class Extender:
    """One configured extender (config ExtenderConfig → HTTPExtender)."""

    name: str = "extender"
    filter_verb: str = ""        # "" = extender doesn't filter
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""       # "" = extender doesn't process preemption
    weight: int = 1
    node_cache_capable: bool = False     # send node names only
    ignorable: bool = False              # errors don't fail scheduling
    managed_resources: Tuple[str, ...] = ()  # only pods requesting these
    transport: Optional[Callable[[str, dict], dict]] = None

    def is_interested(self, pod: Pod) -> bool:
        """extender.go IsInterested: no managedResources = all pods."""
        if not self.managed_resources:
            return True
        req = pod.resource_request()
        names = set(req.scalar_resources) | {
            n for n in ("cpu", "memory") if req.get(n) > 0}
        return bool(names & set(self.managed_resources))

    def supports_filter(self) -> bool:
        return bool(self.filter_verb)

    def supports_prioritize(self) -> bool:
        return bool(self.prioritize_verb)

    def supports_bind(self) -> bool:
        return bool(self.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.preempt_verb)

    # -- verbs -------------------------------------------------------------

    def filter(self, pod: Pod, nodes: Sequence[NodeInfo]) -> Tuple[List[NodeInfo], Dict[str, str], Optional[str]]:
        """Returns (feasible, failed_and_unresolvable?, error). Response shape
        mirrors extender/v1 ExtenderFilterResult (NodeNames/FailedNodes)."""
        payload = {
            "pod": {"name": pod.name, "namespace": pod.namespace, "uid": pod.uid},
            "nodenames": [ni.name for ni in nodes],
        }
        try:
            resp = self.transport("filter", payload)
        except Exception as e:  # noqa: BLE001
            return (list(nodes), {}, None) if self.ignorable else ([], {}, str(e))
        if resp.get("error"):
            return (list(nodes), {}, None) if self.ignorable else ([], {}, resp["error"])
        keep = resp.get("nodenames")
        failed = dict(resp.get("failedNodes", {}))
        if keep is None:
            return list(nodes), failed, None
        keep_set = set(keep)
        return [ni for ni in nodes if ni.name in keep_set], failed, None

    def prioritize(self, pod: Pod, nodes: Sequence[NodeInfo]) -> Dict[str, int]:
        """extender/v1 HostPriorityList → {node: score*weight}."""
        payload = {
            "pod": {"name": pod.name, "namespace": pod.namespace, "uid": pod.uid},
            "nodenames": [ni.name for ni in nodes],
        }
        try:
            resp = self.transport("prioritize", payload)
        except Exception:  # noqa: BLE001
            return {}
        out = {}
        for item in resp.get("hostPriorityList", []):
            out[item["host"]] = int(item["score"]) * self.weight
        return out

    def process_preemption(
        self, pod: Pod, node_name_to_victims: Dict[str, list]
    ) -> Tuple[Dict[str, list], Optional[str]]:
        """ProcessPreemption (extender.go:46-49 / :310): send the candidate
        victim map; the extender returns the subset of nodes (possibly with
        trimmed victim lists) it accepts for preemption. Response shape
        mirrors extender/v1 ExtenderPreemptionResult (NodeNameToMetaVictims,
        collapsed to victim-uid lists here). Unlisted nodes are dropped;
        errors drop the extender's input unless `ignorable`."""
        payload = {
            "pod": {"name": pod.name, "namespace": pod.namespace, "uid": pod.uid},
            "nodeNameToVictims": {
                node: [pi.pod.uid for pi in victims]
                for node, victims in node_name_to_victims.items()},
        }
        try:
            resp = self.transport("preempt", payload)
        except Exception as e:  # noqa: BLE001
            if self.ignorable:
                return node_name_to_victims, None
            return {}, str(e)
        if resp.get("error"):
            if self.ignorable:
                return node_name_to_victims, None
            return {}, resp["error"]
        accepted = resp.get("nodeNameToVictims")
        if accepted is None:
            return node_name_to_victims, None
        out = {}
        for node, uids in accepted.items():
            victims = node_name_to_victims.get(node)
            if victims is None:
                continue
            keep = set(uids)
            out[node] = [pi for pi in victims if pi.pod.uid in keep]
        return out, None

    def bind(self, pod: Pod, node_name: str) -> Optional[str]:
        try:
            resp = self.transport("bind", {
                "podName": pod.name, "podNamespace": pod.namespace,
                "podUID": pod.uid, "node": node_name})
        except Exception as e:  # noqa: BLE001
            return str(e)
        return resp.get("error") or None


def run_extender_filters(
    extenders: Sequence[Extender], pod: Pod, feasible: List[NodeInfo], diagnosis
) -> Tuple[List[NodeInfo], Optional[Status]]:
    """schedule_one.go:894 findNodesThatPassExtenders."""
    for ext in extenders:
        if not feasible:
            break
        if not ext.supports_filter() or not ext.is_interested(pod):
            continue
        feasible, failed, err = ext.filter(pod, feasible)
        if err is not None:
            return [], Status.error(f"extender {ext.name}: {err}")
        for node, reason in failed.items():
            diagnosis.node_to_status[node] = Status.unschedulable(reason)
    return feasible, None


def run_extender_preemption(
    extenders: Sequence[Extender], pod: Pod,
    node_name_to_victims: Dict[str, list],
) -> Tuple[Dict[str, list], Optional[str]]:
    """preemption.go callExtenders: chain ProcessPreemption through every
    preempt-capable interested extender, narrowing the candidate map. A
    non-ignorable transport error surfaces as (original_map_unused, error) —
    the attempt must fail retryably, not park the pod unresolvable."""
    for ext in extenders:
        if not node_name_to_victims:
            break
        if not ext.supports_preemption() or not ext.is_interested(pod):
            continue
        node_name_to_victims, err = ext.process_preemption(
            pod, node_name_to_victims)
        if err is not None:
            return {}, err
    return node_name_to_victims, None


def run_extender_prioritize(
    extenders: Sequence[Extender], pod: Pod, nodes: Sequence[NodeInfo],
    scores: List[NodeScore],
) -> None:
    """schedule_one.go:989-1048: extender scores add onto plugin totals."""
    for ext in extenders:
        if not ext.supports_prioritize() or not ext.is_interested(pod):
            continue
        ext_scores = ext.prioritize(pod, nodes)
        for ns in scores:
            ns.score += ext_scores.get(ns.name, 0)
