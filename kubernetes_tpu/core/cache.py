"""Scheduler cache: assumed-pod-aware aggregate of cluster state with
generation-based incremental snapshots.

Re-expresses pkg/scheduler/backend/cache/cache.go (cacheImpl :61): the cache
holds authoritative NodeInfos, tracks pods assumed-but-not-yet-bound
(AssumePod/ForgetPod/ExpirePod), and refreshes an immutable per-cycle Snapshot
incrementally — only NodeInfos whose generation advanced since the last
UpdateSnapshot are re-cloned (cache.go:206,236-262). The same dirty-generation
walk drives the device mirror's row scatter (kubernetes_tpu/ops.device_state).

The reference's doubly-linked generation list is replaced by a dirty-name set:
equivalent observable behavior, simpler host code.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Set

from ..api.types import Namespace, Node, Pod
from .node_info import NodeInfo, PodInfo, next_generation
from .node_tree import NodeTree


# ---------------------------------------------------------------------------
# Typed cluster-event journal
# ---------------------------------------------------------------------------
#
# The old consumer contract was ONE integer (`Scheduler.cluster_event_seq`):
# a device session could only ask "did anything change since seq S" and tear
# its plan+carry down on any yes. The journal keeps the integer (it is still
# the version every cache consumer keys on) but records WHAT each bump was —
# (kind, node/namespace key, patch-relevant pod facts) — so a session can ask
# "what changed since S" and delta-patch the exact rows an event dirtied
# instead of rebuilding snapshot→features from scratch (the incremental-
# resume generalization of cache.go:206's generation walk; KEP-5598's
# opportunistic batching has the same never-restart-per-event shape).

# Queue-only change: scheduling-gate lift, pending-pod update/delete,
# pod-group registration. Dirties NOTHING node-side — a live session's
# state, plan and carry all stay exact.
EV_QUEUE = "queue"
# Namespace created / labels changed. Only affinity namespaceSelector
# matching reads namespace labels, so this is benign for plans with no
# inter-pod-affinity machinery anywhere in play.
EV_NAMESPACE = "namespace"
# Pod appeared on / left / changed on a node (key = node name). Dirties that
# node's resource aggregates (req_r/nonzero/pod_count rows); dirties
# pod-derived feature tables too unless the pod is `plain` (see
# pod_event_flags) and the plan carries none.
EV_POD_ADD = "pod_add"
EV_POD_REMOVE = "pod_remove"
EV_POD_UPDATE = "pod_update"
# Node object replaced in place with labels/images/declared-features intact
# (key = node name): dirties that row's taint/allocatable/unschedulable
# tensors only. Label or image changes are NOT this kind — they dirty
# host-evaluated per-node feature vectors (sel_match/il_score/na_raw) and
# topology vids, which the delta path does not patch.
EV_NODE_UPDATE = "node_update"
# Node added/removed: row order changes — never delta-patchable.
EV_STRUCTURAL = "structural"
# Everything else (storage objects, reconcile unwinds): full rebuild.
EV_OTHER = "other"


class ClusterEvent(NamedTuple):
    seq: int
    kind: str
    key: str = ""          # node name (pod/node kinds) or namespace name
    # Pod-side facts captured at record time (patch eligibility is decided
    # later, against a specific plan):
    pod_plain: bool = False   # no affinity/spread terms, no PVC/DRA claims
    pod_ports: bool = False   # requests host ports
    # True when the event can only ENLARGE feasibility (pod removed, taint
    # lifted, capacity grown): results already computed on device against the
    # pre-event state remain feasible, so in-flight batches may still commit
    # while the patch waits for the pipeline to drain. Those commits keep
    # their pre-event SCORES — a deliberate relaxation that only applies to
    # events arriving asynchronously mid-session (the threaded inbox seam),
    # where no interleaving against in-flight evaluations is defined and
    # committing them is a legal linearization (the event lands just after).
    # Deterministic (inline) event streams only ever patch at empty-pipeline
    # boundaries, so the bit-identical-to-host-oracle invariant the
    # equivalence suites enforce is unaffected.
    shrink: bool = False


def pod_event_flags(pod: Pod) -> tuple:
    """(pod_plain, pod_ports) for a journal record. `plain` means the pod
    cannot dirty any pod-derived feature table: no affinity/anti-affinity
    terms (required or preferred), no topology-spread constraints, no
    PVC-backed volumes (per-node attach counts), no DRA claims."""
    aff = pod.affinity
    plain = not (
        pod.topology_spread_constraints
        or (aff is not None and (aff.pod_affinity or aff.pod_anti_affinity))
        or any(v.pvc_name for v in pod.volumes)
        or getattr(pod, "resource_claims", None)
    )
    return plain, bool(pod.host_ports())


class EventJournal:
    """Bounded journal of node-state-relevant cluster events.

    `seq` is the authoritative cluster-event version (the scheduler mirrors
    it as `cluster_event_seq`). `since(S)` answers "what changed after S" —
    or None when S has fallen off the retention window, which consumers must
    treat as "anything may have changed" (full rebuild)."""

    __slots__ = ("cap", "seq", "_events")

    def __init__(self, capacity: int = 4096):
        self.cap = capacity
        self.seq = 0
        self._events: deque = deque()

    def record(self, kind: str, key: str = "", pod_plain: bool = False,
               pod_ports: bool = False, shrink: bool = False) -> int:
        self.seq += 1
        self._events.append(ClusterEvent(
            self.seq, kind, key, pod_plain, pod_ports, shrink))
        if len(self._events) > self.cap:
            self._events.popleft()
        return self.seq

    def since(self, seq: int) -> Optional[List[ClusterEvent]]:
        """Events with .seq > seq in order, [] when nothing happened, or
        None when the window was truncated (events older than retention).
        Walks from the RIGHT so the per-invalidation-check cost is
        O(new events), not O(retained window)."""
        if seq >= self.seq:
            return []
        if not self._events or self._events[0].seq > seq + 1:
            return None
        out: List[ClusterEvent] = []
        for e in reversed(self._events):
            if e.seq <= seq:
                break
            out.append(e)
        out.reverse()
        return out


class Snapshot:
    """Immutable per-cycle view (backend/cache/snapshot.go)."""

    def __init__(self):
        self.node_info_map: Dict[str, NodeInfo] = {}
        self.node_info_list: List[NodeInfo] = []
        self.have_pods_with_affinity_list: List[NodeInfo] = []
        self.have_pods_with_required_anti_affinity_list: List[NodeInfo] = []
        self.used_pvc_count: Dict[str, int] = {}
        self.image_num_nodes: Dict[str, int] = {}
        self.generation: int = 0
        self._index: Dict[str, int] = {}
        self._list_members: set = set()

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)

    def num_nodes(self) -> int:
        return len(self.node_info_list)

    def rebuild_lists(self) -> None:
        self.have_pods_with_affinity_list = [
            ni for ni in self.node_info_list if ni.pods_with_affinity
        ]
        self.have_pods_with_required_anti_affinity_list = [
            ni for ni in self.node_info_list if ni.pods_with_required_anti_affinity
        ]
        self.image_num_nodes = {}
        for ni in self.node_info_list:
            for img in ni.image_states:
                self.image_num_nodes[img] = self.image_num_nodes.get(img, 0) + 1
        self._index = {ni.name: i for i, ni in enumerate(self.node_info_list)}
        self._list_members = (
            {ni.name for ni in self.have_pods_with_affinity_list}
            | {ni.name for ni in self.have_pods_with_required_anti_affinity_list})

    # -- in-cycle what-if mutation (gang simulation, snapshot.go:545/:599) --

    def assume_pod(self, pod: Pod) -> None:
        ni = self.node_info_map.get(pod.node_name)
        if ni is None:
            return
        had_aff = bool(ni.pods_with_affinity)
        had_anti = bool(ni.pods_with_required_anti_affinity)
        ni.add_pod(PodInfo.of(pod))
        # Keep the affinity sublists consistent mid-simulation: PreFilter
        # consumers (InterPodAffinity sublist shortcut, ops/features.py)
        # read them against the SAME snapshot object while gang simulations
        # assume members in (snapshot.go AddPod keeps its lists in step).
        if not had_aff and ni.pods_with_affinity:
            self.have_pods_with_affinity_list.append(ni)
            self._list_members.add(ni.name)
        if not had_anti and ni.pods_with_required_anti_affinity:
            self.have_pods_with_required_anti_affinity_list.append(ni)
            self._list_members.add(ni.name)

    def forget_pod(self, pod: Pod) -> None:
        ni = self.node_info_map.get(pod.node_name)
        if ni is None:
            return
        had_aff = bool(ni.pods_with_affinity)
        had_anti = bool(ni.pods_with_required_anti_affinity)
        ni.remove_pod(pod)
        if had_aff and not ni.pods_with_affinity:
            self.have_pods_with_affinity_list = [
                x for x in self.have_pods_with_affinity_list if x is not ni]
        if had_anti and not ni.pods_with_required_anti_affinity:
            self.have_pods_with_required_anti_affinity_list = [
                x for x in self.have_pods_with_required_anti_affinity_list
                if x is not ni]
        if not ni.pods_with_affinity and not ni.pods_with_required_anti_affinity:
            self._list_members.discard(ni.name)

    # -- placement mutation session (snapshot.go:276 StartMutations / :317
    # EndMutations / :708 AssumePlacement): restrict the visible node list to
    # a candidate placement while simulating a pod group against it. NodeInfo
    # objects are shared with the full list, so in-simulation assume/forget
    # stay visible after the placement is forgotten.

    def assume_placement(self, node_names) -> None:
        assert not hasattr(self, "_placement_saved"), "placement already assumed"
        wanted = set(node_names)
        self._placement_saved = self.node_info_list
        self.node_info_list = [ni for ni in self._placement_saved
                               if ni.name in wanted]
        self.rebuild_lists()

    def forget_placement(self) -> None:
        self.node_info_list = self._placement_saved
        del self._placement_saved
        self.rebuild_lists()

    def placement_active(self) -> bool:
        return hasattr(self, "_placement_saved")


class _PodState:
    __slots__ = ("pod", "deadline", "binding_finished")

    def __init__(self, pod: Pod):
        self.pod = pod
        self.deadline: Optional[float] = None
        self.binding_finished = False


class Cache:
    """cacheImpl (backend/cache/cache.go:61)."""

    def __init__(self, ttl_seconds: float = 0.0, now: Callable[[], float] = time.monotonic):
        self.ttl = ttl_seconds
        self.now = now
        self.nodes: Dict[str, NodeInfo] = {}
        # Snapshot order = zone-interleaved NodeTree order + imaginary
        # placeholders; rebuilt lazily when tree membership changes, so
        # truncated sampling spreads across zones exactly as the reference's
        # updateNodeInfoSnapshotList does (backend/cache/snapshot.go,
        # node_tree.go list()).
        self.node_order: List[str] = []
        self._imaginary: List[str] = []  # pods observed before their node
        self._order_dirty = False
        self.node_tree = NodeTree()
        self.assumed_pods: Set[str] = set()
        self.pod_states: Dict[str, _PodState] = {}
        self.namespaces: Dict[str, Namespace] = {}
        # Cluster-wide PVC reference counts over cached+assumed pods (the
        # device path's claim-sharing eligibility check reads this — a
        # shared claim must not ride the kernel's counted-attach encoding).
        self.pvc_refs: Dict[str, int] = {}
        # Count of cached+assumed pods carrying ANY inter-pod (anti-)affinity
        # term. Zero means pod labels and namespaces are scheduling-inert for
        # affinity-free incoming pods — the live-truth gate behind the
        # namespace-erased session signature (models/tpu_scheduler.py
        # _neutral_sig) and the namespace-event delta classification.
        self.affinity_pod_refs = 0
        # Optional scheduled-group-pods index (core/podgroupstate.py), kept
        # in lockstep with the cache's pod view (assumed + bound) — the
        # scheduler-side truth placement generation pins domains against.
        self.pod_group_state = None
        self._dirty: Set[str] = set()
        self._removed_since_snapshot = False

    # -- nodes -------------------------------------------------------------

    def add_node(self, node: Node) -> NodeInfo:
        ni = self.nodes.get(node.name)
        if ni is None:
            ni = NodeInfo(node)
            self.nodes[node.name] = ni
        else:
            ni.set_node(node)
        if node.name in self._imaginary:  # placeholder became real
            self._imaginary.remove(node.name)
            self._order_dirty = True
        if self.node_tree.add_node(node):
            self._order_dirty = True
        self._dirty.add(node.name)
        return ni

    def update_node(self, node: Node) -> NodeInfo:
        return self.add_node(node)

    def remove_node(self, node_name: str) -> None:
        ni = self.nodes.pop(node_name, None)
        if ni is not None:
            if ni.node is not None:
                self.node_tree.remove_node(ni.node)
            if node_name in self._imaginary:
                self._imaginary.remove(node_name)
            self._order_dirty = True
            self._removed_since_snapshot = True
        self._dirty.discard(node_name)

    # -- namespaces --------------------------------------------------------

    def add_namespace(self, ns: Namespace) -> None:
        self.namespaces[ns.name] = ns

    def namespace_labels(self, name: str) -> Optional[Dict[str, str]]:
        ns = self.namespaces.get(name)
        return ns.labels if ns else None

    # -- pods --------------------------------------------------------------

    def assume_pod(self, pod: Pod, pod_info: Optional[PodInfo] = None) -> None:
        """AssumePod (cache.go): optimistically place the pod on its node
        before the bind API call completes. `pod_info` lets callers reuse the
        queue entity's precomputed PodInfo (QueuedPodInfo.pod_info) instead
        of re-deriving it — this runs once per scheduled pod."""
        if pod.uid in self.pod_states:
            raise ValueError(f"pod {pod.uid} is already assumed/added")
        self._add_pod_to_node(pod, pod_info)
        self.assumed_pods.add(pod.uid)
        self.pod_states[pod.uid] = _PodState(pod)

    def finish_binding(self, pod: Pod) -> None:
        st = self.pod_states.get(pod.uid)
        if st is not None and pod.uid in self.assumed_pods:
            st.binding_finished = True
            if self.ttl > 0:
                st.deadline = self.now() + self.ttl

    def forget_pod(self, pod: Pod) -> None:
        st = self.pod_states.get(pod.uid)
        if st is None or pod.uid not in self.assumed_pods:
            return
        self._remove_pod_from_node(st.pod)
        self.assumed_pods.discard(pod.uid)
        del self.pod_states[pod.uid]

    def add_pod(self, pod: Pod) -> None:
        """Confirmed (watch-observed) pod add. Replaces the assumed copy."""
        st = self.pod_states.get(pod.uid)
        if st is not None:
            if pod.uid in self.assumed_pods:
                if st.pod.node_name != pod.node_name:
                    self._remove_pod_from_node(st.pod)
                    self._add_pod_to_node(pod)
                self.assumed_pods.discard(pod.uid)
            st.pod = pod
            st.deadline = None
        else:
            self._add_pod_to_node(pod)
            self.pod_states[pod.uid] = _PodState(pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        if new.uid in self.assumed_pods:
            # Watch-confirmed version of a pod we assumed: treat as Add.
            self.add_pod(new)
            return
        st = self.pod_states.get(old.uid)
        if st is None:
            self.add_pod(new)
            return
        self._remove_pod_from_node(st.pod)
        self._add_pod_to_node(new)
        st.pod = new

    def remove_pod(self, pod: Pod) -> None:
        st = self.pod_states.pop(pod.uid, None)
        if st is not None:
            self._remove_pod_from_node(st.pod)
        self.assumed_pods.discard(pod.uid)

    def is_assumed_pod(self, pod: Pod) -> bool:
        return pod.uid in self.assumed_pods

    def cleanup_expired_assumed_pods(self) -> None:
        if self.ttl <= 0:
            return
        now = self.now()
        for uid in list(self.assumed_pods):
            st = self.pod_states[uid]
            if st.binding_finished and st.deadline is not None and now > st.deadline:
                self._remove_pod_from_node(st.pod)
                self.assumed_pods.discard(uid)
                del self.pod_states[uid]

    def _add_pod_to_node(self, pod: Pod, pod_info: Optional[PodInfo] = None) -> None:
        ni = self.nodes.get(pod.node_name)
        if ni is None:
            # Pod on unknown node: create a placeholder NodeInfo (reference
            # keeps an imaginary nodeInfo so pods on deleted nodes still count).
            ni = NodeInfo()
            self.nodes[pod.node_name] = ni
            self._imaginary.append(pod.node_name)
            self._order_dirty = True
        if pod_info is None or pod_info.pod is not pod:
            pod_info = PodInfo.of(pod)
        ni.add_pod(pod_info)
        if self.pod_group_state is not None:
            self.pod_group_state.record_bound(pod)
        for v in pod.volumes:
            if v.pvc_name:
                key = f"{pod.namespace}/{v.pvc_name}"
                self.pvc_refs[key] = self.pvc_refs.get(key, 0) + 1
        aff = pod.affinity
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
            self.affinity_pod_refs += 1
        self._dirty.add(pod.node_name)

    def _remove_pod_from_node(self, pod: Pod) -> None:
        if self.pod_group_state is not None:
            self.pod_group_state.remove(pod)
        # Symmetric with _add_pod_to_node's unconditional increment: the
        # refcount must drop even when the pod's node has already left the
        # cache (a leak would misclassify future users as 'shared pvc' and
        # silently strip their device eligibility).
        for v in pod.volumes:
            if v.pvc_name:
                key = f"{pod.namespace}/{v.pvc_name}"
                n = self.pvc_refs.get(key, 0) - 1
                if n <= 0:
                    self.pvc_refs.pop(key, None)
                else:
                    self.pvc_refs[key] = n
        aff = pod.affinity
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
            self.affinity_pod_refs = max(0, self.affinity_pod_refs - 1)
        ni = self.nodes.get(pod.node_name)
        if ni is not None:
            ni.remove_pod(pod)
            self._dirty.add(pod.node_name)

    # -- snapshot ----------------------------------------------------------

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """UpdateSnapshot (cache.go:206): re-clone only dirty NodeInfos, and
        patch them into the snapshot's lists IN PLACE — the reference's
        generation walk touches O(changed) nodes per cycle, and the daemonset
        workload (15k nodes, one dirty node per bind) holds this to the same
        bound. Full list rebuilds happen only on structural changes or when
        an affinity/image-relevant membership changed."""
        order_refreshed = self._order_dirty
        if self._order_dirty:
            self.node_order = self.node_tree.list() + list(self._imaginary)
            self._order_dirty = False
        structural = order_refreshed or self._removed_since_snapshot or (
            len(snapshot.node_info_list) != len(self.node_order)
        )
        affinity_dirty = structural
        replaced = []
        for name in self._dirty:
            ni = self.nodes.get(name)
            if ni is None:
                continue
            clone = ni.snapshot_clone()
            old = snapshot.node_info_map.get(name)
            if old is None or bool(old.pods_with_affinity) != bool(clone.pods_with_affinity) \
                    or bool(old.pods_with_required_anti_affinity) != bool(clone.pods_with_required_anti_affinity) \
                    or old.image_states.keys() != clone.image_states.keys():
                affinity_dirty = True
            elif name in getattr(snapshot, "_list_members", ()):
                # The re-cloned node sits in an affinity sublist: the list
                # entry must point at the fresh clone.
                affinity_dirty = True
            snapshot.node_info_map[name] = clone
            replaced.append((name, clone))
        if structural:
            snapshot.node_info_map = {
                name: snapshot.node_info_map.get(name) or self.nodes[name].snapshot_clone()
                for name in self.node_order
            }
            # Imaginary nodes (pods observed before their node) stay in the
            # map for accounting but are excluded from the schedulable list,
            # as the reference excludes nil-node entries from nodeInfoList.
            snapshot.node_info_list = [
                snapshot.node_info_map[n] for n in self.node_order
                if n in snapshot.node_info_map and snapshot.node_info_map[n].node is not None
            ]
            snapshot.rebuild_lists()
        else:
            index = getattr(snapshot, "_index", None)
            if index is None:
                snapshot.rebuild_lists()
                index = snapshot._index
            for name, clone in replaced:
                idx = index.get(name)
                if idx is not None and clone.node is not None:
                    snapshot.node_info_list[idx] = clone
                elif clone.node is not None:
                    affinity_dirty = True  # newly visible node: full rebuild
            if affinity_dirty:
                snapshot.rebuild_lists()
        snapshot.generation = next_generation()
        self._dirty.clear()
        self._removed_since_snapshot = False
        return snapshot

    def dirty_nodes(self) -> Set[str]:
        """Names of nodes changed since the last snapshot (device mirror feed)."""
        return set(self._dirty)
