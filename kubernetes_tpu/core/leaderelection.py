"""Leader election: active/passive HA for the scheduler.

Re-expresses client-go tools/leaderelection/leaderelection.go (573 LoC) over
a lease store: candidates acquire/renew a Lease record; the holder runs, the
others watch and take over when the lease expires (kube-scheduler wiring at
cmd/kube-scheduler/app/server.go:310-342).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease (the modern resourcelock)."""

    name: str
    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration: float = 15.0
    transitions: int = 0


class LeaseStore:
    """The apiserver-side lease objects (shared by all candidates)."""

    def __init__(self):
        self.leases: Dict[str, Lease] = {}

    def get_or_create(self, name: str, duration: float) -> Lease:
        if name not in self.leases:
            self.leases[name] = Lease(name=name, lease_duration=duration)
        return self.leases[name]


class LeaderElector:
    """leaderelection.go LeaderElector: tryAcquireOrRenew loop semantics,
    driven by explicit tick() calls (no background goroutine)."""

    def __init__(
        self,
        store: LeaseStore,
        identity: str,
        lease_name: str = "kube-scheduler",
        lease_duration: float = 15.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        now: Callable[[], float] = time.monotonic,
    ):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.now = now
        self._leading = False

    def is_leader(self) -> bool:
        return self._leading

    def tick(self) -> bool:
        """One tryAcquireOrRenew: returns True iff leading after the call."""
        lease = self.store.get_or_create(self.lease_name, self.lease_duration)
        now = self.now()
        expired = lease.renew_time + lease.lease_duration <= now
        if lease.holder == self.identity:
            lease.renew_time = now  # renew
            if not self._leading:
                self._leading = True
                if self.on_started_leading:
                    self.on_started_leading()
            return True
        if not lease.holder or expired:
            # acquire (the observed holder failed to renew)
            lease.holder = self.identity
            lease.acquire_time = lease.renew_time = now
            lease.transitions += 1
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
            return True
        if self._leading:
            # we lost the lease (another identity holds it)
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
        return False

    def release(self) -> None:
        """Voluntary step-down (ReleaseOnCancel)."""
        lease = self.store.leases.get(self.lease_name)
        if lease is not None and lease.holder == self.identity:
            lease.holder = ""
            lease.renew_time = 0.0
        if self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
