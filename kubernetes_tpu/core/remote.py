"""Process-boundary watch seam: the clientset behind a thread transport.

Re-expresses the part of the reference's architecture the in-process
FakeClientset collapses away: the scheduler talks to an apiserver over a
NETWORK — every write pays a round trip on whichever thread issued it, and
watch events arrive asynchronously on the reflector's thread
(client-go tools/cache/reflector.go:470 ListAndWatch,
shared_informer.go:841 processLoop; integration substrate
test/integration/framework/test_server.go:78).

`RemoteClientset` wraps a FakeClientset (the "apiserver" store):

- WRITES (create/update/delete/bind/patch) are serialized onto an
  apiserver thread and block the CALLER for the configured RTT — exactly
  client-go's synchronous REST semantics. The async API dispatcher's
  thread mode absorbs this latency off the scheduling loop (the binding
  cycle and preemption victim deletion keep scheduling while calls drain),
  which is the machinery's whole purpose and was previously never
  exercised against real latency.
- EVENTS fan out from the apiserver thread — the scheduler's handlers see
  cross-thread delivery and park them in the off-thread inbox
  (core/scheduler.py _threaded, the DeltaFIFO seam), replayed on the
  scheduling loop like a reflector feed.
- READS (the lister dicts: pods/nodes/pvs/...) go straight to the store,
  modeling the informer's local cache (client-go listers read local
  indexed state, not the wire).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

from .clientset import FakeClientset

_WRITE_METHODS = (
    "create_node", "update_node", "delete_node",
    "create_namespace", "create_pod_group", "create_composite_pod_group",
    "create_pv", "create_pvc", "create_storage_class", "create_csi_node",
    "create_resource_slice", "create_resource_claim", "create_device_class",
    "bind_volume",
    "create_pod", "update_pod", "delete_pod", "remove_pod_finalizers",
    "bind", "patch_pod_status",
)

_READ_ATTRS = (
    "pods", "nodes", "namespaces", "pod_groups", "composite_pod_groups",
    "pvs", "pvcs", "storage_classes", "csi_nodes",
    "resource_slices", "resource_claims", "device_classes", "bindings",
)


class RemoteClientset:
    """FakeClientset proxy behind an apiserver thread with a configurable
    round-trip time. Drop-in for the scheduler and the perf harness."""

    def __init__(self, store: FakeClientset | None = None, rtt: float = 0.001):
        self._store = store or FakeClientset()
        self.rtt = rtt
        self._requests: "queue.Queue" = queue.Queue()
        self._server = threading.Thread(
            target=self._serve, name="apiserver", daemon=True)
        self._server.start()
        self.calls = 0

        for name in _WRITE_METHODS:
            setattr(self, name, self._remote(getattr(self._store, name)))

    # -- apiserver thread --------------------------------------------------

    def _serve(self) -> None:
        while True:
            item = self._requests.get()
            if item is None:
                return
            fn, args, kwargs, fut = item
            # One-way latency before the store applies the write; the caller
            # blocks on the future for the full round trip.
            if self.rtt > 0:
                time.sleep(self.rtt / 2)
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 - surfaces at caller
                fut.set_exception(e)

    def _remote(self, fn):
        def call(*args, **kwargs):
            fut: Future = Future()
            self._requests.put((fn, args, kwargs, fut))
            self.calls += 1
            result = fut.result()
            if self.rtt > 0:
                time.sleep(self.rtt / 2)  # response leg
            return result
        return call

    def close(self) -> None:
        self._requests.put(None)

    # -- informer-cache reads + handler registration -----------------------

    def __getattr__(self, name):
        # Reads and handler registration delegate to the store (events then
        # FIRE on the apiserver thread — the cross-thread reflector feed).
        if name in _READ_ATTRS or name.startswith("on_") or name in (
                "attach_pv_controller", "bump_resource_claims_rv"):
            return getattr(self._store, name)
        raise AttributeError(name)

    @property
    def resource_claims_rv(self) -> int:
        return getattr(self._store, "resource_claims_rv", 0)

    @property
    def csi_nodes_rv(self) -> int:
        return getattr(self._store, "csi_nodes_rv", 0)
