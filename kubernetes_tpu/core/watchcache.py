"""Watch-cache read plane + shard-filtered watch streams.

Re-expresses the reference's read-serving cache layer between storage and
watchers (`staging/.../cacher/watch_cache.go`, SURVEY §1 L1) for the
apiserver in core/apiserver.py:

- :class:`WatchCache` — per-kind: an **rv-indexed ring** of recent events
  (the watch RESUME window) plus an **rv-stamped snapshot of wire-encoded
  objects** (the LIST/summary/`/metrics/resources` read plane). Mutation
  happens on the apiserver's existing `_broadcast` fanout path, under the
  broadcast lock and AFTER the WAL append; every read serves under the
  cache's OWN lock — list/summary/resource-metrics reads never touch the
  server's `_write_lock`, so the read plane stops contending with binds
  (the analyzer's `no-read-serving-under-write-lock` rule pins this).
  A resume rv older than the ring window answers None (the 410 Gone
  analogue) and the caller falls back to the existing full-relist path.
  Followers maintain their cache from applied replication frames (the
  same fanout helper), so any replica serves the identical read plane in
  the shared rv space — including across a promotion.

- **Shard-filtered watch streams** (`?watch=true&shard=i/n`): the server
  applies the shard/partition.py crc32 map per event, delivering the full
  pod wire only for pods the watching shard owns and for *wire-relevant*
  foreign pods — pods whose spec can affect OTHER pods' scheduling
  (pod affinity / anti-affinity, topology spread, host ports, PVC
  volumes, DRA claims: exactly what NodeInfo accounting needs, the same
  facts core/cache.py `pod_event_flags` classifies). Everything else
  ships as a **slim event**: the NodeInfo-accounting projection
  ``{uid, nodeName, phase, namespace, podGroup, priority, deletionTs,
  requests}`` (+ the event-level rv) — a shard's per-event decode cost
  scales with 1/N instead of with the whole cluster's churn. A foreign
  slim MODIFIED whose projection did not change is dropped entirely
  (`filtered_out`): the watcher's view of a slim pod depends only on the
  projection.

- **Paged LIST** (``?limit=&continue=``, docs/SCALE.md): ``list_page``
  serves bounded pages of the wire snapshot in sorted-key order under the
  cache's own lock; continuation tokens (``mint_continue``) anchor the
  whole list to the rv of its FIRST page, validated against the resume
  ring on every later page — when the ring no longer covers the anchor
  the page answers the 410 Gone analogue and the client restarts the
  list. A client that completes the list attaches its watch at the
  anchor rv, so the ring replays exactly the events that happened while
  it was paging (list-then-watch consistency); neither side ever
  materializes the full cluster in one response body.

  Label-selector safety: pod-affinity and topology-spread terms match
  OTHER pods by label, so the moment any live pod declares such a term
  (``selector_refs > 0``) slimming is disabled — new events go out full,
  and each filtered stream first *upgrades* every pod it previously
  slimmed with a full rv-less MODIFIED (the same cluster-level trigger
  PR 3's neutral signatures key on). A filtered RESUME against a
  selector-ful cluster falls back to a full re-list (the per-stream slim
  set died with the old connection and cannot be reconstructed).
"""

from __future__ import annotations

import base64
import bisect
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..api.resource import Resource
from ..api.types import Container, Pod
from ..shard.partition import shard_of_key
from . import wire


def wire_key(kind: str, obj: dict) -> str:
    if kind == "pods":
        return obj["uid"]
    if kind in ("podgroups", "replicasets", "deployments", "pdbs"):
        # Namespaced kinds; "ns/name" matches the store/clientset keying
        # so one key space spans the wire and both local maps.
        return f'{obj.get("namespace") or "default"}/{obj["name"]}'
    return obj["name"]


# ---------------------------------------------------------------------------
# Continuation tokens (paged LIST: `?limit=&continue=`)
# ---------------------------------------------------------------------------
#
# A token is opaque on the wire (urlsafe base64 JSON) and anchors the whole
# paged list to the rv at which its FIRST page was served: every later page
# re-validates that the resume ring still covers that anchor, so a client
# that finishes the list can attach a watch at `listRv` and replay exactly
# the events that happened WHILE it was paging (the list-then-watch
# consistency contract, docs/SCALE.md). A token whose anchor fell off the
# ring — or that names another server generation (epoch) — answers 410 Gone
# and the client restarts the list from scratch.


def mint_continue(anchor_rv: int, last_key: str, epoch: str) -> str:
    """Encode one continuation token: (list-anchor rv, last served key,
    server watch epoch)."""
    return base64.urlsafe_b64encode(wire.jdumps(
        {"rv": int(anchor_rv), "k": last_key, "e": epoch}).encode()).decode()


def parse_continue(token: str) -> Optional[dict]:
    """Decode a continuation token; None for garbage (the caller answers
    410 — a malformed token must restart the list, never crash a page)."""
    try:
        d = wire.jloads(base64.urlsafe_b64decode(token.encode()))
    except Exception:  # noqa: BLE001 - any malformed token is 410
        return None
    if (isinstance(d, dict)
            and isinstance(d.get("rv"), int)
            and not isinstance(d.get("rv"), bool)
            and isinstance(d.get("k"), str)
            and isinstance(d.get("e"), str)):
        return d
    return None


RESOURCE_METRICS_HEADER = (
    "# HELP kube_pod_resource_request Resources requested by "
    "workloads on the cluster, broken down by pod.",
    "# TYPE kube_pod_resource_request gauge",
)


def resource_request_lines(namespace: str, pod_name: str, node: str,
                           cpu_milli: int, memory: float,
                           scalars: Dict[str, float]) -> List[str]:
    """One pod's kube_pod_resource_request series — the ONE exposition
    format both `/metrics/resources` endpoints (the apiserver's
    watch-cache render and the scheduler server's informer render) share.
    Pending pods carry an EMPTY node label (reference convention)."""
    phase = "Running" if node else "Pending"
    lines: List[str] = []
    for res_name, val in (("cpu", cpu_milli / 1000.0),
                          ("memory", float(memory))):
        if val:
            lines.append(
                f'kube_pod_resource_request{{namespace="{namespace}",'
                f'pod="{pod_name}",node="{node}",'
                f'resource="{res_name}",phase="{phase}"}} {val}')
    for sname, amount in scalars.items():
        lines.append(
            f'kube_pod_resource_request{{namespace="{namespace}",'
            f'pod="{pod_name}",node="{node}",'
            f'resource="{sname}",phase="{phase}"}} {float(amount)}')
    return lines


def encode_stream_item(item, codec: str = wire.JSON,
                       enc: Optional[wire.SessionEncoder] = None) -> bytes:
    """Resolve one watch-queue item to wire bytes in the STREAM's
    negotiated codec: :class:`~.wire.WireItem` events encode once per
    codec (cached — every stream of that codec reuses the bytes);
    pre-encoded bytes pass through; lazy ("MODIFIED", wire_obj) upgrade
    markers (ShardFilter's selector-transition burst) encode HERE, on the
    stream's consumer thread, so the fanout path never pays an encode per
    slimmed pod under the broadcast lock.

    ``enc`` is the stream's :class:`~.wire.SessionEncoder` when it
    negotiated session frames: a WireItem's DELTA twin then encodes
    per-stream on the session table (the session offer IS the delta
    capability) while twin-less items keep returning the shared cached
    v1 frame — fan-out must never pay a per-stream re-encode for bytes
    the cache already holds. Lazy markers ride the session table (they
    are per-stream by construction) and pre-encoded bytes pass through
    as their self-contained v1 frames. Session state is touched HERE
    only — the consumer thread — never on the fanout path (the
    analyzer's delta-base-under-cache-lock rule)."""
    if isinstance(item, wire.WireItem):
        if enc is not None:
            return item.session_bytes(enc)
        return item.bytes(codec)
    if isinstance(item, bytes):
        return item
    typ, obj = item
    ev = {"type": typ, "object": obj}
    if enc is not None:
        return enc.encode(ev)
    return wire.encode(ev, codec)


def shard_key_from_wire(obj: dict) -> str:
    """shard/partition.py's stable key, computed from the WIRE dict so the
    server never decodes a pod to route it: the gang's identity when the
    pod belongs to one (gangs pin whole), else the pod uid."""
    group = obj.get("podGroup", "")
    if group:
        return f"pg:{obj.get('namespace', 'default')}/{group}"
    return obj["uid"]


def shard_of_wire(obj: dict, count: int) -> int:
    """The ONE crc32 map (shard/partition.py) applied server-side: a
    member's admission predicate and its stream's filter must agree
    exactly, or an owned pod could arrive slim."""
    return shard_of_key(shard_key_from_wire(obj), count)


def wire_plain(obj: dict) -> bool:
    """True when this pod cannot affect any OTHER pod's scheduling: no
    pod-(anti-)affinity terms, no topology spread, no host ports, no
    PVC-backed volumes, no DRA claims — the wire-dict mirror of
    core/cache.py pod_event_flags (node affinity / nodeSelector /
    tolerations only constrain where THIS pod goes, which is its owning
    shard's concern)."""
    aff = obj.get("affinity") or {}
    return not (
        aff.get("podAffinity") or aff.get("podAntiAffinity")
        or obj.get("topologySpread") or obj.get("hostPorts")
        or any(v.get("pvc") for v in obj.get("volumes", ()))
        or obj.get("resourceClaims"))


def wire_selector_source(obj: dict) -> bool:
    """True when this pod's spec contains label-selector terms that match
    OTHER pods (pod affinity / anti-affinity, topology spread): while any
    such pod is live, every pod's labels are wire-relevant and slimming
    is disabled (selectors may be empty = match-all, so even unlabeled
    pods can count toward a spread domain)."""
    aff = obj.get("affinity") or {}
    return bool(aff.get("podAffinity") or aff.get("podAntiAffinity")
                or obj.get("topologySpread"))


def slim_object(obj: dict) -> dict:
    """The NodeInfo-accounting projection of a foreign plain pod: enough
    to partition it (uid/namespace/podGroup), account it into a node's
    committed usage when it binds (requests), rank it as a preemption
    victim (priority), and skip it in adoption sweeps (deletionTs)."""
    return {
        "slim": True,
        "uid": obj["uid"],
        "name": obj.get("name", ""),
        "nodeName": obj.get("nodeName", ""),
        "phase": "Running" if obj.get("nodeName") else "Pending",
        "namespace": obj.get("namespace", "default"),
        "podGroup": obj.get("podGroup", ""),
        "priority": obj.get("priority", 0),
        "deletionTs": obj.get("deletionTs"),
        "requests": obj.get("requests",
                            {"cpu": 0, "memory": 0, "ephemeral": 0,
                             "scalar": {}}),
    }


def pod_from_slim(d: dict, old: Optional[Pod] = None) -> Pod:
    """Client-side decode of a slim event. With a cached copy, MERGE: the
    spec is immutable on this surface, so keep whatever detail the cache
    already holds (possibly the full wire from before a filter upgrade)
    and patch only the projection fields. Without one, build a minimal
    pod carrying exactly the accounting facts; ``wire_slim`` marks it so
    the shard plane knows to hydrate before SCHEDULING it (adoption)."""
    import copy as _copy
    if old is not None:
        pod = _copy.copy(old)
        pod.node_name = d.get("nodeName", "")
        pod.deletion_ts = d.get("deletionTs")
        return pod
    req = d.get("requests") or {}
    res = Resource(milli_cpu=int(req.get("cpu", 0)),
                   memory=int(req.get("memory", 0)),
                   ephemeral_storage=int(req.get("ephemeral", 0)),
                   scalar_resources=dict(req.get("scalar", {})))
    pod = Pod(name=d.get("name", ""), namespace=d.get("namespace", "default"),
              uid=d["uid"], node_name=d.get("nodeName", ""),
              priority=int(d.get("priority", 0)),
              containers=[Container(name="c0", requests=res)],
              phase=d.get("phase", "Pending"))
    pod.pod_group = d.get("podGroup", "")
    pod.deletion_ts = d.get("deletionTs")
    pod.wire_slim = True
    return pod


class WatchCache:
    """Per-kind read-serving cache: rv-indexed event ring + wire-object
    snapshot.

    Locking contract (enforced by the lock-discipline analyzer):
    - ``note_event``/``reset`` (mutation) are called on the apiserver's
      broadcast path with ``_lock`` held, after the WAL append — so ring
      order is commit order and a cached object is always durable;
    - the read methods (``list_wire``/``list_page``/``get_many``/
      ``read_summary``/``events_since``/``render_resources``) take only
      this cache's own lock and MUST NOT be called with the server's
      ``_write_lock`` held — the whole point is a read plane that never
      contends with the write plane."""

    def __init__(self, kind: str, capacity: int = 8192):
        self.kind = kind
        self._lock = threading.Lock()
        self._ring: "deque" = deque(maxlen=capacity)  # (rv, event, data)
        self._objects: Dict[str, dict] = {}
        # key -> rv of the last rv-STAMPED event that touched the key:
        # the base a DELTA record may be minted against. An rv-LESS
        # touch (STATUS nominations — never fanned to watchers) POPS the
        # entry: clients didn't see that change, so the next MODIFIED
        # must ship full or their patched copy would silently diverge.
        self._obj_rv: Dict[str, int] = {}
        self._bound = 0          # pods with a nodeName (summary read)
        self.selector_refs = 0   # live pods with affinity/spread terms
        self.rv = 0
        self.hits = 0       # list/summary/uids/resource reads served
        self.resumes = 0    # interval replays served from the ring
        self.too_old = 0    # resume rvs that fell off the window (410)
        self.deltas_minted = 0    # MODIFIEDs that shipped a DELTA twin
        self.deltas_applied = 0   # DELTA records materialized here
        # Sorted-key index for paged lists: pages iterate the snapshot in
        # sorted-key order so a continuation token names a stable
        # position. Built lazily by the FIRST page served, then maintained
        # incrementally (insort on insert, bisect-remove on delete) by the
        # broadcast path — a churning 50k-node fleet no longer pays a full
        # re-sort per page (docs/SCALE.md). `key_resorts` counts full
        # sorts actually paid (lazy build + post-reinstall rebuilds).
        self._skeys: Optional[List[str]] = None
        self.key_resorts = 0

    # -- mutation (broadcast path; caller holds the server's _lock) ---------

    def note_event(self, rv: Optional[int], typ: str,
                   obj: Optional[dict], data: Optional[bytes] = None,
                   event: Optional[dict] = None) -> None:
        """Apply one committed event: update the object snapshot, and (for
        rv-stamped events) append to the resume ring. rv=None is a STATUS
        upsert (nominations): snapshot only, never the ring — parity with
        its non-evented live fanout."""
        with self._lock:
            if obj is not None:
                self._apply_object(typ, obj)
                try:
                    key = wire_key(self.kind, obj)
                except KeyError:
                    key = None
                if key is not None:
                    # Delta-base bookkeeping: only an rv-stamped touch of
                    # a LIVE snapshot entry leaves a mintable base behind.
                    if (typ == "DELETED" or rv is None
                            or key not in self._objects):
                        self._obj_rv.pop(key, None)
                    else:
                        self._obj_rv[key] = rv
            if rv is not None:
                self.rv = max(self.rv, rv)
                self._ring.append((rv, event or {"type": typ, "object": obj},
                                   data))

    def _apply_object(self, typ: str, obj: dict) -> None:
        if typ == "BOUND":
            cur = self._objects.get(obj.get("uid", ""))
            if cur is not None:
                if not cur.get("nodeName") and obj.get("nodeName"):
                    self._bound += 1
                # copy-on-write: handed-out list_wire() dicts stay frozen
                self._objects[obj["uid"]] = dict(
                    cur, nodeName=obj.get("nodeName", ""))
            return
        key = wire_key(self.kind, obj)
        old = self._objects.get(key)
        if typ == "DELETED":
            if old is not None:
                self._objects.pop(key, None)
                self._skeys_remove(key)
                if self.kind == "pods":
                    if old.get("nodeName"):
                        self._bound -= 1
                    if wire_selector_source(old):
                        self.selector_refs -= 1
            return
        # ADDED / MODIFIED / STATUS: upsert
        self._objects[key] = obj
        if old is None and self._skeys is not None:
            bisect.insort(self._skeys, key)
        if self.kind == "pods":
            if bool(obj.get("nodeName")) != bool(
                    old.get("nodeName") if old else False):
                self._bound += 1 if obj.get("nodeName") else -1
            refs = wire_selector_source(obj)
            had = wire_selector_source(old) if old is not None else False
            if refs != had:
                self.selector_refs += 1 if refs else -1

    # -- delta plane (PR 18, docs/WIRE.md §DELTA) ---------------------------

    def mint_delta(self, event: dict) -> Optional[dict]:
        """Mint the DELTA twin of a MODIFIED event against the snapshot's
        CURRENT copy of the object — called on the apiserver's write path
        BEFORE the event installs (so "current" is the state every
        attached receiver already holds), with the prior wire object read
        under this cache's lock (the analyzer's delta-base-under-cache-lock
        rule pins that read). Returns ``{"type": "DELTA", "rv", "key",
        "baseRv", "patch"}`` — or None when there is no rv-stamped base
        (fresh object, post-STATUS, post-reinstall) or the diff isn't
        worth shipping; the caller then fans the full event as ever."""
        if event.get("type") != "MODIFIED":
            return None
        obj = event.get("object")
        rv = event.get("rv")
        if type(obj) is not dict or rv is None:
            return None
        try:
            key = wire_key(self.kind, obj)
        except KeyError:
            return None
        with self._lock:
            base_rv = self._obj_rv.get(key)
            base = self._objects.get(key) if base_rv is not None else None
        if base is None:
            return None
        # The diff runs outside the lock on purpose: `base` is frozen by
        # the copy-on-write contract, and diffing a large node object
        # under the cache lock would stall every read.
        patch = wire.diff_obj(base, obj)
        if patch is None:
            return None
        self.deltas_minted += 1
        return {"type": "DELTA", "rv": rv, "key": key,
                "baseRv": base_rv, "patch": patch}

    def materialize_delta(self, rec: dict) -> dict:
        """Rebuild the full object a DELTA record describes from this
        cache's own base (a follower applying a shipped frame, with the
        prior wire object read under the cache lock — the same
        delta-base-under-cache-lock contract as minting). Base-unknown is
        ACCEPTED when this cache has no rv on file for the key (fresh
        snapshot install: the installed state is exactly the minter's
        base by the replication ordering); a base at a DIFFERENT rv
        raises :class:`~.wire.DeltaBaseMismatch` — the caller resyncs a
        full copy, never applies onto the wrong base."""
        key = rec.get("key")
        with self._lock:
            base = self._objects.get(key)
            have = self._obj_rv.get(key)
        if base is None or (have is not None and have != rec.get("baseRv")):
            raise wire.DeltaBaseMismatch(
                f"{self.kind}/{key}: base rv {have!r} != "
                f"delta base rv {rec.get('baseRv')!r}")
        self.deltas_applied += 1
        return wire.apply_patch(base, rec.get("patch") or [])

    def _skeys_remove(self, key: str) -> None:
        """Drop one key from the incremental sorted index (caller holds
        this cache's lock and has already popped it from the snapshot)."""
        if self._skeys is None:
            return
        i = bisect.bisect_left(self._skeys, key)
        if i < len(self._skeys) and self._skeys[i] == key:
            del self._skeys[i]
        else:
            # Index out of step with the snapshot (should be impossible):
            # fail safe to a rebuild rather than serve a phantom page.
            self._skeys = None

    def reinstall(self, objects: List[dict], rv: int,
                  ring: Optional[List[Tuple[int, dict, bytes]]] = None) -> None:
        """Replace the whole cache (recovery seed / snapshot install).
        Caller holds the server's broadcast lock."""
        with self._lock:
            # Drop the sorted-key index FIRST so the apply loop below
            # doesn't insort into the dead generation's list; the next
            # page rebuilds it lazily from the installed snapshot.
            self._skeys = None
            self._objects = {}
            # No per-key rvs survive a reinstall: the next MODIFIED per
            # key ships full once (mint_delta finds no base), then deltas
            # resume — cheap, and never wrong.
            self._obj_rv = {}
            self._bound = 0
            self.selector_refs = 0
            for obj in objects:
                self._apply_object("ADDED", obj)
            self._ring.clear()
            for entry in ring or ():
                self._ring.append(entry)
            self.rv = max(rv, self._ring[-1][0] if self._ring else 0)

    # -- reads (own lock ONLY; never under the server's _write_lock) --------

    def list_wire(self) -> List[dict]:
        with self._lock:
            self.hits += 1
            return list(self._objects.values())

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            return self._objects.get(key)

    def get_many(self, keys) -> List[dict]:
        with self._lock:
            self.hits += 1
            return [self._objects[k] for k in keys if k in self._objects]

    def read_summary(self) -> dict:
        with self._lock:
            self.hits += 1
            return {"total": len(self._objects), "bound": self._bound,
                    "rv": self.rv}

    def _covers(self, rv: int) -> bool:
        """Does the resume ring still span everything after ``rv``?
        Caller holds this cache's lock."""
        return rv == self.rv or bool(
            self._ring and self._ring[0][0] <= rv + 1)

    def list_page(self, limit: int, last_key: str = "",
                  anchor_rv: Optional[int] = None):
        """One page of the wire snapshot in sorted-key order, under this
        cache's own lock (never the server's write lock — the analyzer's
        ``no-read-serving-under-write-lock`` rule covers this path).

        -> ``(objs, next_key, anchor, rv)``: up to ``limit`` wire dicts
        with key > ``last_key``; ``next_key`` is "" on the final page;
        ``anchor`` is the list-start rv (minted into the continuation
        token — the rv the client attaches its watch at); ``rv`` is the
        cache head now. Returns None when ``anchor_rv`` fell off the
        resume ring (the 410 Gone analogue: events the finished list
        would need to replay are gone, so the whole list restarts)."""
        limit = max(1, int(limit))
        with self._lock:
            if anchor_rv is not None and not self._covers(anchor_rv):
                self.too_old += 1
                return None
            if self._skeys is None:
                self._skeys = sorted(self._objects)
                self.key_resorts += 1
            keys = self._skeys
            i = bisect.bisect_right(keys, last_key) if last_key else 0
            page = keys[i:i + limit]
            objs = [self._objects[k] for k in page]
            self.hits += 1
            more = (i + limit) < len(keys)
            next_key = page[-1] if (page and more) else ""
            anchor = self.rv if anchor_rv is None else anchor_rv
            return objs, next_key, anchor, self.rv

    def events_since(self, since: int) -> Optional[List[tuple]]:
        """The (rv, event, data) tail with rv > ``since`` — the RESUME
        replay. None when the ring no longer covers ``since`` (too old:
        the 410 Gone analogue; the caller re-lists)."""
        with self._lock:
            if since == self.rv:
                self.resumes += 1
                return []
            if self._ring and self._ring[0][0] <= since + 1:
                self.resumes += 1
                return [e for e in self._ring if e[0] > since]
            self.too_old += 1
            return None

    def render_resources(self) -> str:
        """`/metrics/resources` (kube_pod_resource_request) straight from
        the wire snapshot — the read that used to re-encode the store."""
        with self._lock:
            self.hits += 1
            objs = list(self._objects.values())
        lines = list(RESOURCE_METRICS_HEADER)
        for obj in objs:
            req = obj.get("requests") or {}
            lines.extend(resource_request_lines(
                obj.get("namespace", "default"), obj.get("name", ""),
                obj.get("nodeName") or "",
                int(req.get("cpu", 0)), float(req.get("memory", 0)),
                req.get("scalar") or {}))
        return "\n".join(lines) + "\n"


class ShardFilter:
    """Per-watch-stream shard filter state (pods kind only).

    ``route`` decides, per committed event, what this stream receives:
    the full event, a slim projection, an upgrade burst, or nothing.
    Runs on the fanout path under the server's broadcast lock (so the
    decision sequence is commit order), but does no socket I/O — it only
    enqueues onto the stream's bounded-work queue."""

    def __init__(self, index: int, count: int):
        if count < 1 or not 0 <= index < count:
            # Never coerce: a filter naming no real slot would slim every
            # pod, including the stream owner's own.
            raise ValueError(f"invalid shard spec {index}/{count}")
        self.index = index
        self.count = count
        # uid -> last slim projection delivered (suppression + upgrades)
        self._slimmed: Dict[str, dict] = {}

    def spec(self) -> str:
        return f"{self.index}/{self.count}"

    def prime(self, cache: WatchCache) -> None:
        """RESUME attach: the previous connection's slim set died with it.
        Seed it with every live pod this filter WOULD slim, so a later
        selector transition still upgrades pods slimmed before the
        reconnect. (Reachable with selector_refs == 0, or on a `fresh`
        paged-relist attach — where selector_refs > 0 means the list
        slimmed before a transition and the caller immediately drains
        the seeded map through ``upgrade_all``.)"""
        with cache._lock:
            objs = list(cache._objects.values())
        for obj in objs:
            if wire_plain(obj) and shard_of_wire(obj, self.count) != self.index:
                self._slimmed[obj["uid"]] = slim_object(obj)

    def upgrade_all(self, cache: WatchCache) -> List[object]:
        """Drain the slim map into lazy full-MODIFIED upgrade markers
        (resolve with ``encode_stream_item`` on the consumer thread) —
        the attach-time variant of route()'s selector-transition burst.
        Used when a FRESH filtered attach finds selector_refs > 0: the
        paged list that just rebuilt the client slimmed while refs were
        still 0, and waiting for the next event to trigger the in-band
        burst would leave label-less slims in the cache indefinitely on
        a quiet cluster."""
        with cache._lock:
            fulls = [cache._objects[u] for u in self._slimmed
                     if u in cache._objects]
        self._slimmed = {}
        return [("MODIFIED", full) for full in fulls]

    def route(self, event: dict, data, cache: WatchCache,
              memo: Optional[dict] = None) -> Tuple[List[object], int, int]:
        """-> (events to deliver, slim_count, filtered_out_count). Each
        delivered item is a :class:`~.wire.WireItem` (or pre-encoded
        bytes) or a lazy ("MODIFIED", wire_obj) upgrade marker — resolve
        with ``encode_stream_item`` on the consumer side, outside the
        broadcast lock, in the stream's own negotiated codec.

        ``memo`` is a per-EVENT scratch dict the fanout loop shares
        across its filtered streams: the slim projection and its wire
        item are identical for every stream that slims the event, so
        only the first stream pays the dict build (the loop runs under
        the server's broadcast lock), and the encode itself happens once
        per CODEC on the consumer side. Projections are therefore
        treated as IMMUTABLE once built — updates replace the `_slimmed`
        entry, never mutate it."""
        typ = event.get("type")
        obj = event.get("object")
        if typ == "BOUND":
            # Already the slim-est wire there is; keep the filter's
            # projection current so a later MODIFIED diffs correctly
            # (copy-on-write: the projection may be memo-shared).
            uid = obj.get("uid", "") if obj else ""
            prev = self._slimmed.get(uid)
            if prev is not None:
                node = obj.get("nodeName", "")
                self._slimmed[uid] = dict(
                    prev, nodeName=node,
                    phase="Running" if node else "Pending")
            return [data], 0, 0
        if typ not in ("ADDED", "MODIFIED", "DELETED") or obj is None:
            return [data], 0, 0  # markers/control events pass through
        out: List[object] = []
        if cache.selector_refs > 0 and self._slimmed:
            # Selector transition: a live pod now matches others by label,
            # so labels (even absent ones) became wire-relevant. Upgrade
            # everything this stream slimmed with full rv-less MODIFIEDs
            # (rv-less: the client's resume watermark must not move).
            # The burst runs on the fanout path with the server's
            # broadcast lock held, so it must stay O(slimmed) dict work:
            # ONE cache-lock pass collects the wire dicts (stable —
            # note_event is copy-on-write) and the json encode is
            # deferred to the stream's consumer thread via lazy
            # ("MODIFIED", obj) markers — encoding thousands of full pod
            # wires under the broadcast lock would stall every bind.
            cur_uid = obj.get("uid")
            with cache._lock:
                fulls = [cache._objects[u] for u in self._slimmed
                         if u != cur_uid and u in cache._objects]
            out.extend(("MODIFIED", full) for full in fulls)
            self._slimmed.clear()
        if (cache.selector_refs > 0 or not wire_plain(obj)
                or shard_of_wire(obj, self.count) == self.index):
            out.append(data)
            self._slimmed.pop(obj.get("uid", ""), None)
            return out, 0, 0
        # Foreign plain pod in a selector-free cluster: slim it. The
        # projection + encoded line are event-level facts — memo-shared
        # across every filtered stream in this fanout.
        if memo is None:
            memo = {}
        slim = memo.get("slim")
        if slim is None:
            slim = memo["slim"] = slim_object(obj)
        if typ == "DELETED":
            self._slimmed.pop(obj["uid"], None)
        else:
            prev = self._slimmed.get(obj["uid"])
            if typ == "MODIFIED" and prev == slim:
                # Projection unchanged (e.g. a foreign gate lift): this
                # watcher's view of a slim pod depends only on the
                # projection — drop the event entirely.
                return out, 0, 1
            self._slimmed[obj["uid"]] = slim
        sdata = memo.get("data")
        if sdata is None:
            ev = {k: v for k, v in event.items() if k != "object"}
            ev["object"] = slim
            sdata = memo["data"] = wire.WireItem(ev)
        out.append(sdata)
        return out, 1, 0
