"""PriorityQueue: the three-stage pending-pod store.

Re-expresses pkg/scheduler/backend/queue/scheduling_queue.go (:186-269):
- activeQ   — heap ordered by the QueueSort plugin (priority, FIFO);
- backoffQ  — heap ordered by backoff expiry; exponential backoff
              1s→10s (backoff_queue.go:249 calculateBackoffDuration);
- unschedulableEntities — tried-and-failed pods, flushed to active/backoff
  after podMaxInUnschedulablePodsDuration (5 min) or on cluster events
  (MoveAllToActiveOrBackoffQueue :1817) filtered by per-plugin QueueingHints
  (isPodWorthRequeuing :582, approximated here by the event→plugin map).

Single-threaded by design: the TPU scheduling loop is one pipeline, so `pop`
returns None when empty instead of blocking on a condvar.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..api.types import Pod
from .framework import Status
from .node_info import PodInfo

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_MAX_IN_UNSCHEDULABLE_DURATION = 300.0

# Cluster events (framework/types.go ClusterEvent) — used to decide which
# unschedulable pods a delivered event can unblock.
EVENT_POD_ADD = "Pod/Add"
EVENT_POD_DELETE = "Pod/Delete"
EVENT_ASSIGNED_POD_ADD = "AssignedPod/Add"
EVENT_ASSIGNED_POD_DELETE = "AssignedPod/Delete"
EVENT_NODE_ADD = "Node/Add"
EVENT_NODE_UPDATE = "Node/Update"
EVENT_UNSCHEDULABLE_TIMEOUT = "UnschedulableTimeout"
EVENT_FORCE_ACTIVATE = "ForceActivate"


@dataclass
class QueuedPodInfo:
    """framework/types.go QueuedPodInfo."""

    pod_info: PodInfo
    timestamp: float = 0.0
    attempts: int = 0
    initial_attempt_timestamp: Optional[float] = None
    unschedulable_plugins: Set[str] = field(default_factory=set)
    pending_plugins: Set[str] = field(default_factory=set)
    gated: bool = False
    consecutive_backoff_exempt: bool = False

    @property
    def pod(self) -> Pod:
        return self.pod_info.pod


class _Heap:
    """Stable heap with O(log n) update/delete by key (backend/heap/heap.go)."""

    def __init__(self, less: Callable[[QueuedPodInfo, QueuedPodInfo], bool]):
        self._less = less
        self._entries: List[List] = []  # [sortkey_tiebreak, seq, qpi, valid]
        self._by_uid: Dict[str, List] = {}
        self._seq = itertools.count()

    class _Key:
        __slots__ = ("qpi", "less")

        def __init__(self, qpi, less):
            self.qpi = qpi
            self.less = less

        def __lt__(self, other):
            return self.less(self.qpi, other.qpi)

    def push(self, qpi: QueuedPodInfo) -> None:
        uid = qpi.pod.uid
        self.delete(uid)
        entry = [self._Key(qpi, self._less), next(self._seq), qpi, True]
        self._by_uid[uid] = entry
        heapq.heappush(self._entries, entry)

    def pop(self) -> Optional[QueuedPodInfo]:
        while self._entries:
            entry = heapq.heappop(self._entries)
            if entry[3]:
                del self._by_uid[entry[2].pod.uid]
                return entry[2]
        return None

    def peek(self) -> Optional[QueuedPodInfo]:
        while self._entries and not self._entries[0][3]:
            heapq.heappop(self._entries)
        return self._entries[0][2] if self._entries else None

    def delete(self, uid: str) -> Optional[QueuedPodInfo]:
        entry = self._by_uid.pop(uid, None)
        if entry is not None:
            entry[3] = False
            return entry[2]
        return None

    def get(self, uid: str) -> Optional[QueuedPodInfo]:
        entry = self._by_uid.get(uid)
        return entry[2] if entry else None

    def __contains__(self, uid: str) -> bool:
        return uid in self._by_uid

    def __len__(self) -> int:
        return len(self._by_uid)

    def items(self):
        return [e[2] for e in self._by_uid.values()]


class Nominator:
    """backend/queue/nominator.go — preemption-nominated pods per node."""

    def __init__(self):
        self._node_to_pods: Dict[str, List[PodInfo]] = {}
        self._pod_to_node: Dict[str, str] = {}

    def add_nominated_pod(self, pi: PodInfo, node_name: str) -> None:
        self.delete_nominated_pod(pi.pod)
        if not node_name:
            return
        self._node_to_pods.setdefault(node_name, []).append(pi)
        self._pod_to_node[pi.pod.uid] = node_name

    def delete_nominated_pod(self, pod: Pod) -> None:
        node = self._pod_to_node.pop(pod.uid, None)
        if node is not None:
            self._node_to_pods[node] = [
                p for p in self._node_to_pods.get(node, []) if p.pod.uid != pod.uid
            ]
            if not self._node_to_pods[node]:
                del self._node_to_pods[node]

    def nominated_pods_for_node(self, node_name: str) -> List[PodInfo]:
        return self._node_to_pods.get(node_name, [])

    def nominated_node_for_pod(self, pod: Pod) -> Optional[str]:
        return self._pod_to_node.get(pod.uid)

    def has_nominated_pods(self) -> bool:
        return bool(self._pod_to_node)


class PriorityQueue:
    def __init__(
        self,
        framework=None,
        initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        max_in_unschedulable: float = DEFAULT_MAX_IN_UNSCHEDULABLE_DURATION,
        now: Callable[[], float] = time.monotonic,
        pop_from_backoff_q: bool = True,
    ):
        self.framework = framework
        self.now = now
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.max_in_unschedulable = max_in_unschedulable
        self.pop_from_backoff_q = pop_from_backoff_q

        less = framework.less if framework is not None else (lambda a, b: a.timestamp < b.timestamp)
        self.active_q = _Heap(less)
        self.backoff_q = _Heap(self._backoff_less)
        self.unschedulable: Dict[str, QueuedPodInfo] = {}
        self.nominator = Nominator()
        self._in_flight: Dict[str, List[str]] = {}  # uid -> events seen while in flight
        self.moved_count = 0  # schedulingCycle analogue of moveRequestCycle

    # -- backoff (backoff_queue.go:249) ------------------------------------

    def backoff_duration(self, qpi: QueuedPodInfo) -> float:
        d = self.initial_backoff
        for _ in range(max(0, qpi.attempts - 1)):
            d *= 2
            if d >= self.max_backoff:
                return self.max_backoff
        return d

    def backoff_expiry(self, qpi: QueuedPodInfo) -> float:
        return qpi.timestamp + self.backoff_duration(qpi)

    def is_backing_off(self, qpi: QueuedPodInfo) -> bool:
        if qpi.attempts == 0:
            return False
        return self.backoff_expiry(qpi) > self.now()

    def _backoff_less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        return self.backoff_expiry(a) < self.backoff_expiry(b)

    # -- add / pop ---------------------------------------------------------

    def _new_qpi(self, pod: Pod) -> QueuedPodInfo:
        ts = self.now()
        return QueuedPodInfo(
            pod_info=PodInfo.of(pod), timestamp=ts, initial_attempt_timestamp=None
        )

    def add(self, pod: Pod) -> None:
        """Add (scheduling_queue.go:858) — new pending pod."""
        qpi = self._new_qpi(pod)
        if self.framework is not None:
            st = self.framework.run_pre_enqueue_plugins(pod)
            if not st.is_success():
                qpi.gated = True
                qpi.unschedulable_plugins.add(st.plugin)
                self.unschedulable[pod.uid] = qpi
                return
        self.active_q.push(qpi)

    def update(self, old: Optional[Pod], new: Pod) -> None:
        uid = new.uid
        if uid in self.unschedulable:
            qpi = self.unschedulable.pop(uid)
            qpi.pod_info = PodInfo.of(new)
            if qpi.gated:
                # re-run PreEnqueue — gates may have been removed
                if self.framework is not None:
                    st = self.framework.run_pre_enqueue_plugins(new)
                    if st.is_success():
                        qpi.gated = False
                        qpi.timestamp = self.now()
                        self.active_q.push(qpi)
                        return
                self.unschedulable[uid] = qpi
                return
            # spec update may make it schedulable — move to active/backoff
            self._move_to_active_or_backoff(qpi)
            return
        existing = self.active_q.get(uid)
        if existing is not None:
            # delete + re-push: in-place mutation would corrupt heap order
            # when the update changes priority.
            self.active_q.delete(uid)
            existing.pod_info = PodInfo.of(new)
            self.active_q.push(existing)
            return
        existing = self.backoff_q.get(uid)
        if existing is not None:
            self.backoff_q.delete(uid)
            existing.pod_info = PodInfo.of(new)
            self.backoff_q.push(existing)
            return
        if uid not in self._in_flight:
            self.add(new)

    def delete(self, pod: Pod) -> None:
        self.active_q.delete(pod.uid)
        self.backoff_q.delete(pod.uid)
        self.unschedulable.pop(pod.uid, None)
        self.nominator.delete_nominated_pod(pod)

    def pop(self) -> Optional[QueuedPodInfo]:
        """Pop (scheduling_queue.go:1320 → active_queue.go:315) with the
        pop-from-backoffQ feature: when activeQ is empty, pop the pod whose
        backoff already expired — or, when the gate is on, the earliest-expiry
        backoff pod (SchedulerPopFromBackoffQ)."""
        self.flush_backoff_completed()
        qpi = self.active_q.pop()
        if qpi is None and self.pop_from_backoff_q:
            qpi = self.backoff_q.pop()
        if qpi is None:
            return None
        qpi.attempts += 1
        if qpi.initial_attempt_timestamp is None:
            qpi.initial_attempt_timestamp = self.now()
        self._in_flight[qpi.pod.uid] = []
        return qpi

    def done(self, uid: str) -> None:
        """Done (scheduling_queue.go:1326) — scheduling attempt finished."""
        self._in_flight.pop(uid, None)

    def __len__(self) -> int:
        return len(self.active_q) + len(self.backoff_q) + len(self.unschedulable)

    def pending_counts(self) -> Tuple[int, int, int]:
        return len(self.active_q), len(self.backoff_q), len(self.unschedulable)

    # -- requeue on failure -------------------------------------------------

    def add_unschedulable_if_not_present(self, qpi: QueuedPodInfo, pod_scheduling_cycle: int = 0) -> None:
        """AddUnschedulablePodIfNotPresent (scheduling_queue.go:1058): if a
        relevant event arrived while the pod was in flight, skip the
        unschedulable pool and go straight to backoff/active."""
        uid = qpi.pod.uid
        events = self._in_flight.get(uid, [])
        qpi.timestamp = self.now()
        if events and self._events_relevant(qpi, events):
            self._move_to_active_or_backoff(qpi)
            return
        self.unschedulable[uid] = qpi

    def _events_relevant(self, qpi: QueuedPodInfo, events: List[str]) -> bool:
        # QueueingHint approximation: any cluster event can unblock any
        # unschedulable pod (reference default when a plugin registers no
        # hint fn is to requeue). Per-plugin hints refine this later.
        return True

    def _move_to_active_or_backoff(self, qpi: QueuedPodInfo) -> None:
        if qpi.gated:
            self.unschedulable[qpi.pod.uid] = qpi
            return
        if self.is_backing_off(qpi):
            self.backoff_q.push(qpi)
        else:
            self.active_q.push(qpi)

    def activate(self, pod: Pod) -> None:
        """Activate (scheduling_queue.go:955) — force to activeQ."""
        uid = pod.uid
        qpi = self.unschedulable.pop(uid, None) or self.backoff_q.delete(uid)
        if qpi is not None and not qpi.gated:
            qpi.timestamp = self.now()
            self.active_q.push(qpi)

    def move_all_to_active_or_backoff(self, event: str) -> None:
        """MoveAllToActiveOrBackoffQueue (scheduling_queue.go:1817)."""
        self.moved_count += 1
        for uid in list(self.unschedulable.keys()):
            qpi = self.unschedulable[uid]
            if qpi.gated and event != EVENT_FORCE_ACTIVATE:
                continue
            del self.unschedulable[uid]
            self._move_to_active_or_backoff(qpi)
        for events in self._in_flight.values():
            events.append(event)

    def flush_backoff_completed(self) -> None:
        """backoffQ flush loop (scheduling_queue.go Run :503)."""
        while True:
            qpi = self.backoff_q.peek()
            if qpi is None or self.backoff_expiry(qpi) > self.now():
                return
            self.backoff_q.pop()
            self.active_q.push(qpi)

    def flush_unschedulable_left_over(self) -> None:
        """flushUnschedulablePodsLeftover — pods stuck > 5 min."""
        now = self.now()
        for uid in list(self.unschedulable.keys()):
            qpi = self.unschedulable[uid]
            if qpi.gated:
                continue
            if now - qpi.timestamp > self.max_in_unschedulable:
                del self.unschedulable[uid]
                self._move_to_active_or_backoff(qpi)
