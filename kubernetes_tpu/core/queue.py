"""PriorityQueue: the three-stage pending-pod store.

Re-expresses pkg/scheduler/backend/queue/scheduling_queue.go (:186-269):
- activeQ   — heap ordered by the QueueSort plugin (priority, FIFO);
- backoffQ  — heap ordered by backoff expiry; exponential backoff
              1s→10s (backoff_queue.go:249 calculateBackoffDuration);
- unschedulableEntities — tried-and-failed pods, flushed to active/backoff
  after podMaxInUnschedulablePodsDuration (5 min) or on cluster events
  (MoveAllToActiveOrBackoffQueue :1817) filtered by per-plugin QueueingHints
  (isPodWorthRequeuing :582, approximated here by the event→plugin map).

Single-threaded by design: the TPU scheduling loop is one pipeline, so `pop`
returns None when empty instead of blocking on a condvar.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..api.types import Pod
from .framework import Status
from .node_info import PodInfo

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_MAX_IN_UNSCHEDULABLE_DURATION = 300.0

# Cluster events (framework/types.go ClusterEvent) — used to decide which
# unschedulable pods a delivered event can unblock.
EVENT_POD_ADD = "Pod/Add"
EVENT_POD_DELETE = "Pod/Delete"
EVENT_ASSIGNED_POD_ADD = "AssignedPod/Add"
EVENT_ASSIGNED_POD_DELETE = "AssignedPod/Delete"
EVENT_NODE_ADD = "Node/Add"
EVENT_NODE_UPDATE = "Node/Update"
EVENT_UNSCHEDULABLE_TIMEOUT = "UnschedulableTimeout"
EVENT_FORCE_ACTIVATE = "ForceActivate"
EVENT_STORAGE_ADD = "Storage/Add"  # PV/PVC/StorageClass/CSINode changes

# QueueingHints (scheduling_queue.go:582 isPodWorthRequeuing; per-plugin
# EnqueueExtensions): which cluster events can unblock a pod rejected by a
# given plugin. Plugins absent from the map requeue on any event (the
# reference's default when no hint fn is registered).
QUEUEING_HINTS: Dict[str, Set[str]] = {
    "NodeResourcesFit": {EVENT_NODE_ADD, EVENT_NODE_UPDATE,
                         EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    "NodeAffinity": {EVENT_NODE_ADD, EVENT_NODE_UPDATE},
    "NodeName": {EVENT_NODE_ADD, EVENT_NODE_UPDATE},
    "NodeUnschedulable": {EVENT_NODE_ADD, EVENT_NODE_UPDATE},
    "TaintToleration": {EVENT_NODE_ADD, EVENT_NODE_UPDATE},
    "NodePorts": {EVENT_NODE_ADD, EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    "PodTopologySpread": {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_ASSIGNED_POD_ADD,
                          EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    "InterPodAffinity": {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_ASSIGNED_POD_ADD,
                         EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    "DefaultPreemption": {EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    "VolumeBinding": {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_STORAGE_ADD},
    "VolumeZone": {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_STORAGE_ADD},
    "NodeVolumeLimits": {EVENT_NODE_ADD, EVENT_ASSIGNED_POD_DELETE,
                         EVENT_POD_DELETE, EVENT_STORAGE_ADD},
    "VolumeRestrictions": {EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    "DynamicResources": {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_STORAGE_ADD,
                         EVENT_ASSIGNED_POD_DELETE, EVENT_POD_DELETE},
    # Composite trees with topology-constrained leaves are rejected by
    # design on the composite path (schedule_composite_group) — no cluster
    # event changes that, so nothing requeues them before the
    # unschedulable-timeout flush.
    "TopologyPlacementGenerator": set(),
}


@dataclass
class QueuedPodInfo:
    """framework/types.go QueuedPodInfo."""

    pod_info: PodInfo
    timestamp: float = 0.0
    attempts: int = 0
    initial_attempt_timestamp: Optional[float] = None
    # Queue-admission instant (never reset by requeues of THIS info object,
    # unlike `timestamp`): the start of the queue.wait span and of the
    # scheduler_e2e_scheduling_duration_seconds observation.
    enqueued_at: Optional[float] = None
    unschedulable_plugins: Set[str] = field(default_factory=set)
    pending_plugins: Set[str] = field(default_factory=set)
    gated: bool = False
    consecutive_backoff_exempt: bool = False

    @property
    def pod(self) -> Pod:
        return self.pod_info.pod

    @property
    def uid(self) -> str:
        return self.pod_info.pod.uid


@dataclass
class QueuedPodGroupInfo:
    """The gang-scheduling queue entity (scheduling_queue.go
    QueuedPodGroupInfo; invariants :196-206): a PodGroup whose member pods
    have all arrived pops as ONE unit and is scheduled all-or-nothing."""

    group: "object"  # api.types.PodGroup
    members: List[QueuedPodInfo] = field(default_factory=list)
    timestamp: float = 0.0
    attempts: int = 0
    initial_attempt_timestamp: Optional[float] = None
    unschedulable_plugins: Set[str] = field(default_factory=set)
    pending_plugins: Set[str] = field(default_factory=set)
    gated: bool = False
    consecutive_backoff_exempt: bool = False

    @property
    def pod(self) -> Pod:
        """Queue-ordering shim: group entities sort by group priority and
        arrival (the reference's workload-aware lessFn)."""
        return self.members[0].pod if self.members else Pod(name="(empty-group)")

    @property
    def pods(self) -> List[Pod]:
        return [m.pod for m in self.members]

    @property
    def uid(self) -> str:
        return f"pg:{self.group.namespace}/{self.group.name}"


@dataclass
class QueuedCompositeGroupInfo:
    """The queue entity for a whole CompositePodGroup TREE: the root
    composite plus every leaf PodGroup's buffered members. Pops as ONE unit
    and schedules all-or-nothing across levels
    (workload_forest.go buildQueuedPodGroupInfo + schedule_one_podgroup.go
    composite paths)."""

    cpg: "object"  # api.types.CompositePodGroup (the root)
    # [(PodGroup, [QueuedPodInfo, ...])] — one entry per leaf group
    groups: List[Tuple["object", List[QueuedPodInfo]]] = field(default_factory=list)
    timestamp: float = 0.0
    attempts: int = 0
    initial_attempt_timestamp: Optional[float] = None
    unschedulable_plugins: Set[str] = field(default_factory=set)
    pending_plugins: Set[str] = field(default_factory=set)
    gated: bool = False
    consecutive_backoff_exempt: bool = False

    @property
    def pod(self) -> Pod:
        for _g, members in self.groups:
            if members:
                return members[0].pod
        return Pod(name="(empty-composite)")

    @property
    def uid(self) -> str:
        return f"cpg:{self.cpg.namespace}/{self.cpg.name}"


class WorkloadForest:
    """Consistent queue-side view of the PodGroup/CompositePodGroup
    hierarchy (backend/queue/workload_forest.go): child→parent links are
    recorded even before the parent object is observed, so late parents
    retroactively own their children without a full rescan."""

    def __init__(self, composite_enabled: bool = True):
        self.composite_enabled = composite_enabled
        self.pod_groups: Dict[Tuple[str, str], object] = {}
        self.composites: Dict[Tuple[str, str], object] = {}
        # parent cpg key -> {("pg"|"cpg", child key)}
        self.children: Dict[Tuple[str, str], Set[Tuple[str, Tuple[str, str]]]] = {}

    def add_pod_group(self, group) -> None:
        key = (group.namespace, group.name)
        self.pod_groups[key] = group
        parent = getattr(group, "parent_name", "")
        if parent and self.composite_enabled:
            self.children.setdefault((group.namespace, parent), set()).add(
                ("pg", key))

    def add_composite(self, cpg) -> None:
        key = (cpg.namespace, cpg.name)
        self.composites[key] = cpg
        if cpg.parent_name:
            self.children.setdefault((cpg.namespace, cpg.parent_name), set()).add(
                ("cpg", key))

    def root_of_group(self, group):
        """Walk parent links to the outermost observed composite. Returns
        (kind, obj) — ("pg", group) when the group is its own root,
        ("cpg", cpg) for a composite root — or (None, None) while an
        ancestor in the chain is not yet observed (the tree must wait,
        getRootLookupInfoForPod)."""
        if not self.composite_enabled or not getattr(group, "parent_name", ""):
            return "pg", group
        ns = group.namespace
        name = group.parent_name
        cpg = None
        seen = set()
        while name:
            if (ns, name) in seen:
                return None, None  # cycle: never schedulable
            seen.add((ns, name))
            cpg = self.composites.get((ns, name))
            if cpg is None:
                return None, None  # parent not observed yet
            name = cpg.parent_name
        return "cpg", cpg

    def leaf_groups(self, cpg) -> Optional[List[object]]:
        """Every PodGroup in the subtree rooted at `cpg`, or None when a
        composite child has no observed object or a composite has no leaves
        (getLeafPodGroups)."""
        out: List[object] = []
        stack = [(cpg.namespace, cpg.name)]
        visited = set()
        while stack:
            key = stack.pop()
            if key in visited:
                continue
            visited.add(key)
            kids = self.children.get(key)
            if not kids:
                return None  # interior node with no observed children
            for kind, ckey in sorted(kids):
                if kind == "pg":
                    g = self.pod_groups.get(ckey)
                    if g is None:
                        return None
                    out.append(g)
                else:
                    if ckey not in self.composites:
                        return None
                    stack.append(ckey)
        return out or None


class _Heap:
    """Stable heap with O(log n) update/delete by key (backend/heap/heap.go).

    When the queue-sort comparison exposes a `sort_key(qpi)` (PrioritySort
    does), entries carry a plain tuple compared at C speed; otherwise a
    comparison shim routes through the less function."""

    def __init__(self, less: Callable[[QueuedPodInfo, QueuedPodInfo], bool],
                 sort_key: Optional[Callable[[QueuedPodInfo], tuple]] = None):
        self._less = less
        self._sort_key = sort_key
        self._entries: List[List] = []  # [sortkey_tiebreak, seq, qpi, valid]
        self._by_uid: Dict[str, List] = {}
        self._seq = itertools.count()

    class _Key:
        __slots__ = ("qpi", "less")

        def __init__(self, qpi, less):
            self.qpi = qpi
            self.less = less

        def __lt__(self, other):
            return self.less(self.qpi, other.qpi)

    def push(self, qpi) -> None:
        uid = qpi.uid
        self.delete(uid)
        key = (self._sort_key(qpi) if self._sort_key is not None
               else self._Key(qpi, self._less))
        entry = [key, next(self._seq), qpi, True]
        self._by_uid[uid] = entry
        heapq.heappush(self._entries, entry)

    def pop(self) -> Optional[QueuedPodInfo]:
        while self._entries:
            entry = heapq.heappop(self._entries)
            if entry[3]:
                del self._by_uid[entry[2].uid]
                return entry[2]
        return None

    def peek(self) -> Optional[QueuedPodInfo]:
        while self._entries and not self._entries[0][3]:
            heapq.heappop(self._entries)
        return self._entries[0][2] if self._entries else None

    def delete(self, uid: str) -> Optional[QueuedPodInfo]:
        entry = self._by_uid.pop(uid, None)
        if entry is not None:
            entry[3] = False
            return entry[2]
        return None

    def get(self, uid: str) -> Optional[QueuedPodInfo]:
        entry = self._by_uid.get(uid)
        return entry[2] if entry else None

    def __contains__(self, uid: str) -> bool:
        return uid in self._by_uid

    def __len__(self) -> int:
        return len(self._by_uid)

    def items(self):
        return [e[2] for e in self._by_uid.values()]


class _FairTenantHeap:
    """activeQ with per-tenant weighted fair dequeue (the scheduler-side
    half of the overload plane, docs/RESILIENCE.md § overload & fairness;
    the queue-admission analogue of the apiserver's priority-and-fairness
    dequeue in core/flowcontrol.py).

    One :class:`_Heap` per namespace preserves the queue-sort order WITHIN
    a tenant; `pop` picks the tenant by smooth weighted round-robin, so a
    namespace flooding the queue gets its weight's share of scheduling
    cycles and nothing more — the other tenants' heads keep popping at
    their own proportional cadence instead of starving behind the flood's
    (equal-priority) backlog. Same interface as _Heap, so the queue's
    flows (update/delete/activate/requeue) need no special cases."""

    def __init__(self, less: Callable[[QueuedPodInfo, QueuedPodInfo], bool],
                 sort_key: Optional[Callable[[QueuedPodInfo], tuple]] = None,
                 weights: Optional[Dict[str, float]] = None,
                 now: Callable[[], float] = time.monotonic):
        self._less = less
        self._sort_key = sort_key
        self.weights: Dict[str, float] = dict(weights or {})
        self.now = now
        self._heaps: Dict[str, _Heap] = {}
        self._ns_of: Dict[str, str] = {}   # entity uid -> namespace
        self._credit: Dict[str, float] = {}
        self.pops: Dict[str, int] = {}     # per-tenant service counts
        self.last_served: Dict[str, float] = {}

    def _ns(self, qpi) -> str:
        return qpi.pod.namespace or "default"

    def _weight(self, ns: str) -> float:
        return max(1e-6, float(self.weights.get(ns, 1.0)))

    def push(self, qpi) -> None:
        uid = qpi.uid
        self.delete(uid)
        ns = self._ns(qpi)
        heap = self._heaps.get(ns)
        if heap is None:
            heap = self._heaps[ns] = _Heap(self._less, self._sort_key)
            self._credit.setdefault(ns, 0.0)
        heap.push(qpi)
        self._ns_of[uid] = ns

    def pop(self) -> Optional[QueuedPodInfo]:
        nonempty = [ns for ns, h in self._heaps.items() if len(h)]
        if not nonempty:
            return None
        # Smooth WRR: every tenant with queued work earns its weight, the
        # richest tenant is served and charged the round's total — long-run
        # service converges to the weight proportions (fairness unit suite).
        total = 0.0
        for ns in nonempty:
            w = self._weight(ns)
            self._credit[ns] = self._credit.get(ns, 0.0) + w
            total += w
        best = max(nonempty, key=lambda ns: (self._credit[ns], ns))
        self._credit[best] -= total
        qpi = self._heaps[best].pop()
        if qpi is not None:
            self._ns_of.pop(qpi.uid, None)
            self.pops[best] = self.pops.get(best, 0) + 1
            self.last_served[best] = self.now()
        self._gc(best)
        return qpi

    def _gc(self, ns: str) -> None:
        heap = self._heaps.get(ns)
        if heap is not None and not len(heap):
            del self._heaps[ns]
            self._credit.pop(ns, None)

    def peek(self) -> Optional[QueuedPodInfo]:
        for heap in self._heaps.values():
            got = heap.peek()
            if got is not None:
                return got
        return None

    def delete(self, uid: str) -> Optional[QueuedPodInfo]:
        ns = self._ns_of.pop(uid, None)
        if ns is None:
            return None
        got = self._heaps[ns].delete(uid)
        self._gc(ns)
        return got

    def get(self, uid: str) -> Optional[QueuedPodInfo]:
        ns = self._ns_of.get(uid)
        return self._heaps[ns].get(uid) if ns is not None else None

    def __contains__(self, uid: str) -> bool:
        return uid in self._ns_of

    def __len__(self) -> int:
        return len(self._ns_of)

    def items(self):
        return [q for h in self._heaps.values() for q in h.items()]


class Nominator:
    """backend/queue/nominator.go — preemption-nominated pods per node."""

    def __init__(self):
        self._node_to_pods: Dict[str, List[PodInfo]] = {}
        self._pod_to_node: Dict[str, str] = {}
        # Bumped on every add/delete: device sessions and failure memos key
        # on the nomination SET (a changed set changes two-pass filter
        # outcomes), not just on whether any nomination exists.
        self.version = 0

    def add_nominated_pod(self, pi: PodInfo, node_name: str) -> None:
        self.delete_nominated_pod(pi.pod)
        if not node_name:
            return
        self._node_to_pods.setdefault(node_name, []).append(pi)
        self._pod_to_node[pi.pod.uid] = node_name
        self.version += 1

    def delete_nominated_pod(self, pod: Pod) -> None:
        node = self._pod_to_node.pop(pod.uid, None)
        if node is not None:
            self._node_to_pods[node] = [
                p for p in self._node_to_pods.get(node, []) if p.pod.uid != pod.uid
            ]
            if not self._node_to_pods[node]:
                del self._node_to_pods[node]
            self.version += 1

    def all_nominated_pod_infos(self) -> List[PodInfo]:
        return [pi for pis in self._node_to_pods.values() for pi in pis]

    def nominated_pods_for_node(self, node_name: str) -> List[PodInfo]:
        return self._node_to_pods.get(node_name, [])

    def nominated_node_for_pod(self, pod: Pod) -> Optional[str]:
        return self._pod_to_node.get(pod.uid)

    def has_nominated_pods(self) -> bool:
        return bool(self._pod_to_node)


class _UnschedulableMap(dict):
    """unschedulableEntities map with a non-gated uid index, so cluster-event
    requeues (move_all_to_active_or_backoff) never iterate gated pods. The
    index is keyed on insert-time `gated` — every flow that ungates a pod
    pops it from the map first (queue.update / activate), so the value can't
    go stale while stored."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.non_gated = set(u for u, q in self.items() if not q.gated)

    def __setitem__(self, uid, qpi):
        super().__setitem__(uid, qpi)
        if qpi.gated:
            self.non_gated.discard(uid)
        else:
            self.non_gated.add(uid)

    def __delitem__(self, uid):
        super().__delitem__(uid)
        self.non_gated.discard(uid)

    def pop(self, uid, *default):
        self.non_gated.discard(uid)
        return super().pop(uid, *default)

    def clear(self):
        super().clear()
        self.non_gated.clear()


class PriorityQueue:
    def __init__(
        self,
        framework=None,
        initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        max_in_unschedulable: float = DEFAULT_MAX_IN_UNSCHEDULABLE_DURATION,
        now: Callable[[], float] = time.monotonic,
        pop_from_backoff_q: bool = True,
        gang_enabled: bool = True,
        queueing_hints_enabled: bool = True,
        composite_enabled: bool = False,
        fair_tenant_dequeue: bool = False,
        tenant_weights: Optional[Dict[str, float]] = None,
    ):
        self.framework = framework
        self.metrics = None  # optional SchedulerMetrics (hint latency series)
        self.queueing_hints_enabled = queueing_hints_enabled
        self.composite_enabled = composite_enabled
        self.forest = WorkloadForest(composite_enabled)
        self.now = now
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.max_in_unschedulable = max_in_unschedulable
        self.pop_from_backoff_q = pop_from_backoff_q
        self.gang_enabled = gang_enabled

        less = framework.less if framework is not None else (lambda a, b: a.timestamp < b.timestamp)
        sort_key = framework.queue_sort_key if framework is not None else None
        # Per-tenant fairness (docs/RESILIENCE.md § overload & fairness):
        # with fair_tenant_dequeue, the activeQ becomes per-namespace heaps
        # popped by smooth weighted round-robin — one flooding tenant gets
        # its weight's share of cycles, not the whole scheduler. Off by
        # default: single-tenant workloads keep the global queue-sort order.
        self.fair_tenant_dequeue = fair_tenant_dequeue
        if fair_tenant_dequeue:
            self.active_q = _FairTenantHeap(less, sort_key=sort_key,
                                            weights=tenant_weights, now=now)
        else:
            self.active_q = _Heap(less, sort_key=sort_key)
        self.backoff_q = _Heap(self._backoff_less)
        self.unschedulable: "_UnschedulableMap" = _UnschedulableMap()
        self.nominator = Nominator()
        # In-flight entities + the SHARED event log (scheduling_queue.go
        # inFlightEvents): each entity records the log position at pop time;
        # events append ONCE to the log instead of once per in-flight entity
        # (device sessions keep ~2 batches of pods in flight, and every own
        # bind fires an AssignedPodAdd — per-entity lists would be O(batch²)
        # per batch). The log clears whenever nothing is in flight.
        self._in_flight: Dict[str, int] = {}  # uid -> event-log index at pop
        self._event_log: List = []
        self.moved_count = 0  # schedulingCycle analogue of moveRequestCycle
        # Gang scheduling (workload_forest.go / pod_group_member_pods.go):
        # member pods buffer until their group has min_count arrivals, then
        # the whole group enters the queue as one entity.
        self.pod_groups: Dict[Tuple[str, str], object] = {}
        self._group_members: Dict[Tuple[str, str], List[QueuedPodInfo]] = {}

    # -- backoff (backoff_queue.go:249) ------------------------------------

    def backoff_duration(self, qpi: QueuedPodInfo) -> float:
        d = self.initial_backoff
        for _ in range(max(0, qpi.attempts - 1)):
            d *= 2
            if d >= self.max_backoff:
                return self.max_backoff
        return d

    def backoff_expiry(self, qpi: QueuedPodInfo) -> float:
        return qpi.timestamp + self.backoff_duration(qpi)

    def is_backing_off(self, qpi: QueuedPodInfo) -> bool:
        if qpi.attempts == 0:
            return False
        return self.backoff_expiry(qpi) > self.now()

    def _backoff_less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        return self.backoff_expiry(a) < self.backoff_expiry(b)

    # -- add / pop ---------------------------------------------------------

    def _new_qpi(self, pod: Pod) -> QueuedPodInfo:
        ts = self.now()
        # A pod that already rode the queue (conflict requeue via
        # on_async_bind_error, generic async-error re-add) keeps its
        # ORIGINAL admission instant: pop() stamps it on the pod, so
        # scheduler_e2e_scheduling_duration_seconds covers the whole
        # conflict-retry span instead of restarting at the requeue.
        return QueuedPodInfo(
            pod_info=PodInfo.of(pod), timestamp=ts,
            initial_attempt_timestamp=None,
            enqueued_at=pod.__dict__.get("_enqueued_at", ts),
        )

    def add(self, pod: Pod) -> None:
        """Add (scheduling_queue.go:858) — new pending pod."""
        qpi = self._new_qpi(pod)
        if self.framework is not None and self.framework.pre_enqueue_plugins:
            st = self.framework.run_pre_enqueue_plugins(pod)
            if not st.is_success():
                qpi.gated = True
                qpi.unschedulable_plugins.add(st.plugin)
                self.unschedulable[pod.uid] = qpi
                return
        if pod.pod_group and self.gang_enabled:
            self._add_group_member(qpi)
            return
        self.active_q.push(qpi)

    # -- gang scheduling ---------------------------------------------------

    def register_pod_group(self, group) -> None:
        """PodGroup/CompositePodGroup informer event: record in the forest
        and activate whatever ROOT became complete
        (scheduling_queue.go pod-group invariants + workload_forest.go)."""
        from ..api.types import CompositePodGroup
        if isinstance(group, CompositePodGroup):
            self.forest.add_composite(group)
            if self.composite_enabled:
                # A late parent can complete any subtree: re-check once per
                # DISTINCT root (not per buffered group — each composite
                # check walks the whole tree).
                roots = {}
                for key in list(self._group_members):
                    g = self.pod_groups.get(key)
                    if g is None:
                        continue
                    kind, root = self.forest.root_of_group(g)
                    if kind == "cpg":
                        roots[(root.namespace, root.name)] = root
                for root in roots.values():
                    self._maybe_activate_composite(root)
            return
        key = (group.namespace, group.name)
        self.pod_groups[key] = group
        self.forest.add_pod_group(group)
        self._maybe_activate_group(key)

    def _add_group_member(self, qpi: QueuedPodInfo) -> None:
        pod = qpi.pod
        key = (pod.namespace, pod.pod_group)
        members = self._group_members.setdefault(key, [])
        members.append(qpi)
        existing = self._group_entity(key)
        if existing is not None:
            existing.members = list(members)  # late joiner widens the gang
            return
        self._maybe_activate_group(key)

    def _group_entity(self, key) -> Optional[QueuedPodGroupInfo]:
        group = self.pod_groups.get(key)
        if group is None:
            return None
        uid = f"pg:{key[0]}/{key[1]}"
        ent = self.active_q.get(uid) or self.backoff_q.get(uid) or self.unschedulable.get(uid)
        return ent

    def _maybe_activate_group(self, key) -> None:
        """PodGroupPodsCount gate at ROOT granularity: a flat group becomes
        schedulable once min_count members arrived; a group inside a
        composite tree only when EVERY leaf group of the whole tree is
        complete (podgrouppodscount/ + workload_forest.go)."""
        group = self.pod_groups.get(key)
        if group is None:
            return
        kind, root = self.forest.root_of_group(group)
        if kind == "cpg":
            self._maybe_activate_composite(root)
            return
        if kind is None:
            return  # an ancestor is unobserved: the tree waits
        members = self._group_members.get(key, [])
        if len(members) < max(1, group.min_count):
            return
        if self._group_entity(key) is not None or f"pg:{key[0]}/{key[1]}" in self._in_flight:
            return
        ent = QueuedPodGroupInfo(
            group=group, members=list(members), timestamp=self.now())
        self.active_q.push(ent)
        if self.metrics is not None:
            self.metrics.queue_incoming_entities.inc("active", "GroupComplete")

    def _maybe_activate_composite(self, cpg) -> None:
        leaves = self.forest.leaf_groups(cpg)
        if leaves is None:
            return
        groups = []
        for g in leaves:
            members = self._group_members.get((g.namespace, g.name), [])
            if len(members) < max(1, g.min_count):
                return  # an incomplete leaf holds the whole tree back
            groups.append((g, list(members)))
        uid = f"cpg:{cpg.namespace}/{cpg.name}"
        ent = (self.active_q.get(uid) or self.backoff_q.get(uid)
               or self.unschedulable.get(uid))
        if ent is not None:
            ent.groups = groups  # late joiner widens the queued tree
            return
        if uid in self._in_flight:
            return
        self.active_q.push(QueuedCompositeGroupInfo(
            cpg=cpg, groups=groups, timestamp=self.now()))
        if self.metrics is not None:
            self.metrics.queue_incoming_entities.inc("active", "TreeComplete")

    def remove_group_member(self, pod: Pod) -> None:
        key = (pod.namespace, pod.pod_group)
        members = self._group_members.get(key)
        if not members:
            return
        self._group_members[key] = [m for m in members if m.pod.uid != pod.uid]
        ent = self._group_entity(key)
        if ent is not None:
            ent.members = [m for m in ent.members if m.pod.uid != pod.uid]
            group = self.pod_groups.get(key)
            if group is not None and len(ent.members) < max(1, group.min_count):
                self.active_q.delete(ent.uid)
                self.backoff_q.delete(ent.uid)
                self.unschedulable.pop(ent.uid, None)
        # A queued COMPOSITE entity holding this pod must not schedule it:
        # filter the member IN PLACE (preserving the entity's backoff and
        # attempt state, like the flat-gang path above); the entity only
        # drops when a leaf falls below min_count — buffers then re-activate
        # it when enough members return.
        group = self.pod_groups.get(key)  # may be None when only buffered
        if group is not None and self.composite_enabled:
            kind, root = self.forest.root_of_group(group)
            if kind == "cpg":
                uid = f"cpg:{root.namespace}/{root.name}"
                ent = (self.active_q.get(uid) or self.backoff_q.get(uid)
                       or self.unschedulable.get(uid))
                if ent is not None:
                    ent.groups = [
                        (g, [m for m in ms if m.pod.uid != pod.uid])
                        for g, ms in ent.groups]
                    if any(len(ms) < max(1, g.min_count)
                           for g, ms in ent.groups):
                        self.active_q.delete(uid)
                        self.backoff_q.delete(uid)
                        self.unschedulable.pop(uid, None)

    def clear_group_members(self, group_key: Tuple[str, str], uids) -> None:
        """Members successfully scheduled leave the buffer."""
        members = self._group_members.get(group_key)
        if members:
            self._group_members[group_key] = [
                m for m in members if m.pod.uid not in uids]

    def update(self, old: Optional[Pod], new: Pod) -> None:
        uid = new.uid
        if new.pod_group and self.gang_enabled:
            # A buffered gang member updates in place — falling through to
            # add() would append a duplicate member entry.
            key = (new.namespace, new.pod_group)
            for m in self._group_members.get(key, ()):
                if m.pod.uid == uid:
                    m.pod_info = PodInfo.of(new)
                    return
        if uid in self.unschedulable:
            qpi = self.unschedulable.pop(uid)
            qpi.pod_info = PodInfo.of(new)
            if qpi.gated:
                # re-run PreEnqueue — gates may have been removed
                if self.framework is not None:
                    st = self.framework.run_pre_enqueue_plugins(new)
                    if st.is_success():
                        qpi.gated = False
                        qpi.timestamp = self.now()
                        if new.pod_group and self.gang_enabled:
                            self._add_group_member(qpi)  # rejoin the gang
                        else:
                            self.active_q.push(qpi)
                        return
                self.unschedulable[uid] = qpi
                return
            # spec update may make it schedulable — move to active/backoff
            self._move_to_active_or_backoff(qpi)
            return
        existing = self.active_q.get(uid)
        if existing is not None:
            # delete + re-push: in-place mutation would corrupt heap order
            # when the update changes priority.
            self.active_q.delete(uid)
            existing.pod_info = PodInfo.of(new)
            self.active_q.push(existing)
            return
        existing = self.backoff_q.get(uid)
        if existing is not None:
            self.backoff_q.delete(uid)
            existing.pod_info = PodInfo.of(new)
            self.backoff_q.push(existing)
            return
        if uid not in self._in_flight:
            self.add(new)

    def delete(self, pod: Pod) -> None:
        if pod.pod_group:
            self.remove_group_member(pod)
        self.active_q.delete(pod.uid)
        self.backoff_q.delete(pod.uid)
        self.unschedulable.pop(pod.uid, None)
        self.nominator.delete_nominated_pod(pod)

    def pop(self) -> Optional[QueuedPodInfo]:
        """Pop (scheduling_queue.go:1320 → active_queue.go:315) with the
        pop-from-backoffQ feature: when activeQ is empty, pop the pod whose
        backoff already expired — or, when the gate is on, the earliest-expiry
        backoff pod (SchedulerPopFromBackoffQ)."""
        self.flush_backoff_completed()
        qpi = self.active_q.pop()
        if qpi is None and self.pop_from_backoff_q:
            qpi = self.backoff_q.pop()
        if qpi is None:
            return None
        qpi.attempts += 1
        if qpi.initial_attempt_timestamp is None:
            qpi.initial_attempt_timestamp = self.now()
        eq = getattr(qpi, "enqueued_at", None)
        pi = getattr(qpi, "pod_info", None)
        if eq is not None and pi is not None:
            # Stamp the admission instant on the pod itself: requeue paths
            # that only have the Pod (async bind conflicts build a fresh
            # QueuedPodInfo) recover it in _new_qpi, keeping the e2e
            # histogram honest across conflict retries.
            pi.pod.__dict__["_enqueued_at"] = eq
        self._in_flight[qpi.uid] = len(self._event_log)
        return qpi

    def done(self, uid: str) -> None:
        """Done (scheduling_queue.go:1326) — scheduling attempt finished."""
        self._in_flight.pop(uid, None)
        if not self._in_flight:
            self._event_log.clear()
        elif len(self._event_log) > 4096:
            # Pipelined scheduling can keep SOMETHING in flight for the whole
            # run; trim the prefix no live entity can reference and rebase
            # (the reference trims inFlightEvents as pods complete). Amortized
            # by the length gate so the min() scan is rare.
            mn = min(self._in_flight.values())
            if mn > 0:
                del self._event_log[:mn]
                for k in self._in_flight:
                    self._in_flight[k] -= mn

    def __len__(self) -> int:
        return len(self.active_q) + len(self.backoff_q) + len(self.unschedulable)

    def pending_counts(self) -> Tuple[int, int, int]:
        return len(self.active_q), len(self.backoff_q), len(self.unschedulable)

    def starvation_by_namespace(self) -> Dict[str, float]:
        """Starvation accounting (`scheduler_queue_starvation_seconds`
        {namespace}): per tenant, how long its LONGEST-waiting runnable
        entity (active + backoff — not the unschedulable pool, which waits
        on cluster events by design) has been queued since admission.
        Computed from live queue contents at scrape time — O(pending),
        zero bookkeeping on the hot add/pop paths."""
        now = self.now()
        out: Dict[str, float] = {}
        for qpi in list(self.active_q.items()) + list(self.backoff_q.items()):
            ns = qpi.pod.namespace or "default"
            start = getattr(qpi, "enqueued_at", None)
            if start is None:
                start = qpi.timestamp
            wait = max(0.0, now - start)
            if wait > out.get(ns, 0.0):
                out[ns] = wait
        return out

    # -- requeue on failure -------------------------------------------------

    def add_unschedulable_if_not_present(self, qpi, pod_scheduling_cycle: int = 0) -> None:
        """AddUnschedulablePodIfNotPresent (scheduling_queue.go:1058): if a
        relevant event arrived while the entity was in flight, skip the
        unschedulable pool and go straight to backoff/active. Entities key by
        their queue uid (pod uid, or "pg:ns/name" for gangs)."""
        uid = qpi.uid
        start = self._in_flight.get(uid)
        events = self._event_log[start:] if start is not None else []
        qpi.timestamp = self.now()
        if events and self._events_relevant(qpi, events):
            self._move_to_active_or_backoff(qpi)
            return
        self.unschedulable[uid] = qpi

    def _events_relevant(self, qpi, events: List) -> bool:
        """isPodWorthRequeuing (scheduling_queue.go:582): does any of the
        events plausibly resolve one of the plugins that rejected this
        entity? Per-plugin QueueingHintFn callbacks (EventsToRegister →
        ClusterEventWithHint; framework/types.go:217) are evaluated over the
        event's (old, new) objects when the plugin registered them; plugins
        without callbacks fall back to the static event map; unknown
        rejection causes requeue on anything. Events arrive as plain strings
        or (event, old, new) tuples."""
        plugins = qpi.unschedulable_plugins
        if not plugins:
            return True
        hint_map = (getattr(self.framework, "queueing_hint_map", None)
                    if self.queueing_hints_enabled else None)
        for ev in events:
            event, old, new = ev if isinstance(ev, tuple) else (ev, None, None)
            if event in (EVENT_UNSCHEDULABLE_TIMEOUT, EVENT_FORCE_ACTIVATE):
                return True
            for p in plugins:
                registered = hint_map.get(p) if hint_map is not None else None
                if registered is None:
                    hints = QUEUEING_HINTS.get(p)
                    if hints is None or event in hints:
                        return True
                    continue
                fns = registered.get(event)
                if fns is None:
                    # Plugin registered its events and this isn't one of
                    # them: the event cannot help this rejection.
                    continue
                pod = qpi.pod
                for fn in fns:
                    if fn is None:
                        return True  # no hint fn: always Queue
                    _m = self.metrics
                    _t0 = time.perf_counter() if _m is not None else 0.0
                    try:
                        queue_it = bool(fn(pod, old, new))
                    except Exception:  # noqa: BLE001 - hint errors → Queue
                        queue_it = True  # (the reference logs and queues)
                    if _m is not None:
                        _m.queueing_hint_execution_duration.observe(
                            time.perf_counter() - _t0, p, event)
                    if queue_it:
                        return True
        return False

    def _move_to_active_or_backoff(self, qpi) -> None:
        if qpi.gated:
            self.unschedulable[qpi.uid] = qpi
            return
        if self.is_backing_off(qpi):
            self.backoff_q.push(qpi)
        else:
            self.active_q.push(qpi)

    def requeue_conflict(self, qpi) -> None:
        """Optimistic-binding conflict (409 from the binding subresource):
        the entity goes straight to the backoffQ — never the unschedulable
        pool, because no cluster event is needed to make it schedulable
        again; it only needs to wait out the backoff so the winning commit
        arrives through the watch feed (Omega's conflict-then-retry)."""
        qpi.timestamp = self.now()
        if qpi.gated:
            self.unschedulable[qpi.uid] = qpi
            return
        if qpi.pod.pod_group and self.gang_enabled:
            # A gang member's conflict re-enters through the group buffer,
            # exactly like add(): a bare backoffQ singleton would later pop
            # and schedule SOLO, outside the gang's all-or-nothing. (Reached
            # from failover-overlap 409s — the partitioner pins gangs whole,
            # so only transient dual ownership can race a gang's binds.)
            self._add_group_member(qpi)
            return
        self.backoff_q.push(qpi)
        if self.metrics is not None:
            self.metrics.queue_incoming_entities.inc("backoff", "BindConflict")

    def has_entity(self, uid: str) -> bool:
        """Is this pod/entity anywhere in the queue's custody (active,
        backoff, unschedulable, in flight, or buffered as a gang member)?
        Shard adoption sweeps use this to avoid double-admitting."""
        if (uid in self.active_q or uid in self.backoff_q
                or uid in self.unschedulable or uid in self._in_flight):
            return True
        return any(m.pod.uid == uid for ms in self._group_members.values()
                   for m in ms)

    def activate(self, pod: Pod) -> None:
        """Activate (scheduling_queue.go:955) — force to activeQ."""
        uid = pod.uid
        qpi = self.unschedulable.pop(uid, None) or self.backoff_q.delete(uid)
        if qpi is not None and not qpi.gated:
            qpi.timestamp = self.now()
            self.active_q.push(qpi)

    def move_all_to_active_or_backoff(self, event: str, old=None, new=None) -> None:
        """MoveAllToActiveOrBackoffQueue (scheduling_queue.go:1817), with
        per-plugin QueueingHint filtering over the event's (old, new)
        objects. Gated pods are skipped via the map's non-gated index —
        cluster events must cost O(requeue-able pods), not O(gated pods)
        (the SchedulingWhileGated perf contract: 10k parked gated pods while
        deletes fire during the window)."""
        self.moved_count += 1
        ev = (event, old, new)
        uids = (list(self.unschedulable.keys()) if event == EVENT_FORCE_ACTIVATE
                else list(self.unschedulable.non_gated))
        for uid in uids:
            qpi = self.unschedulable.get(uid)
            if qpi is None:
                continue
            if qpi.gated and event != EVENT_FORCE_ACTIVATE:
                continue
            if not self._events_relevant(qpi, [ev]):
                continue
            del self.unschedulable[uid]
            self._move_to_active_or_backoff(qpi)
        if self._in_flight:
            self._event_log.append(ev)

    def flush_backoff_completed(self) -> None:
        """backoffQ flush loop (scheduling_queue.go Run :503)."""
        while True:
            qpi = self.backoff_q.peek()
            if qpi is None or self.backoff_expiry(qpi) > self.now():
                return
            self.backoff_q.pop()
            self.active_q.push(qpi)

    def flush_unschedulable_left_over(self) -> None:
        """flushUnschedulablePodsLeftover — pods stuck > 5 min."""
        now = self.now()
        for uid in list(self.unschedulable.keys()):
            qpi = self.unschedulable[uid]
            if qpi.gated:
                continue
            if now - qpi.timestamp > self.max_in_unschedulable:
                del self.unschedulable[uid]
                self._move_to_active_or_backoff(qpi)
