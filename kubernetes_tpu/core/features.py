"""Feature gates (component-base/featuregate/feature_gate.go mechanism;
gate inventory from pkg/features/kube_features.go — the scheduler-relevant
subset of the reference's 189 gates, plus this framework's own).

Usage:
    gates = FeatureGates()                  # defaults
    gates = FeatureGates({"TPUBatchScheduling": False})
    if gates.enabled(GENERIC_WORKLOAD): ...
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Tuple

ALPHA = "Alpha"
BETA = "Beta"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str = BETA
    # Gates that must be enabled for this one to take effect
    # (kube_features.go dependency graph :2534-2740).
    depends_on: Tuple[str, ...] = ()


# Reference gates the scheduler consumes (kube_features.go anchors).
GENERIC_WORKLOAD = "GenericWorkload"                      # :441 gang scheduling
COMPOSITE_POD_GROUP = "CompositePodGroup"                 # :158
OPPORTUNISTIC_BATCHING = "OpportunisticBatching"          # :818 KEP-5598
SCHEDULER_ASYNC_API_CALLS = "SchedulerAsyncAPICalls"
SCHEDULER_ASYNC_PREEMPTION = "SchedulerAsyncPreemption"      # :1048
SCHEDULER_POP_FROM_BACKOFF_Q = "SchedulerPopFromBackoffQ"  # :1062
NOMINATED_NODE_NAME_FOR_EXPECTATION = "NominatedNodeNameForExpectation"  # :812
SCHEDULER_QUEUEING_HINTS = "SchedulerQueueingHints"
NODE_DECLARED_FEATURES = "NodeDeclaredFeatures"
DRA_EXTENDED_RESOURCE = "DRAExtendedResource"             # :240 fork
DRA_NODE_ALLOCATABLE_RESOURCES = "DRANodeAllocatableResources"  # :261 fork
DYNAMIC_RESOURCE_ALLOCATION = "DynamicResourceAllocation"
MATCH_LABEL_KEYS_IN_POD_TOPOLOGY_SPREAD = "MatchLabelKeysInPodTopologySpread"
# TPU-native framework gates.
TPU_BATCH_SCHEDULING = "TPUBatchScheduling"               # the device hot path
TPU_STATE_RESIDENCY = "TPUStateResidency"                 # carry adoption

DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    GENERIC_WORKLOAD: FeatureSpec(True, BETA),
    COMPOSITE_POD_GROUP: FeatureSpec(False, ALPHA, depends_on=(GENERIC_WORKLOAD,)),
    OPPORTUNISTIC_BATCHING: FeatureSpec(True, BETA),
    SCHEDULER_ASYNC_API_CALLS: FeatureSpec(True, BETA),
    SCHEDULER_ASYNC_PREEMPTION: FeatureSpec(True, BETA),
    SCHEDULER_POP_FROM_BACKOFF_Q: FeatureSpec(True, BETA),
    NOMINATED_NODE_NAME_FOR_EXPECTATION: FeatureSpec(True, BETA),
    SCHEDULER_QUEUEING_HINTS: FeatureSpec(True, BETA),
    NODE_DECLARED_FEATURES: FeatureSpec(True, BETA),
    DYNAMIC_RESOURCE_ALLOCATION: FeatureSpec(False, ALPHA),
    DRA_EXTENDED_RESOURCE: FeatureSpec(
        False, ALPHA, depends_on=(DYNAMIC_RESOURCE_ALLOCATION,)),
    DRA_NODE_ALLOCATABLE_RESOURCES: FeatureSpec(
        False, ALPHA, depends_on=(DYNAMIC_RESOURCE_ALLOCATION,)),
    MATCH_LABEL_KEYS_IN_POD_TOPOLOGY_SPREAD: FeatureSpec(True, GA),
    TPU_BATCH_SCHEDULING: FeatureSpec(True, BETA),
    TPU_STATE_RESIDENCY: FeatureSpec(True, BETA, depends_on=(TPU_BATCH_SCHEDULING,)),
}


class FeatureGates:
    def __init__(self, overrides: Optional[Mapping[str, bool]] = None,
                 known: Optional[Mapping[str, FeatureSpec]] = None):
        self._known = dict(known or DEFAULT_FEATURES)
        self._enabled: Dict[str, bool] = {
            name: spec.default for name, spec in self._known.items()}
        for name, val in (overrides or {}).items():
            if name not in self._known:
                raise ValueError(f"unknown feature gate {name!r}")
            self._enabled[name] = bool(val)
        self._validate_dependencies()

    def _validate_dependencies(self) -> None:
        for name, spec in self._known.items():
            if self._enabled[name]:
                for dep in spec.depends_on:
                    if not self._enabled.get(dep, False):
                        raise ValueError(
                            f"feature {name} requires {dep} to be enabled")

    def enabled(self, name: str) -> bool:
        if name not in self._known:
            raise ValueError(f"unknown feature gate {name!r}")
        return self._enabled[name]

    def known(self) -> Dict[str, FeatureSpec]:
        return dict(self._known)
