from .cache import Cache, Snapshot
from .clientset import FakeClientset
from .framework import (
    MAX_NODE_SCORE,
    CycleState,
    Diagnosis,
    FitError,
    Framework,
    NodeScore,
    PreFilterResult,
    Status,
)
from .node_info import NodeInfo, PodInfo
from .queue import Nominator, PriorityQueue, QueuedPodInfo
from .registry import build_framework, default_profiles, fit_only_profiles
from .scheduler import Handle, ScheduleResult, Scheduler

__all__ = [
    "Cache",
    "Snapshot",
    "FakeClientset",
    "MAX_NODE_SCORE",
    "CycleState",
    "Diagnosis",
    "FitError",
    "Framework",
    "NodeScore",
    "PreFilterResult",
    "Status",
    "NodeInfo",
    "PodInfo",
    "Nominator",
    "PriorityQueue",
    "QueuedPodInfo",
    "build_framework",
    "default_profiles",
    "fit_only_profiles",
    "Handle",
    "ScheduleResult",
    "Scheduler",
]
