"""The scheduler: run loop + the one-pod scheduling cycle.

Re-expresses pkg/scheduler/scheduler.go (Scheduler struct :69, Run :537) and
pkg/scheduler/schedule_one.go — the hot path:

    schedule_one → scheduling_cycle:
        Cache.update_snapshot                      (cache.go:206)
        find_nodes_that_fit_pod                    (schedule_one.go:630)
            run_pre_filter_plugins
            nominated-node fast path               (:722)
            find_nodes_that_pass_filters           (:779, adaptive sampling
                                                    :866 + rotation :816)
        prioritize_nodes                           (:945)
        select_host                                (:?  reservoir over max)
        assume + reserve + permit                  (:315, :211)
    binding cycle (sync here; async overlap is the device pipeline's job)
        pre-bind → bind → post-bind                (:466,:478,:1100)
    failure → handle_scheduling_failure → requeue  (:1152)

TPU-first deviation: when the active profile has a `batch_evaluator` (the
device backend), schedule_one pulls a *row-block* of same-signature pods and
dispatches one kernel call that runs the whole greedy sequential assignment as
a lax.scan on device (kubernetes_tpu/ops.kernel) — the generalization of
OpportunisticBatching (runtime/batch.go) the survey calls for (§2.4).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod
from .apiserver import EVICTED_ANNOTATION
from .cache import (
    EV_NAMESPACE,
    EV_NODE_UPDATE,
    EV_OTHER,
    EV_POD_ADD,
    EV_POD_REMOVE,
    EV_POD_UPDATE,
    EV_QUEUE,
    EV_STRUCTURAL,
    Cache,
    EventJournal,
    Snapshot,
    pod_event_flags,
)
from .clientset import FakeClientset
from .framework import (
    MAX_NODE_SCORE,
    CycleState,
    Diagnosis,
    FitError,
    Framework,
    NodeScore,
    Status,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    WAIT,
)
from .node_info import NodeInfo
from .queue import (
    EVENT_ASSIGNED_POD_ADD,
    EVENT_ASSIGNED_POD_DELETE,
    EVENT_NODE_ADD,
    EVENT_NODE_UPDATE,
    PriorityQueue,
    QueuedCompositeGroupInfo,
    QueuedPodGroupInfo,
    QueuedPodInfo,
)

MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int = 0) -> int:
    """schedule_one.go:866 — adaptive 5-50% sampling, floor 100. The single
    source of truth shared by the host loop and the device kernel's sampling
    emulation (ops/features.py)."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND:
        return num_all_nodes
    if percentage > 0:
        pct = percentage
    else:
        pct = 50 - num_all_nodes // 125
        if pct < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            pct = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    return max(num_all_nodes * pct // 100, MIN_FEASIBLE_NODES_TO_FIND)


@dataclass
class ScheduleResult:
    suggested_host: str = ""
    evaluated_nodes: int = 0
    feasible_nodes: int = 0
    waiting: bool = False  # a Permit plugin returned WAIT


class Handle:
    """framework.Handle (interface.go:844) subset plugins consume."""

    def __init__(self, scheduler: "Scheduler"):
        self._scheduler = scheduler
        self.clientset = scheduler.clientset

    def snapshot(self) -> Snapshot:
        return self._scheduler.snapshot

    def namespace_labels(self, name: str):
        return self._scheduler.cache.namespace_labels(name)

    @property
    def nominator(self):
        return self._scheduler.queue.nominator

    @property
    def metrics(self):
        return self._scheduler.metrics

    @property
    def gates(self):
        return self._scheduler.gates

    @property
    def api_dispatcher(self):
        return self._scheduler.api_dispatcher

    @property
    def extenders(self):
        return self._scheduler.extenders

    @property
    def pod_group_state(self):
        return self._scheduler.pod_group_state

    # waiting pods (Permit WAIT; framework.Handle IterateOverWaitingPods /
    # GetWaitingPod surface, collapsed to allow/reject by uid)
    def allow_waiting_pod(self, uid: str) -> bool:
        return self._scheduler.allow_waiting_pod(uid)

    def reject_waiting_pod(self, uid: str, reason: str = "rejected") -> bool:
        return self._scheduler.reject_waiting_pod(uid, reason)

    def simulate_pod_group(self, group, members) -> bool:
        """Feasibility probe for pod-group preemption (the
        podGroupSchedulingFunc handed to PodGroupEvaluator.Preempt): would the
        group schedule against the CURRENT snapshot, under the SAME algorithm
        a real cycle would use (placement-constrained when the group carries
        topology constraints)? Leaves the snapshot unchanged; the caller owns
        any NodeInfo mutations (victim removals) around this probe."""
        return self._scheduler.group_feasible(group, members)

    def device_dry_run_preemption(self, fw, state, pod, node_to_status,
                                  num_candidates: int, start: int):
        """Batched DryRunPreemption when the scheduler has a device backend
        (models/tpu_scheduler.py); None routes the Evaluator to the exact
        host per-node simulation loop."""
        fn = getattr(self._scheduler, "device_dry_run_preemption", None)
        if fn is None:
            return None
        return fn(fw, state, pod, node_to_status, num_candidates, start)

    def on_async_bind_error(self, pod, exc: Exception) -> None:
        """Async dispatcher bind failure: unwind the optimistic commit. A
        409 is an optimistic-binding conflict (another scheduler won the
        pod/node): counted, not logged as an error — the re-added pod is
        skipped once the winner's commit lands through the watch feed."""
        s = self._scheduler
        s.state_unwinds += 1
        lost_node = pod.node_name  # captured for the conflict span below
        s.cache.forget_pod(pod)
        pod.node_name = ""
        s.scheduled = max(0, s.scheduled - 1)
        s.failures += 1
        if getattr(exc, "code", None) == 409:
            # Classify from the 409 BODY ({"error": AlreadyBound|
            # OutOfCapacity}): the HTTPError's str() carries only the HTTP
            # status phrase ("Conflict"), which would land every single
            # (non-bulk) async conflict in the unclassified reason bucket.
            msg = str(exc)
            try:
                import json as _json
                msg = _json.loads(exc.read()).get("error", "") or msg
            except Exception:  # noqa: BLE001 - keep the phrase fallback
                pass
            s._note_bind_conflict(msg, pod, lost_node)
            s.conflict_requeues += 1
            # Same routing as the sync path's _unwind_binding: straight to
            # the backoffQ. Plain queue.add would put the loser on the
            # activeQ, where it re-pops and re-binds against a cache that
            # has not yet seen the winner's BOUND event — a 409 hot loop at
            # full cycle speed until the watch feed catches up.
            s.queue.requeue_conflict(s.queue._new_qpi(pod))
            return
        if getattr(exc, "code", None) == 429:
            # Flow-control shed (core/flowcontrol.py) surviving the retry
            # layers' Retry-After backoff: route through the SAME
            # conflict-style backoff requeue — never the error log, and
            # never a plain add() (activeQ would re-pop into the shed wave
            # at full cycle speed). _new_qpi recovers the pod's original
            # enqueued_at stamp, so the e2e histogram spans the shed retry.
            s._note_bind_shed(pod, lost_node)
            s.queue.requeue_conflict(s.queue._new_qpi(pod))
            return
        s.error_log.append(
            f"async bind {pod.namespace}/{pod.name}: {exc!r}")
        s.queue.add(pod)

    # storage listers (volume plugins)
    @property
    def pvs(self):
        return self._scheduler.clientset.pvs

    @property
    def pvcs(self):
        return self._scheduler.clientset.pvcs

    @property
    def storage_classes(self):
        return self._scheduler.clientset.storage_classes

    @property
    def csi_nodes(self):
        return self._scheduler.clientset.csi_nodes

    # DRA listers (plugins/dynamicresources.py)
    @property
    def resource_slices(self):
        return self._scheduler.clientset.resource_slices

    @property
    def resource_claims(self):
        return self._scheduler.clientset.resource_claims

    @property
    def device_classes(self):
        return self._scheduler.clientset.device_classes


class Scheduler:
    # Queue wait past this horizon force-samples the pod's trace and emits
    # a queue.starved event (overload plane, docs/RESILIENCE.md).
    STARVATION_FORCE_S = 30.0

    def __init__(
        self,
        clientset: Optional[FakeClientset] = None,
        profile_factory: Optional[Callable[[Handle], Dict[str, Framework]]] = None,
        percentage_of_nodes_to_score: int = 0,
        seed: int = 0,
        deterministic_ties: bool = False,
        config=None,  # SchedulerConfiguration (core/config.py)
        now: Callable[[], float] = time.monotonic,
    ):
        from .config import SchedulerConfiguration  # local: avoid cycle
        from .features import (
            COMPOSITE_POD_GROUP,
            GENERIC_WORKLOAD,
            SCHEDULER_POP_FROM_BACKOFF_Q,
            SCHEDULER_QUEUEING_HINTS,
            FeatureGates,
        )
        from .metrics import SchedulerMetrics

        self.config: SchedulerConfiguration = config or SchedulerConfiguration()
        self.gates: "FeatureGates" = self.config.gates()
        self.metrics = SchedulerMetrics()
        self.clientset = clientset or FakeClientset()
        self.cache = Cache(now=now)
        self.snapshot = Snapshot()
        self.now = now
        self.rng = random.Random(seed)
        self.percentage_of_nodes_to_score = (
            percentage_of_nodes_to_score or self.config.percentage_of_nodes_to_score)
        # deterministic_ties picks the first max-score node in evaluation
        # order instead of reservoir-sampling among ties (schedule_one.go
        # selectHost) — required for host↔device assignment equivalence.
        self.deterministic_ties = deterministic_ties
        self.next_start_node_index = 0

        handle = Handle(self)
        if profile_factory is not None:
            self.profiles = profile_factory(handle)
        elif config is not None:
            from .registry import build_framework
            self.profiles = {
                p.scheduler_name: build_framework(
                    handle, profile_name=p.scheduler_name,
                    plugins=p.plugins.resolve(), plugin_args=p.plugin_config)
                for p in self.config.profiles
            }
        else:
            from .registry import default_profiles
            self.profiles = default_profiles(handle)
        self.handle = handle
        first = next(iter(self.profiles.values()))
        import os as _os
        self.queue = PriorityQueue(
            framework=first,
            initial_backoff=self.config.pod_initial_backoff_seconds,
            max_backoff=self.config.pod_max_backoff_seconds,
            now=now,
            pop_from_backoff_q=self.gates.enabled(SCHEDULER_POP_FROM_BACKOFF_Q),
            gang_enabled=self.gates.enabled(GENERIC_WORKLOAD),
            queueing_hints_enabled=self.gates.enabled(SCHEDULER_QUEUEING_HINTS),
            composite_enabled=self.gates.enabled(COMPOSITE_POD_GROUP),
            # Per-tenant weighted fair dequeue (overload plane, docs/
            # RESILIENCE.md): config-driven, with an env seam so the shard
            # harness's OS-process schedulers can switch it on uniformly.
            fair_tenant_dequeue=(
                getattr(self.config, "fair_tenant_dequeue", False)
                or _os.environ.get("TPU_SCHED_FAIR_TENANTS", "") == "1"),
            tenant_weights=getattr(self.config, "tenant_weights", None),
        )
        self.queue.metrics = self.metrics  # queueing-hint latency series
        # Extenders (extender.go; config extenders or injected objects).
        from .extender import Extender, http_transport
        self.extenders: List[Extender] = []
        for e in self.config.extenders:
            if isinstance(e, Extender):
                self.extenders.append(e)
            else:
                ext = Extender(
                    name=e.get("name", e.get("urlPrefix", "extender")),
                    filter_verb=e.get("filterVerb", ""),
                    prioritize_verb=e.get("prioritizeVerb", ""),
                    bind_verb=e.get("bindVerb", ""),
                    preempt_verb=e.get("preemptVerb", ""),
                    weight=e.get("weight", 1),
                    ignorable=e.get("ignorable", False),
                    managed_resources=tuple(e.get("managedResources", ())),
                    transport=http_transport(e["urlPrefix"]),
                )
                self.extenders.append(ext)
        # Async API dispatcher (backend/api_dispatcher; SchedulerAsyncAPICalls).
        from .api_dispatcher import APIDispatcher
        from .features import SCHEDULER_ASYNC_API_CALLS
        mode = "inline"
        if self.gates.enabled(SCHEDULER_ASYNC_API_CALLS) and getattr(
                self.config, "async_dispatch_threads", False):
            mode = "thread"
        self.api_dispatcher = APIDispatcher(mode=mode, metrics=self.metrics)
        # Callback gauges (free until exposed): queue/dispatcher depth series.
        self.metrics.inflight_events._fn = lambda: {
            (): float(len(self.queue._event_log))}
        self.metrics.pending_async_api_calls._fn = lambda: {
            (): float(self.api_dispatcher.pending_count())}
        self.metrics.queued_entities._fn = self._queued_entity_counts
        self.metrics.unschedulable_pods._fn = self._unschedulable_by_plugin
        # Per-tenant starvation gauge (overload plane): computed from live
        # queue contents at scrape time, zero hot-path bookkeeping.
        self.metrics.queue_starvation._fn = lambda: {
            (ns,): v
            for ns, v in self.queue.starvation_by_namespace().items()}
        # Watch decode cost, by wire form (core/watchcache.py shard-filtered
        # streams) and codec (core/wire.py binary vs JSON): counters live on
        # the HTTP clientset's reflector thread; the gauges read them at
        # scrape time so bench.py --shards can show the per-shard
        # decoded-events/bytes 1/N and which plane ran. Empty on a
        # FakeClientset (no wire).
        _cs = self.clientset
        self.metrics.watch_decoded_events._fn = lambda: {
            k: float(v) for k, v in
            getattr(_cs, "wire_decode_events", {}).items()}
        self.metrics.watch_decoded_bytes._fn = lambda: {
            k: float(v) for k, v in
            getattr(_cs, "wire_decode_bytes", {}).items()}
        # Waiting pods (Permit WAIT; framework.go waitingPods registry).
        # _next_wait_deadline makes expiry TIMER-DRIVEN: schedule_one checks
        # it every cycle (O(1)), so a parked pod times out even while the
        # scheduler is continuously busy (runtime/framework.go:2097
        # WaitOnPermit runs on its own timer in the reference).
        self.waiting_pods: Dict[str, tuple] = {}
        self.permit_wait_timeout = 60.0
        self._next_wait_deadline = float("inf")
        # Scheduled-group-pods store (backend/podgroupstate): group members
        # the CACHE considers placed (assumed + bound), maintained by the
        # cache's add/remove flow — placement generation pins a partially
        # scheduled gang's domain against the scheduler-side truth, with no
        # watch-feed lag under thread-mode async binds.
        from .podgroupstate import PodGroupState
        self.pod_group_state = PodGroupState()
        self.cache.pod_group_state = self.pod_group_state
        # Event recorder + step tracing (schedule_one.go:1138, :574).
        from .tracing import EventRecorder
        self.recorder = EventRecorder()
        # Pod-lifecycle spans (core/spans.py; docs/OBSERVABILITY.md): the
        # process-global tracer — head-sampled, ring-buffered; every stage
        # below checks `tracer.wants(ctx)` before building anything.
        from .spans import default_tracer
        self.tracer = default_tracer()
        # metrics
        self.attempts = 0
        self.scheduled = 0
        self.failures = 0
        self.error_log: List[str] = []
        # Versions node-state-relevant cluster changes (see _on_pod_event).
        # The typed journal records WHAT each bump was, so device sessions
        # can delta-patch instead of tearing down (cache.py EventJournal);
        # cluster_event_seq mirrors journal.seq for all existing consumers.
        self.journal = EventJournal()
        self.cluster_event_seq = 0
        # Versions cache-state UNWINDS that happen outside a scheduling
        # attempt (bind failure after Permit WAIT release, waiter expiry,
        # async bind error): a device session/resume carry or fail memo
        # computed before an unwind no longer reflects the cache.
        self.state_unwinds = 0
        # Placements the watch feed revoked (a re-list/resume after an
        # apiserver restart reported a cache-placed pod as UNBOUND): the
        # assumed-vs-recovered-truth reconciliation below unwound them.
        self.reconcile_unwinds = 0
        # Control-plane failovers this scheduler has reacted to: a FAILOVER
        # watch marker (replicated apiserver promotion) bumps the
        # clientset's failover_count; run_until_idle notices and runs
        # reconcile_bindings — a bind the dead leader acked but never
        # shipped is unbound in the promoted truth and has NO event to
        # trigger the per-event reconcile path above.
        self._seen_failovers = 0
        # Shard plane (kubernetes_tpu/shard/): optional admission predicate —
        # when set, only pods it accepts enter THIS scheduler's queue (the
        # shard-scoped admission seam; the cache still mirrors the whole
        # cluster so every shard plans against full node state). Optimistic
        # binding: a 409 from the binding subresource is counted here and
        # requeued through the backoffQ (see _unwind_binding).
        self.pod_admission: Optional[Callable[[Pod], bool]] = None
        self.shard_member = None  # set by shard.ShardMember (debugger dump)
        # Flow-control sheds (429) this scheduler's binds absorbed: each
        # one requeued through the conflict-style backoff path with its
        # original queue-admission stamp preserved.
        self.shed_requeues = 0
        # Pods re-entering the queue after a node-lifecycle eviction (the
        # server's recreate carries the eviction-intent annotation). One
        # eviction = one recreate event = exactly one bump — the chaos
        # acceptance diffs this against the controller's evictions_total.
        # `_eviction_residue` (uid -> intent) mirrors the server's ledger
        # lifecycle: the annotation stays on the recreated pod, so a
        # re-list (watch Replace after an apiserver failover) replays the
        # same pending pod as a fresh ADDED — a matching residue entry is
        # that replay, not a new eviction. The entry dies when the pod is
        # observed bound (or deleted), because any LATER eviction — even
        # one re-minting the same uid@node intent after the pod returned
        # to a recovered-then-refailed node — must count again.
        self.eviction_requeues = 0
        self._eviction_residue: Dict[str, str] = {}
        # Per-cycle hook (run_until_idle): the shard member's ownership
        # refresh runs here so queue-mutating failover stays on the
        # scheduling thread even through long drains.
        self.loop_hook: Optional[Callable[[], object]] = None
        self.bind_conflicts = 0
        self.conflict_requeues = 0
        # True when every bind terminates at the apiserver's binding
        # subresource, whose Omega-style transaction validation rejects an
        # overcommitting commit with a 409 (set by shard.ShardMember). Lets
        # device sessions treat a peer shard's bind feed optimistically:
        # commit in-flight results as-is and let the store arbitrate,
        # instead of invalidating the session pessimistically.
        self.bind_capacity_validated = False
        # Off-thread watch-event inbox (see _threaded): deque append/popleft
        # are atomic under the GIL, so no lock is needed.
        from collections import deque
        self._event_inbox = deque()
        self._wire_event_handlers()

    # -- event handlers (eventhandlers.go:624 addAllEventHandlers) ---------

    def _wire_event_handlers(self) -> None:
        self.clientset.on_pod_event(self._threaded(
            self._timed_event("pod", self._on_pod_event)))
        self.clientset.on_node_event(self._threaded(
            self._timed_event("node", self._on_node_event)))
        self.clientset.on_namespace_event(self._threaded(self._bump(
            self.cache.add_namespace, EV_NAMESPACE,
            keyfn=lambda ns: ns.name)))
        self.clientset.on_pod_group_event(self._threaded(self._bump(
            self.queue.register_pod_group, EV_QUEUE)))
        self.clientset.on_storage_event(self._threaded(
            self._timed_event("storage", self._on_storage_event)))

    def _timed_event(self, name: str, handler):
        """event_handling_duration_seconds per handler invocation
        (eventhandlers.go handler latency series)."""
        hist = self.metrics.event_handling_duration

        def h(*args):
            t0 = time.perf_counter()
            try:
                handler(*args)
            finally:
                hist.observe(time.perf_counter() - t0, name)
        return h

    def _record_event(self, kind: str, key: str = "", pod_plain: bool = False,
                      pod_ports: bool = False, shrink: bool = False) -> None:
        """Journal one typed event and advance cluster_event_seq."""
        self.cluster_event_seq = self.journal.record(
            kind, key, pod_plain=pod_plain, pod_ports=pod_ports,
            shrink=shrink)

    def _bump(self, handler, kind: str, keyfn=None):
        """Wrap a handler so it versions cluster_event_seq with a typed
        record (namespace labels and pod-group registrations affect
        scheduling outcomes)."""
        def h(*args):
            self._record_event(kind, keyfn(*args) if keyfn else "")
            handler(*args)
        return h

    def _threaded(self, handler):
        """Watch events raised off the scheduling thread (e.g. the thread-mode
        dispatcher's bind fanning out through the clientset) are parked in an
        inbox and replayed by the scheduling loop — the DeltaFIFO seam
        (client-go delta_fifo.go): cache/queue mutation stays single-threaded.
        Events raised on the scheduling thread dispatch inline, preserving the
        synchronous semantics tests rely on."""
        loop_ident = threading.get_ident()  # get_ident beats current_thread

        def dispatch(*args):
            if threading.get_ident() == loop_ident:
                handler(*args)
            else:
                self._event_inbox.append((handler, args))
        return dispatch

    def drain_event_inbox(self) -> int:
        """Replay off-thread watch events on the scheduling loop."""
        n = 0
        while self._event_inbox:
            try:
                handler, args = self._event_inbox.popleft()
            except IndexError:
                break
            handler(*args)
            n += 1
        return n

    def _on_storage_event(self, kind: str, obj) -> None:
        from .queue import EVENT_STORAGE_ADD
        # Device-session validity: only storage objects that change NODE
        # capability can stale an in-flight carry (CSINode limits, device
        # pools, PV topology, binding-mode classes). New claims/PVCs are
        # pod-side state — they unblock WAITING pods (queue move below) but
        # cannot invalidate decisions already made for eligible pods, and
        # bumping the seq per created claim would tear down a session per
        # measured pod (the claim-template workload creates one each).
        if kind not in ("pvc", "resource_claim"):
            self._record_event(EV_OTHER, kind)
        self.queue.move_all_to_active_or_backoff(EVENT_STORAGE_ADD, None, obj)

    def _responsible_for_pod(self, pod: Pod) -> bool:
        """eventhandlers.go responsibleForPod: only queue pods whose
        schedulerName names one of our profiles."""
        return pod.scheduler_name in self.profiles

    def _admits(self, pod: Pod) -> bool:
        """Shard-scoped admission: with no shard plane every pod is ours."""
        return self.pod_admission is None or self.pod_admission(pod)

    def _on_pod_event(self, kind: str, old: Optional[Pod], new: Pod) -> None:
        if (getattr(new, "wire_slim", False) and not new.node_name
                and kind in ("add", "update")
                and self.pod_admission is not None
                and self._responsible_for_pod(new) and self._admits(new)):
            # A slim-projection pod this scheduler ADMITS: shard ownership
            # grew past the watch stream's static `shard=i/n` filter
            # (adoption) — the pod arrived without its real spec
            # (selectors, tolerations, gates). Hydrate from the server's
            # watch cache before any queue state is built from the
            # projection; on a transient fetch failure the pod stays out
            # of the queue and the adoption sweep retries. Gated on an
            # ATTACHED shard plane (pod_admission): before the ShardMember
            # exists, _admits answers True for everything, and the
            # constructor-time handler replay would hydrate every foreign
            # pod — while deadlocking on the clientset's _dispatch_lock,
            # which that replay already holds on this thread.
            hydrate = getattr(self.clientset, "hydrate_pod", None)
            if hydrate is not None:
                full = hydrate(new.uid)
                if full is not None:
                    new = full
        # cluster_event_seq versions node-state-relevant cluster changes so a
        # device batch session (models/tpu_scheduler.py) knows whether the
        # on-device carry still reflects the cluster; the typed journal
        # record lets it patch instead of tearing down. Benign for the
        # carry (no record): pending-pod adds (queue-only) and our own bind
        # confirms (the carry already holds that placement via the assume).
        if kind == "add" and not new.node_name:
            pass
        elif (kind == "update" and new.node_name
                and self.cache.is_assumed_pod(new)):
            # Our own bind confirm: the scheduler already assumed this pod
            # onto the node (note `old` may alias the scheduler's mutated
            # object, so old.node_name can't distinguish the transition —
            # the assumed set can).
            self._note_own_bind_confirm(new)
        else:
            self._record_pod_event(kind, old, new)
        if new.node_name or kind == "delete":
            # Bound or gone closes the evicted-pending window — matching
            # the apiserver's ledger prune — so this pod's NEXT eviction
            # counts even if it re-mints the same uid@node intent.
            self._eviction_residue.pop(new.uid, None)
        if kind == "add":
            if new.node_name:
                self.cache.add_pod(new)
                self.queue.move_all_to_active_or_backoff(
                    EVENT_ASSIGNED_POD_ADD, None, new)
            elif (self._responsible_for_pod(new) and self._admits(new)
                    and not getattr(new, "wire_slim", False)):
                # A still-slim pod (hydration failed) must never be
                # SCHEDULED from its projection; the sweep retries it.
                intent = new.annotations.get(EVICTED_ANNOTATION)
                if intent and self._eviction_residue.get(new.uid) != intent:
                    self._eviction_residue[new.uid] = intent
                    self.eviction_requeues += 1
                self.queue.add(new)
        elif kind == "update":
            if new.node_name:
                if old is not None and not old.node_name:
                    # pending → bound transition (our own bind confirm):
                    # still an AssignedPodAdd for QUEUEING purposes — parked
                    # pods whose affinity/spread terms this pod satisfies
                    # must requeue (eventhandlers.go addPodToCache →
                    # MoveAllToActiveOrBackoffQueue(AssignedPodAdd)). The
                    # cluster_event_seq stays unbumped (the carry already
                    # holds the placement via the assume).
                    self.cache.add_pod(new)
                    self.queue.move_all_to_active_or_backoff(
                        EVENT_ASSIGNED_POD_ADD, None, new)
                else:
                    self.cache.update_pod(old, new)
            else:
                st = self.cache.pod_states.get(new.uid)
                if st is not None and st.binding_finished:
                    # Post-restart reconciliation: the API says this pod is
                    # UNBOUND while the cache holds a placement whose bind
                    # COMPLETED (binding_finished) — the control plane lost
                    # the committed bind (apiserver restarted from a store
                    # that predates it; the re-list/resume replay is the
                    # diff against recovered truth). Unwind the phantom
                    # placement and reschedule; the retry/bind layers will
                    # re-commit it. A placement whose bind is still IN
                    # FLIGHT is deliberately not touched: a stale re-list
                    # can race a healthy bind, and exhaustion of that
                    # bind's retries already unwinds via the bind-error
                    # paths.
                    self.reconcile_unwinds += 1
                    self.state_unwinds += 1
                    self.cache.remove_pod(st.pod)
                    self.queue.move_all_to_active_or_backoff(
                        EVENT_ASSIGNED_POD_DELETE, st.pod, None)
                    if self._responsible_for_pod(new) and self._admits(new):
                        new.node_name = ""
                        self.queue.add(new)
                else:
                    if ((self._admits(new) or self.queue.has_entity(new.uid))
                            and not getattr(new, "wire_slim", False)):
                        # Non-admitted pending pods stay out of the queue;
                        # an already-queued one (ownership shrank after
                        # adoption handback) still takes spec updates — the
                        # optimistic 409 path resolves any overlap. A pod
                        # still in slim projection (hydration failed) must
                        # not fall through update() into a spec-less add.
                        self.queue.update(old, new)
        elif kind == "delete":
            if new.node_name:
                self.cache.remove_pod(new)
                self.queue.move_all_to_active_or_backoff(
                    EVENT_ASSIGNED_POD_DELETE, new, None)
            else:
                self.queue.delete(new)

    def _note_own_bind_confirm(self, new: Pod) -> None:
        """Seam: the watch stream confirmed one of OUR binds (the pod is in
        the assumed set and arrived bound). Subclasses settle any
        optimistic-commit bookkeeping here — models/tpu_scheduler.py drops
        the score-hint take-back tag, since no 409 can follow a confirm."""

    def _record_pod_event(self, kind: str, old: Optional[Pod], new: Pod) -> None:
        """Journal classification for a non-benign watch pod event."""
        plain, ports = pod_event_flags(new)
        if old is not None and old is not new:
            oplain, oports = pod_event_flags(old)
            plain, ports = plain and oplain, ports or oports
        if kind == "add":
            self._record_event(EV_POD_ADD, new.node_name,
                               pod_plain=plain, pod_ports=ports)
        elif kind == "update":
            if new.node_name:
                old_node = old.node_name if old is not None else ""
                if not old_node:
                    # Externally assigned (someone else's bind): load appears
                    # on the node exactly like an assigned-pod add.
                    self._record_event(EV_POD_ADD, new.node_name,
                                       pod_plain=plain, pod_ports=ports)
                elif old_node == new.node_name:
                    self._record_event(EV_POD_UPDATE, new.node_name,
                                       pod_plain=plain, pod_ports=ports)
                else:  # moved between nodes: old row shrinks, new row grows
                    self._record_event(EV_POD_REMOVE, old_node,
                                       pod_plain=plain, pod_ports=ports,
                                       shrink=True)
                    self._record_event(EV_POD_ADD, new.node_name,
                                       pod_plain=plain, pod_ports=ports)
            else:
                st = self.cache.pod_states.get(new.uid)
                if st is not None and st.binding_finished:
                    # Lost-bind reconciliation unwind (below): cache state
                    # moves outside any single node row's aggregates.
                    self._record_event(EV_OTHER, new.uid)
                else:
                    # Pending-pod spec update — the scheduling-gate lift
                    # path. Queue-only: no node state moves.
                    self._record_event(EV_QUEUE, new.uid)
        elif kind == "delete":
            if new.node_name:
                self._record_event(EV_POD_REMOVE, new.node_name,
                                   pod_plain=plain, pod_ports=ports,
                                   shrink=True)
            else:
                self._record_event(EV_QUEUE, new.uid)
        else:
            self._record_event(EV_OTHER, new.uid)

    @staticmethod
    def _node_shrink_only(old, new) -> bool:
        """True when `new` can only ENLARGE feasibility vs `old`: no taint
        added, allocatable not reduced, unschedulable not switched on —
        device results computed against `old` stay feasible under `new`."""
        if new.unschedulable and not old.unschedulable:
            return False
        o_t = {(t.key, t.value, t.effect) for t in old.taints}
        if any((t.key, t.value, t.effect) not in o_t for t in new.taints):
            return False
        oa, na = old.allocatable, new.allocatable
        if (na.milli_cpu < oa.milli_cpu or na.memory < oa.memory
                or na.ephemeral_storage < oa.ephemeral_storage
                or na.allowed_pod_number < oa.allowed_pod_number):
            return False
        return all(na.scalar_resources.get(k, 0) >= v
                   for k, v in oa.scalar_resources.items())

    def _on_node_event(self, kind: str, old, new) -> None:
        if kind == "update" and old is not None and old.name == new.name \
                and old.labels == new.labels and old.images == new.images \
                and old.declared_features == new.declared_features:
            # Taint/allocatable/unschedulable-only change: one row's
            # non-feature tensors — delta-patchable by a live session.
            self._record_event(EV_NODE_UPDATE, new.name,
                               shrink=self._node_shrink_only(old, new))
        elif kind == "update":
            self._record_event(EV_OTHER, new.name)
        else:
            self._record_event(EV_STRUCTURAL, new.name)
        if kind == "add":
            self.cache.add_node(new)
            self.queue.move_all_to_active_or_backoff(EVENT_NODE_ADD, None, new)
        elif kind == "update":
            self.cache.update_node(new)
            self.queue.move_all_to_active_or_backoff(
                EVENT_NODE_UPDATE, old, new)
        elif kind == "delete":
            self.cache.remove_node(new.name)

    # -- profiles ----------------------------------------------------------

    def framework_for_pod(self, pod: Pod) -> Framework:
        fw = self.profiles.get(pod.scheduler_name)
        if fw is None:
            raise KeyError(f"no profile for scheduler name {pod.scheduler_name!r}")
        return fw

    # -- run loop ----------------------------------------------------------

    def run_until_idle(self, max_cycles: int = 1_000_000) -> int:
        """Drive schedule_one until the queue drains (test/bench harness)."""
        fc = getattr(self.clientset, "failover_count", 0)
        if fc != self._seen_failovers:
            # Control-plane leadership moved (FAILOVER watch marker): drain
            # the inbox so the cache reflects everything the stream already
            # delivered, then sweep for placements whose committed bind the
            # promoted leader does not hold (see reconcile_bindings).
            self._seen_failovers = fc
            self.drain_event_inbox()
            self.reconcile_bindings()
        n = 0
        while n < max_cycles:
            if self.loop_hook is not None:
                self.loop_hook()
            if not self.schedule_one():
                self.queue.flush_backoff_completed()
                self.flush_expired_waiters()
                # Drain async bind failures on THIS thread (the inbox keeps
                # cache/queue mutation off the dispatcher worker), then
                # re-check: an unwound pod goes back onto the queue. The
                # flush is a SHORT slice, not a full barrier — with binds in
                # flight, a blocking flush would starve the event inbox
                # (newly created pods can't enter the queue while the loop
                # is parked), which capped sharded throughput at the bind
                # drain rate. Only a fully idle dispatcher ends the loop, so
                # the contract is unchanged: on return, the queue is drained
                # AND every accepted write has landed or reported.
                self.api_dispatcher.flush(timeout=0.05)
                self.process_async_api_errors()
                if not self.schedule_one():
                    if self.api_dispatcher.idle():
                        break
                    n += 1  # count the wait slice: max_cycles stays a bound
                    continue  # writes still in flight: stay responsive
            n += 1
        return n

    def reconcile_bindings(self) -> int:
        """Failover sweep (scheduling thread only): unwind every cache
        placement whose COMPLETED bind the control plane does not hold.

        The per-event reconcile in _on_pod_event covers binds revoked by a
        re-list/resume replay — but a bind the dead LEADER acked and never
        shipped to the promoted follower produces NO event at all (the
        follower simply never saw it), so after a FAILOVER marker this
        sweep compares the informer truth against the cache directly.
        In-flight binds are deliberately untouched: their retry layers
        re-commit through the idempotent/409 surface."""
        unwound = 0
        for uid, st in list(self.cache.pod_states.items()):
            if not st.binding_finished:
                continue
            api_pod = self.clientset.pods.get(uid)
            if api_pod is None or api_pod.node_name:
                continue  # deleted -> DELETED event path; bound -> coherent
            self.reconcile_unwinds += 1
            self.state_unwinds += 1
            self._record_event(EV_OTHER, uid)
            self.cache.remove_pod(st.pod)
            self.queue.move_all_to_active_or_backoff(
                EVENT_ASSIGNED_POD_DELETE, st.pod, None)
            if self._responsible_for_pod(api_pod) and self._admits(api_pod):
                api_pod.node_name = ""
                self.queue.add(api_pod)
            unwound += 1
        return unwound

    def process_async_api_errors(self) -> int:
        """Run deferred thread-mode on_error handlers on the scheduling loop
        (the reference's dispatcher invokes onError on the scheduling side via
        the cache adapter; backend/api_dispatcher/). Also replays off-thread
        watch events parked by _threaded. Cheap no-op when both are empty."""
        self.drain_event_inbox()
        if not self.api_dispatcher.has_errors():
            return 0
        drained = self.api_dispatcher.drain_errors()
        for call, exc in drained:
            call.on_error(exc)
        return len(drained)

    # -- one cycle ---------------------------------------------------------

    def schedule_one(self) -> bool:
        self.process_async_api_errors()
        if self.waiting_pods and self.now() >= self._next_wait_deadline:
            self.flush_expired_waiters()
        qpi = self.queue.pop()
        if qpi is None:
            return False
        self.process_one(qpi)
        return True

    def process_one(self, qpi) -> None:
        """One full scheduling+binding cycle for an already-popped entity."""
        if isinstance(qpi, QueuedCompositeGroupInfo):
            self.schedule_composite_group(qpi)
            return
        if isinstance(qpi, QueuedPodGroupInfo):
            _t_pg = time.perf_counter()
            _before = self.metrics.podgroup_schedule_attempts.value("scheduled")
            self.schedule_pod_group(qpi)
            dt = time.perf_counter() - _t_pg
            self.metrics.podgroup_scheduling_algorithm_duration.observe(dt)
            self.metrics.podgroup_scheduling_attempt_duration.observe(
                dt, "scheduled" if self.metrics.podgroup_schedule_attempts.value(
                    "scheduled") > _before else "unschedulable")
            return
        pod = qpi.pod
        if pod.deletion_ts is not None:
            # skipPodSchedule (schedule_one.go:93): the pod is being deleted;
            # don't attempt it — the delete event will clear it from the queue.
            self.queue.done(pod.uid)
            return
        if pod.uid in self.cache.pod_states:
            # skipPodSchedule: the cache already holds a placement for this
            # pod (a reconcile unwind raced the bind-confirm event — the
            # re-queued copy predates the confirmation). Scheduling it again
            # would double-place it.
            self.queue.done(pod.uid)
            return
        from .tracing import StepTrace
        fw = self.framework_for_pod(pod)
        self.attempts += 1
        t0 = time.perf_counter()
        ctx = self.tracer.context_for(pod.uid)
        eq = getattr(qpi, "enqueued_at", None)
        if eq is not None and self.now() - eq >= self.STARVATION_FORCE_S:
            # A pod that waited past the starvation horizon is FORCE-
            # sampled (overload forensics): its whole trace — queue.wait
            # through bind — survives into the flight ring regardless of
            # the head-sampling rate.
            ctx = self.tracer.context_for(pod.uid, force=True)
            self.tracer.event("queue.starved", ctx,
                              wait=round(self.now() - eq, 3),
                              namespace=pod.namespace)
        self.record_queue_wait(qpi, ctx)
        trace = StepTrace("Scheduling", ctx=ctx,
                          pod=f"{pod.namespace}/{pod.name}")
        state = CycleState()
        try:
            self._process_one_traced(fw, state, qpi, trace, t0)
        finally:
            # utiltrace logs via defer: slow cycles are reported on EVERY
            # outcome — bound, unschedulable, Permit WAIT, or error.
            trace.log_if_long()

    def _process_one_traced(self, fw, state, qpi, trace, t0) -> None:
        pod = qpi.pod
        try:
            result = self.scheduling_cycle(fw, state, qpi)
            trace.step("scheduling cycle done")
        except FitError as fe:
            self.handle_fit_error(fw, state, qpi, fe, t0)
            trace.step("unschedulable")
            return
        except Exception as e:  # noqa: BLE001
            self.error_log.append(f"{pod.namespace}/{pod.name}: {e!r}")
            self.handle_scheduling_failure(fw, qpi, Status.error(str(e)), None)
            self.queue.done(pod.uid)
            self.metrics.schedule_attempts.inc("error", fw.profile_name)
            return
        if result.waiting:
            # WaitOnPermit (framework.go:2097): the pod stays reserved
            # (assumed in the cache) until a Permit plugin allows or rejects
            # it, or the wait times out (flush_expired_waiters).
            self.park_waiting_pod(fw, state, qpi, result)
            self.queue.done(pod.uid)
            return
        bound = self.run_binding_cycle(fw, state, qpi, result)
        self.queue.done(pod.uid)
        trace.step("binding cycle done")
        elapsed = time.perf_counter() - t0
        if bound:
            # Host-path commit span: the whole cycle (algorithm + bind
            # enqueue) — the device path records finer-grained stages.
            self.tracer.record("host.commit", trace.ctx, elapsed,
                               node=result.suggested_host, path="host")
        self.metrics.schedule_attempts.inc("scheduled" if bound else "error", fw.profile_name)
        self.metrics.scheduling_attempt_duration.observe(
            elapsed, "scheduled" if bound else "error", fw.profile_name)
        if bound and qpi.initial_attempt_timestamp is not None:
            self.metrics.pod_scheduling_sli_duration.observe(
                self.now() - qpi.initial_attempt_timestamp, str(qpi.attempts))
        if bound:
            self.metrics.pod_scheduling_attempts.observe(max(1, qpi.attempts))

    def handle_fit_error(self, fw: Framework, state: CycleState,
                         qpi: QueuedPodInfo, fe: FitError, t0: float) -> None:
        """The scheduling-cycle FitError tail (schedule_one.go:169 tail +
        :1152 handleSchedulingFailure): PostFilter (preemption) with the
        diagnosis, nomination recording, requeue, metrics. Shared by the host
        cycle and the device path's vectorized diagnosis."""
        pod = qpi.pod
        if fw.post_filter_plugins:
            _t = time.perf_counter()
            result, post_st = fw.run_post_filter_plugins(
                state, pod, fe.diagnosis.node_to_status)
            self._observe_point("PostFilter", _t, post_st.is_success())
            nominated = getattr(result, "nominating_info", None) if result else None
            if post_st.is_success() and nominated:
                pod.nominated_node_name = nominated
                self.clientset.patch_pod_status(pod, nominated_node_name=nominated)
                self.queue.nominator.add_nominated_pod(qpi.pod_info, nominated)
        self.handle_scheduling_failure(fw, qpi, Status(UNSCHEDULABLE, (str(fe),)), fe.diagnosis)
        self.queue.done(pod.uid)
        self.metrics.schedule_attempts.inc("unschedulable", fw.profile_name)
        self.metrics.scheduling_attempt_duration.observe(
            time.perf_counter() - t0, "unschedulable", fw.profile_name)

    def scheduling_cycle(self, fw: Framework, state: CycleState, qpi: QueuedPodInfo) -> ScheduleResult:
        pod = qpi.pod
        self.cache.update_snapshot(self.snapshot)
        _t_alg = time.perf_counter()
        result = self.schedule_pod(fw, state, pod)
        self.metrics.scheduling_algorithm_duration.observe(
            time.perf_counter() - _t_alg)
        # assume (schedule_one.go:1060): in-memory commit before binding
        assumed = pod
        assumed.node_name = result.suggested_host
        self.cache.assume_pod(assumed, qpi.pod_info)
        _t = time.perf_counter()
        st = fw.run_reserve_plugins_reserve(state, assumed, result.suggested_host)
        _t = self._observe_point("Reserve", _t, st.is_success())
        if not st.is_success():
            fw.run_reserve_plugins_unreserve(state, assumed, result.suggested_host)
            self.cache.forget_pod(assumed)
            assumed.node_name = ""
            raise RuntimeError(f"reserve failed: {st.message()}")
        st = fw.run_permit_plugins(state, assumed, result.suggested_host)
        self._observe_point("Permit", _t, not st.is_rejected())
        if st.is_rejected():
            fw.run_reserve_plugins_unreserve(state, assumed, result.suggested_host)
            self.cache.forget_pod(assumed)
            assumed.node_name = ""
            raise RuntimeError(f"permit rejected: {st.message()}")
        if st.code == WAIT:
            result.waiting = True  # parks in waiting_pods; binds on Allow
        return result

    # -- gang cycle (schedule_one_podgroup.go) -----------------------------

    def schedule_pod_group(self, qgpi: QueuedPodGroupInfo) -> None:
        """Pod-group scheduling (scheduleOnePodGroup :81 → podGroupCycle :428).

        With placement plugins and a topology-constrained group, the
        PLACEMENT algorithm runs (schedule_one_podgroup.go:971
        podGroupSchedulingPlacementAlgorithm): generate candidate node
        subsets, simulate the group against each under a snapshot placement
        session, gate with PlacementFeasible, score the successful candidates
        with PlacementScore plugins, and commit the best. Otherwise the
        default algorithm (:556): member-wise placement against the snapshot
        (assumed into the snapshot, not the cache, schedule_one.go:1077-1082)
        with LIFO revert on any failure (revertFns :50-75)."""
        self.attempts += 1
        members = sorted(
            qgpi.members,
            key=lambda m: (-m.pod.priority, m.timestamp))
        if not members:
            self.queue.done(qgpi.uid)
            return
        fw = self.framework_for_pod(members[0].pod)
        self.cache.update_snapshot(self.snapshot)

        group = qgpi.group
        if fw.placement_generate_plugins and getattr(group, "topology_keys", ()):
            # A topology-constrained group is scheduled ONLY through the
            # placement algorithm — falling back to unconstrained member-wise
            # placement would violate the constraint (the reference returns
            # "0/N placements are available" in that case).
            self._schedule_group_with_placements(fw, qgpi, members)
            return

        placed: List[Tuple[QueuedPodInfo, CycleState, ScheduleResult]] = []
        failure: Optional[FitError] = None
        for m in members:
            state = CycleState()
            try:
                result = self.schedule_pod(fw, state, m.pod)
            except FitError as fe:
                failure = fe
                qgpi.unschedulable_plugins |= fe.diagnosis.unschedulable_plugins
                break
            m.pod.node_name = result.suggested_host
            self.snapshot.assume_pod(m.pod)  # simulate in-snapshot only
            placed.append((m, state, result))

        if failure is not None:
            # LIFO revert: the snapshot returns to the pre-cycle view.
            for m, _, _ in reversed(placed):
                self.snapshot.forget_pod(m.pod)
                m.pod.node_name = ""
            self._fail_pod_group(fw, qgpi, members, failure.diagnosis)
            return

        # Commit (submitPodGroupAlgorithmResult :812): assume into the cache
        # and run each member's binding cycle (each member keeps ITS
        # simulation CycleState — stateful plugins wrote PreFilter/Reserve
        # data there). Every attempted member leaves the group buffer —
        # commit failures are requeued individually and must not be
        # double-tracked.
        committed = 0
        attempted_uids = set()
        for m, state, result in placed:
            attempted_uids.add(m.pod.uid)
            self.cache.assume_pod(m.pod)
            if self._commit_group_member(fw, m, state, result):
                committed += 1
        _t_store = time.perf_counter()
        group_key = (qgpi.group.namespace, qgpi.group.name)
        self.queue.clear_group_members(group_key, attempted_uids)
        self.queue.done(qgpi.uid)
        self.metrics.store_schedule_results_duration.observe(
            time.perf_counter() - _t_store)
        self.metrics.podgroup_schedule_attempts.inc(
            "scheduled" if committed else "unschedulable")

    def schedule_composite_group(self, qcgi: QueuedCompositeGroupInfo) -> None:
        """The composite tree cycle (schedule_one_podgroup.go composite
        paths + completeCompositePodGroupAlgorithmResult): every leaf
        PodGroup of the root CompositePodGroup simulates member-wise against
        the snapshot; ANY leaf failure rolls the WHOLE tree back (partial
        results are discarded, :51) and parks the root; success commits
        every member. Leaves schedule with the default member-wise
        algorithm (placement-constrained leaves inside composites are out of
        this reduced scope and fail the tree)."""
        self.attempts += 1
        self.cache.update_snapshot(self.snapshot)
        placed: List[Tuple[QueuedPodInfo, CycleState, ScheduleResult]] = []
        failure: Optional[FitError] = None
        for group, members in qcgi.groups:
            ms = sorted(members, key=lambda m: (-m.pod.priority, m.timestamp))
            if not ms:
                continue
            fw = self.framework_for_pod(ms[0].pod)
            if getattr(group, "topology_keys", ()):
                qcgi.unschedulable_plugins.add("TopologyPlacementGenerator")
                break
            for m in ms:
                state = CycleState()
                try:
                    result = self.schedule_pod(fw, state, m.pod)
                except FitError as fe:
                    failure = fe
                    qcgi.unschedulable_plugins |= fe.diagnosis.unschedulable_plugins
                    break
                m.pod.node_name = result.suggested_host
                self.snapshot.assume_pod(m.pod)
                placed.append((m, state, result))
            else:
                continue
            break
        else:
            if placed:
                # Whole tree feasible: commit every member (each keeps ITS
                # simulation CycleState, submitPodGroupAlgorithmResult).
                committed = 0
                attempted: Dict[Tuple[str, str], set] = {}
                for m, state, result in placed:
                    self.cache.assume_pod(m.pod)
                    gkey = (m.pod.namespace, m.pod.pod_group)
                    attempted.setdefault(gkey, set()).add(m.pod.uid)
                    fw = self.framework_for_pod(m.pod)
                    if self._commit_group_member(fw, m, state, result):
                        committed += 1
                for gkey, uids in attempted.items():
                    self.queue.clear_group_members(gkey, uids)
                self.queue.done(qcgi.uid)
                self.metrics.podgroup_schedule_attempts.inc(
                    "scheduled" if committed else "unschedulable")
                return
            # Empty tree (every leaf memberless): nothing was attempted, so
            # parking it unschedulable with an EMPTY plugin set would make
            # every cluster event "relevant" — a busy reactivate/re-park
            # loop until members arrive. Drop the entity instead; the member
            # buffers re-activate the tree when members show up. Member adds
            # that arrived WHILE this entity was in flight were swallowed by
            # the in-flight gate (_maybe_activate_composite), so re-check
            # activation once the slot clears.
            self.queue.done(qcgi.uid)
            self.queue._maybe_activate_composite(qcgi.cpg)
            return

        # LIFO rollback across the whole tree (revertFns :50-75 applied at
        # composite scope: parents propagate failure to children).
        for m, _st, _r in reversed(placed):
            self.snapshot.forget_pod(m.pod)
            m.pod.node_name = ""
        self.failures += 1
        qcgi.timestamp = self.now()
        self.queue.add_unschedulable_if_not_present(qcgi)
        self.queue.done(qcgi.uid)
        self.metrics.podgroup_schedule_attempts.inc("unschedulable")

    def _schedule_group_with_placements(
        self, fw: Framework, qgpi: QueuedPodGroupInfo,
        members: List[QueuedPodInfo],
    ) -> bool:
        """podGroupSchedulingPlacementAlgorithm (schedule_one_podgroup.go:971)
        + findBestPodGroupPlacement (:1173). Owns the whole cycle: commits
        the best feasible placement, or parks the group unschedulable ("0/N
        placements are available")."""
        from .framework import Placement, PlacementProgress, PodGroupAssignments

        group = qgpi.group
        pg_state = CycleState()
        parent = Placement("", [ni.name for ni in self.snapshot.node_info_list])
        placements, st = fw.run_placement_generate_plugins(
            pg_state, group, members, parent)
        if not st.is_success() or not placements:
            self._fail_pod_group(fw, qgpi, members, None)
            return False
        self.metrics.generated_placements.observe(len(placements))
        self.metrics.generated_placements_total.inc(value=len(placements))

        start_save = self.next_start_node_index
        candidates = self._evaluate_placements(
            fw, pg_state, group, members, placements, start_save)
        self.next_start_node_index = start_save

        if not candidates:
            # "0/N placements are available" (schedule_one_podgroup.go:1038)
            self._fail_pod_group(fw, qgpi, members, None)
            return False

        totals = fw.run_placement_score_plugins(
            pg_state, group, [pga for _, _, pga in candidates])
        best_i = max(range(len(totals)), key=lambda i: (totals[i], -i))
        best_placement, assignment, _pga = candidates[best_i]

        # Commit the winning placement's assignments: assume into the cache
        # and run each member's binding cycle; members the placement could
        # not fit are requeued individually (submitPodGroupAlgorithmResult).
        # Each member keeps the CycleState from the WINNING simulation —
        # stateful Reserve/PreBind plugins (VolumeBinding, DynamicResources)
        # wrote their PreFilter/Filter data there
        # (schedule_one_podgroup.go algorithmResult.GetCycleState →
        # submitPodGroupAlgorithmResult).
        committed = 0
        attempted_uids = set()
        for m in members:
            attempted_uids.add(m.pod.uid)
            entry = assignment.get(m.pod.uid)
            if entry is None:
                self.handle_scheduling_failure(
                    fw, m, Status.unschedulable(
                        f"did not fit placement {best_placement.name!r}"), None)
                continue
            node, m_state = entry
            m.pod.node_name = node
            self.cache.assume_pod(m.pod, m.pod_info)
            if self._commit_group_member(fw, m, m_state,
                                         ScheduleResult(suggested_host=node)):
                committed += 1
        group_key = (group.namespace, group.name)
        self.queue.clear_group_members(group_key, attempted_uids)
        self.queue.done(qgpi.uid)
        self.metrics.podgroup_schedule_attempts.inc(
            "scheduled" if committed else "unschedulable")
        return True

    def _evaluate_placements(self, fw: Framework, pg_state: CycleState,
                             group, members: List[QueuedPodInfo],
                             placements, start_index: int) -> List[tuple]:
        """Evaluate every candidate placement; returns the feasible
        candidates as (placement, assignment, PodGroupAssignments) tuples.
        The host loop simulates placements one by one; TPUScheduler
        overrides this with one stacked kernel evaluation of ALL candidates
        (ops/kernel.py schedule_placements)."""
        from .framework import PodGroupAssignments

        _t_pe = time.perf_counter()
        self.metrics.placement_evaluations.inc("host", value=len(placements))
        candidates: List[tuple] = []
        for placement in placements:
            assignment = self._evaluate_placement(
                fw, pg_state, group, members, placement, start_index)
            if assignment is not None:
                pga = PodGroupAssignments(
                    placement,
                    proposed=[(m.pod, assignment[m.pod.uid][0]) for m in members
                              if m.pod.uid in assignment],
                    nodes=[self.snapshot.get(n) for n in placement.node_names])
                candidates.append((placement, assignment, pga))
        self.metrics.placement_evaluation_duration.observe(
            time.perf_counter() - _t_pe)
        return candidates

    def _evaluate_placement(self, fw: Framework, pg_state: CycleState,
                            group, members: List[QueuedPodInfo], placement,
                            start_index: int) -> Optional[Dict[str, tuple]]:
        """Simulate the group against one candidate placement under a
        snapshot placement session. Returns {pod uid: (node, CycleState)}
        when the PlacementFeasible gate passes, else None — the per-member
        CycleState carries stateful-plugin simulation data into the commit
        (schedule_one_podgroup.go initPodSchedulingContext). The snapshot is
        ALWAYS restored (placement and pod assumptions), even on plugin
        exceptions.

        Simulation spec (shared with the device evaluator,
        ops/kernel.py schedule_placements): each simulation evaluates its
        WHOLE candidate — no adaptive truncation — from rotation origin 0.
        Placements are domain-sized (a zone/rack), so full evaluation is the
        point, and a fixed origin makes host and device placement
        evaluation bit-identical."""
        from .framework import PlacementProgress

        self.snapshot.assume_placement(placement.node_names)
        self.next_start_node_index = 0
        pct_save = self.percentage_of_nodes_to_score
        self.percentage_of_nodes_to_score = 100  # evaluate the full candidate
        placed: List[Tuple[QueuedPodInfo, CycleState]] = []
        failed = 0
        try:
            for m in members:
                m_state = CycleState()
                try:
                    result = self.schedule_pod(fw, m_state, m.pod)
                except FitError:
                    failed += 1
                    continue
                m.pod.node_name = result.suggested_host
                self.snapshot.assume_pod(m.pod)
                placed.append((m, m_state))
            progress = PlacementProgress(len(placed), failed, len(members))
            feasible = placed and fw.run_placement_feasible_plugins(
                pg_state, group, progress).is_success()
            assignment = {m.pod.uid: (m.pod.node_name, st) for m, st in placed}
        finally:
            # LIFO revert: the snapshot returns to the placement view, then
            # the full view (snapshot.go revertFns + ForgetPlacement).
            for m, _st in reversed(placed):
                self.snapshot.forget_pod(m.pod)
                m.pod.node_name = ""
            self.snapshot.forget_placement()
            self.percentage_of_nodes_to_score = pct_save
        return assignment if feasible else None

    def group_feasible(self, group, members: List[QueuedPodInfo]) -> bool:
        """Would this group schedule right now, under the same algorithm a
        real cycle would use? The feasibility probe behind pod-group
        preemption (podgrouppreemption.go podGroupSchedulingFunc): a
        topology-constrained group must fit some CANDIDATE PLACEMENT, not
        just the unconstrained cluster."""
        from .framework import Placement

        members = [m for m in members]
        if not members:
            return False
        fw = self.framework_for_pod(members[0].pod)
        start_save = self.next_start_node_index
        pg_state = CycleState()
        if fw.placement_generate_plugins and getattr(group, "topology_keys", ()):
            parent = Placement("", [ni.name for ni in self.snapshot.node_info_list])
            placements, st = fw.run_placement_generate_plugins(
                pg_state, group, members, parent)
            if not st.is_success():
                return False
            try:
                return any(
                    self._evaluate_placement(fw, pg_state, group, members,
                                             placement, start_save) is not None
                    for placement in placements)
            finally:
                self.next_start_node_index = start_save
        # Unconstrained default algorithm: all members must fit.
        placed: List[QueuedPodInfo] = []
        ok = True
        try:
            for m in members:
                try:
                    result = self.schedule_pod(fw, CycleState(), m.pod)
                except FitError:
                    ok = False
                    break
                m.pod.node_name = result.suggested_host
                self.snapshot.assume_pod(m.pod)
                placed.append(m)
        finally:
            for m in reversed(placed):
                self.snapshot.forget_pod(m.pod)
                m.pod.node_name = ""
            self.next_start_node_index = start_save
        return ok

    def _commit_group_member(self, fw: Framework, m: QueuedPodInfo,
                             state: CycleState, result: ScheduleResult) -> bool:
        """Reserve → permit → binding cycle for one group member whose pod is
        already assumed into the cache with node_name set. Returns True when
        the member is committed (bound or parked at Permit WAIT)."""
        node = result.suggested_host
        st = fw.run_reserve_plugins_reserve(state, m.pod, node)
        if st.is_success():
            st = fw.run_permit_plugins(state, m.pod, node)
        if st.code == WAIT:
            self.park_waiting_pod(fw, state, m, result)
            return True
        if not st.is_success():
            fw.run_reserve_plugins_unreserve(state, m.pod, node)
            self.cache.forget_pod(m.pod)
            m.pod.node_name = ""
            self.handle_scheduling_failure(fw, m, st, None)
            return False
        return self.run_binding_cycle(fw, state, m, result)

    def _fail_pod_group(self, fw: Framework, qgpi: QueuedPodGroupInfo,
                        members: List[QueuedPodInfo], diagnosis) -> None:
        """Group-unschedulable tail shared by the placement and default
        algorithms: PodGroupPostFilter hook (framework.go:1212 — a chance to
        make room via pod-group preemption), then park the group."""
        if fw.pod_group_post_filter_plugins:
            _, post_st = fw.run_pod_group_post_filter_plugins(
                CycleState(), qgpi.group, members, diagnosis)
            if post_st.is_success():
                qgpi.timestamp = self.now()
                self.queue.add_unschedulable_if_not_present(qgpi)
                self.queue.done(qgpi.uid)
                self.metrics.podgroup_schedule_attempts.inc("post_filter")
                return
        self.failures += 1
        qgpi.timestamp = self.now()
        self.queue.add_unschedulable_if_not_present(qgpi)
        self.queue.done(qgpi.uid)
        self.metrics.podgroup_schedule_attempts.inc("unschedulable")

    # -- schedulePod (schedule_one.go:572) ---------------------------------

    def schedule_pod(self, fw: Framework, state: CycleState, pod: Pod) -> ScheduleResult:
        if self.snapshot.num_nodes() == 0:
            raise FitError(pod, 0, Diagnosis(pre_filter_msg="no nodes available"))
        feasible, diagnosis = self.find_nodes_that_fit_pod(fw, state, pod)
        if not feasible:
            raise FitError(pod, self.snapshot.num_nodes(), diagnosis)
        if len(feasible) == 1:
            return ScheduleResult(
                suggested_host=feasible[0].name,
                evaluated_nodes=1 + len(diagnosis.node_to_status),
                feasible_nodes=1,
            )
        priority_list = self.prioritize_nodes(fw, state, pod, feasible)
        host = self.select_host(priority_list)
        return ScheduleResult(
            suggested_host=host,
            evaluated_nodes=len(feasible) + len(diagnosis.node_to_status),
            feasible_nodes=len(feasible),
        )

    def _observe_point(self, point: str, t0: float, ok: bool = True) -> float:
        """framework_extension_point_duration_seconds observation; returns a
        fresh perf_counter for chaining (one call per point per cycle —
        Histogram.observe is O(1))."""
        t1 = time.perf_counter()
        self.metrics.framework_extension_point_duration.observe(
            t1 - t0, point, "Success" if ok else "Error", "")
        return t1

    def find_nodes_that_fit_pod(
        self, fw: Framework, state: CycleState, pod: Pod
    ) -> Tuple[List[NodeInfo], Diagnosis]:
        diagnosis = Diagnosis()
        all_nodes = self.snapshot.node_info_list
        _t = time.perf_counter()
        pre_res, st = fw.run_pre_filter_plugins(state, pod, all_nodes)
        _t = self._observe_point("PreFilter", _t, st.is_success())
        if not st.is_success():
            if st.is_rejected():
                diagnosis.pre_filter_msg = st.message()
                diagnosis.unschedulable_plugins.add(st.plugin)
                return [], diagnosis
            raise RuntimeError(f"prefilter failed: {st.message()}")

        # Nominated-node fast path (schedule_one.go:722): if a previous
        # preemption nominated a node, evaluate it first.
        if pod.nominated_node_name:
            ni = self.snapshot.get(pod.nominated_node_name)
            if ni is not None:
                st = fw.run_filter_plugins_with_nominated_pods(
                    state, pod, ni, self.queue.nominator
                )
                if st.is_success():
                    return [ni], diagnosis

        nodes = all_nodes
        if pre_res is not None and not pre_res.all_nodes():
            if len(pre_res.node_names) == 1:
                # The daemonset shape narrows 15k nodes to ONE per pod: a map
                # lookup, not an O(all nodes) scan per pod.
                ni = self.snapshot.get(next(iter(pre_res.node_names)))
                nodes = [ni] if ni is not None else []
            else:
                # Preserve snapshot order (rotation parity over the narrowed
                # list, schedule_one.go:630).
                nodes = [ni for ni in all_nodes if ni.name in pre_res.node_names]
        feasible = self.find_nodes_that_pass_filters(fw, state, pod, diagnosis, nodes)
        self._observe_point("Filter", _t)
        # PluginEvaluationTotal at cycle granularity (one evaluation of each
        # enabled plugin per scheduling cycle; the reference's per-node inc
        # would cost a dict write per node per plugin on the hot loop).
        pet = self.metrics.plugin_evaluation_total
        for p in fw.pre_filter_plugins:
            pet.inc(p.name, "PreFilter", fw.profile_name)
        for p in fw.filter_plugins:
            if p.name not in state.skip_filter_plugins:
                pet.inc(p.name, "Filter", fw.profile_name)
        if feasible and self.extenders:
            from .extender import run_extender_filters
            feasible, err = run_extender_filters(self.extenders, pod, feasible, diagnosis)
            if err is not None:
                raise RuntimeError(f"extender filter failed: {err.message()}")
        return feasible, diagnosis

    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        return num_feasible_nodes_to_find(num_all_nodes, self.percentage_of_nodes_to_score)

    def find_nodes_that_pass_filters(
        self,
        fw: Framework,
        state: CycleState,
        pod: Pod,
        diagnosis: Diagnosis,
        nodes: Sequence[NodeInfo],
    ) -> List[NodeInfo]:
        num_nodes = len(nodes)
        to_find = self.num_feasible_nodes_to_find(num_nodes)
        feasible: List[NodeInfo] = []
        start = self.next_start_node_index % max(1, num_nodes)
        evaluated = 0
        for i in range(num_nodes):
            ni = nodes[(start + i) % num_nodes]
            evaluated += 1
            st = fw.run_filter_plugins_with_nominated_pods(state, pod, ni, self.queue.nominator)
            if st.is_success():
                feasible.append(ni)
                if len(feasible) >= to_find:
                    break
            else:
                diagnosis.node_to_status[ni.name] = st
                if st.plugin:
                    diagnosis.unschedulable_plugins.add(st.plugin)
        self.next_start_node_index = (start + evaluated) % max(1, num_nodes)
        return feasible

    def prioritize_nodes(
        self, fw: Framework, state: CycleState, pod: Pod, nodes: Sequence[NodeInfo]
    ) -> List[NodeScore]:
        _t = time.perf_counter()
        st = fw.run_pre_score_plugins(state, pod, nodes)
        _t = self._observe_point("PreScore", _t, st.is_success())
        if not st.is_success():
            raise RuntimeError(f"prescore failed: {st.message()}")
        plugin_scores = fw.run_score_plugins(state, pod, nodes)
        self._observe_point("Score", _t)
        for p, _w in fw.score_plugins:
            self.metrics.plugin_evaluation_total.inc(
                p.name, "Score", fw.profile_name)
        total = [NodeScore(ni.name, 0) for ni in nodes]
        for scores in plugin_scores.values():
            for i, ns in enumerate(scores):
                total[i].score += ns.score
        if self.extenders:
            from .extender import run_extender_prioritize
            run_extender_prioritize(self.extenders, pod, nodes, total)
        return total

    def select_host(self, node_scores: List[NodeScore]) -> str:
        """Reservoir-sample among max-score nodes (schedule_one.go selectHost),
        seeded RNG so runs are reproducible; first-max when
        deterministic_ties is set (device-parity mode)."""
        best = node_scores[0]
        cnt = 1
        for ns in node_scores[1:]:
            if ns.score > best.score:
                best = ns
                cnt = 1
            elif ns.score == best.score and not self.deterministic_ties:
                cnt += 1
                if self.rng.random() < 1.0 / cnt:
                    best = ns
        return best.name

    # -- binding cycle (schedule_one.go:141 runBindingCycle) ---------------

    def run_binding_cycle(
        self, fw: Framework, state: CycleState, qpi: QueuedPodInfo, result: ScheduleResult
    ) -> bool:
        """Returns True iff the pod was bound (False: unwound + requeued)."""
        pod = qpi.pod
        node_name = result.suggested_host
        _t = time.perf_counter()
        if fw.pre_bind_plugins:
            # PreBindPreFlight (runtime/framework.go:1875): plugins that
            # declare no work for this pod are skipped; all-skip bypasses
            # the PreBind phase.
            st = fw.run_pre_bind_pre_flight(state, pod, node_name)
            if not st.is_success() and not st.is_skip():
                self._unwind_binding(fw, state, qpi, node_name, st)
                return False
            if not st.is_skip():
                st = fw.run_pre_bind_plugins(state, pod, node_name)
                _t = self._observe_point("PreBind", _t, st.is_success())
                if not st.is_success():
                    self._unwind_binding(fw, state, qpi, node_name, st)
                    return False
        # Extender bind delegation (schedule_one.go:1100 bind: an interested
        # extender with a bind verb binds instead of the bind plugins).
        bind_ext = next(
            (e for e in self.extenders
             if e.supports_bind() and e.is_interested(pod)), None) \
            if self.extenders else None
        if bind_ext is not None:
            err = bind_ext.bind(pod, node_name)
            st = Status() if err is None else Status.error(err)
        else:
            st = fw.run_bind_plugins(state, pod, node_name)
        self._observe_point("Bind", _t, st.is_success())
        if not st.is_success():
            self._unwind_binding(fw, state, qpi, node_name, st)
            return False
        self.cache.finish_binding(pod)
        self.queue.nominator.delete_nominated_pod(pod)
        self.scheduled += 1
        self.observe_bound(qpi, node_name)
        self.recorder.eventf(
            pod.namespace + "/" + pod.name, "Normal", "Scheduled",
            ("Successfully assigned %s/%s to %s",
             (pod.namespace, pod.name, node_name)))
        fw.run_post_bind_plugins(state, pod, node_name)
        return True

    def _unwind_binding(self, fw, state, qpi: QueuedPodInfo, node_name: str, st: Status) -> None:
        """handleBindingCycleError (schedule_one.go:507): unreserve, forget,
        flush an AssignedPodDelete-equivalent event, requeue. A tagged bind
        CONFLICT (409: another scheduler won the shared state) skips the
        unschedulable pool and goes straight to the backoffQ — by the time
        the backoff elapses the watch feed has delivered the winning commit
        and the retry either skips the pod (already placed) or re-plans
        against the updated node state."""
        pod = qpi.pod
        self.state_unwinds += 1
        fw.run_reserve_plugins_unreserve(state, pod, node_name)
        self.cache.forget_pod(pod)
        pod.node_name = ""
        self.queue.move_all_to_active_or_backoff(
            EVENT_ASSIGNED_POD_DELETE, pod, None)
        if getattr(st, "conflict", False):
            self._note_bind_conflict(st.message(), pod, node_name)
            self.conflict_requeues += 1
            self.queue.requeue_conflict(qpi)
            return
        if getattr(st, "shed", False):
            # Flow-control shed (429): the write plane rejected before any
            # state changed. Same routing as a conflict — straight to the
            # backoffQ with the ORIGINAL enqueued_at preserved (qpi is the
            # popped info object), so scheduler_e2e_scheduling_duration
            # spans the shed-and-retried pod too. 100%-sampled span: shed
            # pods are exactly the ones worth tracing under overload.
            self._note_bind_shed(pod, node_name)
            self.queue.requeue_conflict(qpi)
            return
        self.handle_scheduling_failure(fw, qpi, st, None)

    def _note_bind_shed(self, pod: Pod, node: str = "") -> None:
        """One shed bind's accounting: counter + a FORCED bind.shed span
        (overload forensics — the trace analyzer's overload timeline needs
        every shed, not a sample)."""
        self.shed_requeues += 1
        self.tracer.record(
            "bind.shed", self.tracer.context_for(pod.uid, force=True),
            node=node, pod=f"{pod.namespace}/{pod.name}")

    def _note_bind_conflict(self, message: str, pod: Optional[Pod] = None,
                            node: str = "") -> None:
        reason = ("capacity" if "OutOfCapacity" in message
                  else "already_bound" if "AlreadyBound" in message
                  else "conflict")
        self.bind_conflicts += 1
        self.metrics.bind_conflict_total.inc(reason)
        if pod is not None:
            # Conflict paths sample at 100% (forced context): the trace
            # analyzer's cross-shard conflict timeline is built from these.
            self.tracer.record(
                "bind.conflict", self.tracer.context_for(pod.uid, force=True),
                reason=reason, node=node,
                pod=f"{pod.namespace}/{pod.name}")

    # -- span helpers (core/spans.py; docs/OBSERVABILITY.md) ----------------

    def record_queue_wait(self, qpi, ctx) -> None:
        """Retroactive queue.admission event + queue.wait span, recorded at
        pop time (no hot add-path cost). Guarded against double recording
        when a device-popped pod falls back to the host cycle."""
        tr = self.tracer
        if not tr.wants(ctx) or getattr(qpi, "_qwait_recorded", False):
            return
        qpi._qwait_recorded = True
        start = getattr(qpi, "enqueued_at", None)
        wait = max(0.0, self.now() - start) if start is not None else 0.0
        wall_pop = time.time()
        tr.record("queue.admission", ctx, start=wall_pop - wait)
        tr.record("queue.wait", ctx, wait, start=wall_pop - wait,
                  attempts=qpi.attempts)

    def observe_bound(self, qpi, node_name: str) -> None:
        """Every successful bind feeds scheduler_e2e_scheduling_duration_
        seconds (queue admission -> bound, ALL pods — the histogram is
        latency truth, sampling only thins the span ring) and closes the
        sampled pod's trace with its pod.e2e span."""
        start = getattr(qpi, "enqueued_at", None)
        if start is None:
            return
        e2e = max(0.0, self.now() - start)
        self.metrics.e2e_scheduling_duration.observe(e2e)
        tr = self.tracer
        ctx = tr.context_for(qpi.pod.uid)
        if tr.wants(ctx):
            tr.record("pod.e2e", ctx, e2e, node=node_name,
                      attempts=qpi.attempts)

    # -- failure (schedule_one.go:1152 handleSchedulingFailure) ------------

    # -- waiting pods (Permit WAIT) ----------------------------------------

    def allow_waiting_pod(self, uid: str) -> bool:
        """A Permit plugin allowed a parked pod: run its binding cycle
        (waitingPod.Allow → WaitOnPermit unblocks)."""
        entry = self.waiting_pods.pop(uid, None)
        if entry is None:
            return False
        fw, state, qpi, result, deadline = entry
        self.metrics.permit_wait_duration.observe(
            self.now() - (deadline - self.permit_wait_timeout), "allowed")
        self.run_binding_cycle(fw, state, qpi, result)
        return True

    def reject_waiting_pod(self, uid: str, reason: str = "rejected") -> bool:
        entry = self.waiting_pods.pop(uid, None)
        if entry is None:
            return False
        fw, state, qpi, result, deadline = entry
        self.metrics.permit_wait_duration.observe(
            self.now() - (deadline - self.permit_wait_timeout), "rejected")
        self.state_unwinds += 1
        fw.run_reserve_plugins_unreserve(state, qpi.pod, result.suggested_host)
        self.cache.forget_pod(qpi.pod)
        qpi.pod.node_name = ""
        self.handle_scheduling_failure(fw, qpi, Status.unschedulable(reason), None)
        return True

    def park_waiting_pod(self, fw, state, qpi, result) -> None:
        """Park a WAITing pod and arm the expiry timer (WaitOnPermit)."""
        deadline = self.now() + self.permit_wait_timeout
        self.waiting_pods[qpi.pod.uid] = (fw, state, qpi, result, deadline)
        if deadline < self._next_wait_deadline:
            self._next_wait_deadline = deadline

    def _rearm_wait_deadline(self) -> None:
        self._next_wait_deadline = min(
            (e[4] for e in self.waiting_pods.values()), default=float("inf"))

    def flush_expired_waiters(self) -> int:
        now = self.now()
        expired = [uid for uid, e in self.waiting_pods.items() if e[4] <= now]
        for uid in expired:
            self.reject_waiting_pod(uid, "permit wait timed out")
        self._rearm_wait_deadline()
        return len(expired)

    def _queued_entity_counts(self) -> Dict[tuple, float]:
        """queued_entities gauge callback: queued entities by kind."""
        from .queue import QueuedCompositeGroupInfo, QueuedPodGroupInfo
        counts = {"pod": 0, "podgroup": 0, "composite": 0}
        try:
            return self._queued_entity_counts_unsafe(counts)
        except RuntimeError:
            # /metrics is scraped from the HTTP thread while the scheduling
            # loop mutates the queues; a torn iteration yields a stale scrape
            # rather than a 500.
            return {(k,): float(v) for k, v in counts.items()}

    def _queued_entity_counts_unsafe(self, counts) -> Dict[tuple, float]:
        from .queue import QueuedCompositeGroupInfo, QueuedPodGroupInfo
        for q in (self.queue.active_q, self.queue.backoff_q):
            for ent in q.items():
                if isinstance(ent, QueuedCompositeGroupInfo):
                    counts["composite"] += 1
                elif isinstance(ent, QueuedPodGroupInfo):
                    counts["podgroup"] += 1
                else:
                    counts["pod"] += 1
        for ent in self.queue.unschedulable.values():
            if isinstance(ent, QueuedCompositeGroupInfo):
                counts["composite"] += 1
            elif isinstance(ent, QueuedPodGroupInfo):
                counts["podgroup"] += 1
            else:
                counts["pod"] += 1
        return {(k,): float(v) for k, v in counts.items()}

    def _unschedulable_by_plugin(self) -> Dict[tuple, float]:
        """unschedulable_pods gauge callback: parked pods by rejecting
        plugin (metrics.go UnschedulablePods)."""
        counts: Dict[str, int] = {}
        try:
            for ent in list(self.queue.unschedulable.values()):
                plugins = ent.unschedulable_plugins or {""}
                for p in plugins:
                    counts[p] = counts.get(p, 0) + 1
        except RuntimeError:
            pass  # concurrent scrape during queue mutation: stale is fine
        return {(k,): float(v) for k, v in counts.items()}

    def update_pending_metrics(self) -> None:
        """Refresh the pending_pods gauges (metrics.go pending_pods)."""
        active, backoff, unsched = self.queue.pending_counts()
        gated = sum(1 for q in self.queue.unschedulable.values() if q.gated)
        self.metrics.pending_pods.set(active, "active")
        self.metrics.pending_pods.set(backoff, "backoff")
        self.metrics.pending_pods.set(unsched - gated, "unschedulable")
        self.metrics.pending_pods.set(gated, "gated")

    def expose_metrics(self) -> str:
        """/metrics (app/server.go:376)."""
        self.update_pending_metrics()
        out = self.metrics.expose()
        # Step-accounting counters (plan/device/host split, device-vs-host
        # path mix, conflict/unwind tallies): in-process harnesses read
        # these attributes directly, but a shard-plane scheduler is only
        # reachable over HTTP — the split must ride /metrics for a sharded
        # run to be diagnosable from outside (docs/SHARDING.md
        # observability; bench.py --shards detail).
        extra = []
        for name, val in (
                ("scheduler_plan_build_seconds_total",
                 getattr(self, "plan_build_s", 0.0)),
                ("scheduler_device_wait_seconds_total",
                 getattr(self, "device_wait_s", 0.0)),
                ("scheduler_host_commit_seconds_total",
                 getattr(self, "host_commit_s", 0.0)),
                ("scheduler_host_path_pods_total",
                 getattr(self, "host_path_pods", 0)),
                ("scheduler_device_scheduled_pods_total",
                 getattr(self, "device_scheduled", 0)),
                ("scheduler_device_batches_total",
                 getattr(self, "device_batches", 0)),
                ("scheduler_state_unwinds_total", self.state_unwinds),
                ("scheduler_conflict_requeues_total", self.conflict_requeues),
                ("scheduler_eviction_requeues_total", self.eviction_requeues),
                ("scheduler_attempts_total", self.attempts)):
            extra.append(f"# TYPE {name} counter")
            extra.append(f"{name} {float(val)}")
        return out + "\n".join(extra) + "\n"

    def handle_scheduling_failure(
        self, fw: Framework, qpi: QueuedPodInfo, status: Status, diagnosis: Optional[Diagnosis]
    ) -> None:
        self.failures += 1
        if diagnosis is not None:
            qpi.unschedulable_plugins |= diagnosis.unschedulable_plugins
            qpi.pending_plugins |= diagnosis.pending_plugins
        if status.code == UNSCHEDULABLE_AND_UNRESOLVABLE and not qpi.unschedulable_plugins:
            qpi.unschedulable_plugins.add(status.plugin or "unknown")
        pod = qpi.pod
        self.recorder.eventf(
            f"{pod.namespace}/{pod.name}", "Warning", "FailedScheduling",
            status.message())
        self.queue.add_unschedulable_if_not_present(qpi)
