"""Zone-interleaved node ordering (backend/cache/node_tree.go).

Nodes are bucketed by zone (topology.kubernetes.io/zone + region) and listed
round-robin across zones so that naive index-order iteration spreads load.
"""

from __future__ import annotations

from typing import Dict, List

from ..api.types import LABEL_REGION, LABEL_ZONE, Node


def _zone_key(node: Node) -> str:
    region = node.labels.get(LABEL_REGION, "")
    zone = node.labels.get(LABEL_ZONE, "")
    return f"{region}:\x00:{zone}"


class NodeTree:
    def __init__(self):
        self.tree: Dict[str, List[str]] = {}
        self.zones: List[str] = []
        self.num_nodes = 0

    def add_node(self, node: Node) -> None:
        zone = _zone_key(node)
        if zone not in self.tree:
            self.tree[zone] = []
            self.zones.append(zone)
        if node.name not in self.tree[zone]:
            self.tree[zone].append(node.name)
            self.num_nodes += 1

    def remove_node(self, node: Node) -> None:
        zone = _zone_key(node)
        names = self.tree.get(zone)
        if names and node.name in names:
            names.remove(node.name)
            self.num_nodes -= 1
            if not names:
                del self.tree[zone]
                self.zones.remove(zone)

    def list(self) -> List[str]:
        """Round-robin across zones (node_tree.go list())."""
        out: List[str] = []
        idx = [0] * len(self.zones)
        remaining = self.num_nodes
        z = 0
        while remaining > 0 and self.zones:
            zone = self.zones[z % len(self.zones)]
            nodes = self.tree[zone]
            i = idx[z % len(self.zones)]
            if i < len(nodes):
                out.append(nodes[i])
                idx[z % len(self.zones)] += 1
                remaining -= 1
            z += 1
            if z > 10 * (self.num_nodes + len(self.zones) + 1):
                break
        return out
