"""Zone-interleaved node ordering (backend/cache/node_tree.go).

Nodes are bucketed by zone (topology.kubernetes.io/zone + region) and listed
round-robin across zones so that naive index-order iteration spreads load.
"""

from __future__ import annotations

from typing import Dict, List

from ..api.types import LABEL_REGION, LABEL_ZONE, Node


def _zone_key(node: Node) -> str:
    region = node.labels.get(LABEL_REGION, "")
    zone = node.labels.get(LABEL_ZONE, "")
    return f"{region}:\x00:{zone}"


class NodeTree:
    def __init__(self):
        self.tree: Dict[str, List[str]] = {}
        self.zones: List[str] = []
        self.node_zone: Dict[str, str] = {}
        self.num_nodes = 0

    def add_node(self, node: Node) -> bool:
        """Add or re-bucket a node. Returns True when tree structure changed
        (new node, or an existing node moved zones — node_tree.go updateNode)."""
        zone = _zone_key(node)
        old_zone = self.node_zone.get(node.name)
        if old_zone == zone:
            return False
        if old_zone is not None:
            self._remove_from_zone(node.name, old_zone)
        if zone not in self.tree:
            self.tree[zone] = []
            self.zones.append(zone)
        self.tree[zone].append(node.name)
        self.node_zone[node.name] = zone
        self.num_nodes += 1
        return True

    def _remove_from_zone(self, name: str, zone: str) -> None:
        names = self.tree.get(zone)
        if names and name in names:
            names.remove(name)
            self.num_nodes -= 1
            if not names:
                del self.tree[zone]
                self.zones.remove(zone)
        self.node_zone.pop(name, None)

    def remove_node(self, node: Node) -> None:
        zone = self.node_zone.get(node.name, _zone_key(node))
        self._remove_from_zone(node.name, zone)

    def list(self) -> List[str]:
        """Round-robin across zones (node_tree.go list())."""
        out: List[str] = []
        idx = [0] * len(self.zones)
        remaining = self.num_nodes
        z = 0
        while remaining > 0 and self.zones:
            zone = self.zones[z % len(self.zones)]
            nodes = self.tree[zone]
            i = idx[z % len(self.zones)]
            if i < len(nodes):
                out.append(nodes[i])
                idx[z % len(self.zones)] += 1
                remaining -= 1
            z += 1
            if z > 10 * (self.num_nodes + len(self.zones) + 1):
                break
        return out
