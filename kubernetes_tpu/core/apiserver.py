"""A minimal REST + watch apiserver over the FakeClientset store, and the
HTTP client/reflector that lets a scheduler run against it across a REAL
process boundary (no shared objects — JSON on the wire).

Re-expresses the scheduler-relevant slice of the reference's L2/L3 stack:

- apiserver REST surface (staging/src/k8s.io/apiserver collapsed to the
  verbs the scheduler uses): create/delete pods and nodes, the binding and
  status subresources, and a `?watch=true` chunked event stream per
  resource. A watch opens with resourceVersion=0 semantics: the server
  streams ADDED for every existing object, then a SYNC marker, then live
  events — so nothing can fall between a separate LIST and the watch
  registration.
- client-go's reflector/informer seam (tools/cache/reflector.go:470
  ListAndWatch → shared_informer.go:841 processLoop): HTTPClientset
  consumes the stream on its own thread, maintains the informer's local
  object cache, and fans events into the scheduler's registered handlers —
  which the scheduler's off-thread inbox (core/scheduler.py _threaded)
  replays on the scheduling loop. Handler registration replays the cache
  under the dispatch lock, so attach-time replay cannot race live events.

The JSON codec covers the full scheduling-relevant pod/node spec (requests,
tolerations, selectors, node+pod affinity, topology spread, gates, host
ports, PVC volumes, resource claims, nominations, deletion state); GVK /
admission stay out of scope (SURVEY §7). The etcd seam is re-expressed by
an optional durable store (`data_dir`, core/wal.py): every committed write
appends a WAL record, snapshots compact the log, and a restarted server
replays snapshot+WAL — recovering objects, rv counters, the boot epoch, and
the watch backlog, so clients resume (`RESUME`) instead of re-listing
across a ``kill -9``.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib import request as urlrequest

from ..api.labels import LabelSelector, Requirement
from ..api.resource import Resource
from ..api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from . import spans as _spans
from . import wire
from .clientset import FakeClientset
from .flowcontrol import FlowController
from .watchcache import (
    ShardFilter,
    WatchCache,
    encode_stream_item,
    mint_continue,
    parse_continue,
    pod_from_slim,
    shard_of_wire,
    slim_object,
    wire_key,
    wire_plain,
)


def _lease_clock() -> float:
    """Lease clock: one process-local monotonic source. Expiry is always
    computed server-side against this clock, so shard clients never compare
    wall clocks across processes."""
    return time.monotonic()


# ---------------------------------------------------------------------------
# JSON codec — full scheduling-relevant spec
# ---------------------------------------------------------------------------


def _req_to_wire(r: Requirement) -> dict:
    return {"key": r.key, "op": r.operator, "values": list(r.values)}


def _req_from_wire(d: dict) -> Requirement:
    return Requirement(d["key"], d["op"], tuple(d.get("values", ())))


def _sel_to_wire(s: Optional[LabelSelector]) -> Optional[dict]:
    if s is None:
        return None
    return {"matchLabels": dict(s.match_labels),
            "matchExpressions": [_req_to_wire(r) for r in s.match_expressions]}


def _sel_from_wire(d: Optional[dict]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector.of(
        d.get("matchLabels", {}),
        [_req_from_wire(r) for r in d.get("matchExpressions", ())])


def _nsel_to_wire(ns: Optional[NodeSelector]) -> Optional[list]:
    if ns is None:
        return None
    return [{"matchExpressions": [_req_to_wire(r) for r in t.match_expressions],
             "matchFields": [_req_to_wire(r) for r in t.match_fields]}
            for t in ns.terms]


def _nsel_from_wire(terms: Optional[list]) -> Optional[NodeSelector]:
    if terms is None:
        return None
    return NodeSelector(terms=tuple(
        NodeSelectorTerm(
            match_expressions=tuple(_req_from_wire(r)
                                    for r in t.get("matchExpressions", ())),
            match_fields=tuple(_req_from_wire(r)
                               for r in t.get("matchFields", ())))
        for t in terms))


def _pterm_to_wire(t: PodAffinityTerm) -> dict:
    return {"labelSelector": _sel_to_wire(t.label_selector),
            "namespaces": list(t.namespaces),
            "topologyKey": t.topology_key,
            "namespaceSelector": _sel_to_wire(t.namespace_selector)}


def _pterm_from_wire(d: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_sel_from_wire(d.get("labelSelector")),
        namespaces=tuple(d.get("namespaces", ())),
        topology_key=d.get("topologyKey", ""),
        namespace_selector=_sel_from_wire(d.get("namespaceSelector")))


def _affinity_to_wire(a: Optional[Affinity]) -> Optional[dict]:
    if a is None:
        return None
    out: dict = {}
    if a.node_affinity is not None:
        out["nodeAffinity"] = {
            "required": _nsel_to_wire(a.node_affinity.required),
            "preferred": [{"weight": p.weight,
                           "term": _nsel_to_wire(NodeSelector((p.preference,)))[0]}
                          for p in a.node_affinity.preferred],
        }
    for attr, key in (("pod_affinity", "podAffinity"),
                      ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(a, attr)
        if pa is not None:
            out[key] = {
                "required": [_pterm_to_wire(t) for t in pa.required],
                "preferred": [{"weight": w.weight,
                               "term": _pterm_to_wire(w.term)}
                              for w in pa.preferred],
            }
    return out or None


def _affinity_from_wire(d: Optional[dict]) -> Optional[Affinity]:
    if not d:
        return None
    na = None
    if "nodeAffinity" in d:
        nd = d["nodeAffinity"]
        na = NodeAffinity(
            required=_nsel_from_wire(nd.get("required")),
            preferred=tuple(
                PreferredSchedulingTerm(
                    weight=p["weight"],
                    preference=_nsel_from_wire([p["term"]]).terms[0])
                for p in nd.get("preferred", ())))

    def _pa(key, cls):
        if key not in d:
            return None
        pd = d[key]
        return cls(
            required=tuple(_pterm_from_wire(t) for t in pd.get("required", ())),
            preferred=tuple(
                WeightedPodAffinityTerm(weight=w["weight"],
                                        term=_pterm_from_wire(w["term"]))
                for w in pd.get("preferred", ())))

    return Affinity(node_affinity=na,
                    pod_affinity=_pa("podAffinity", PodAffinity),
                    pod_anti_affinity=_pa("podAntiAffinity", PodAntiAffinity))


def pod_to_wire(p: Pod) -> dict:
    req = p.resource_request()
    return {
        "name": p.name, "namespace": p.namespace, "uid": p.uid,
        "nodeName": p.node_name, "schedulerName": p.scheduler_name,
        "nominatedNodeName": p.nominated_node_name,
        "labels": dict(p.labels), "annotations": dict(p.annotations),
        "priority": p.priority, "podGroup": p.pod_group,
        "deletionTs": p.deletion_ts, "finalizers": list(p.finalizers),
        "requests": {"cpu": req.milli_cpu, "memory": req.memory,
                     "ephemeral": req.ephemeral_storage,
                     "scalar": dict(req.scalar_resources)},
        "hostPorts": [{"port": hp.host_port, "protocol": hp.protocol,
                       "hostIP": hp.host_ip}
                      for hp in p.host_ports()],
        "tolerations": [
            {"key": t.key, "operator": t.operator, "value": t.value,
             "effect": t.effect} for t in p.tolerations],
        "nodeSelector": dict(p.node_selector),
        "affinity": _affinity_to_wire(p.affinity),
        "topologySpread": [
            {"maxSkew": c.max_skew, "topologyKey": c.topology_key,
             "whenUnsatisfiable": c.when_unsatisfiable,
             "labelSelector": _sel_to_wire(c.label_selector),
             "minDomains": c.min_domains,
             "nodeAffinityPolicy": c.node_affinity_policy,
             "nodeTaintsPolicy": c.node_taints_policy}
            for c in p.topology_spread_constraints],
        "schedulingGates": list(p.scheduling_gates),
        "volumes": [{"name": v.name, "pvc": v.pvc_name} for v in p.volumes],
        "resourceClaims": list(getattr(p, "resource_claims", ()) or ()),
    }


def pod_from_wire(d: dict) -> Pod:
    req = Resource(milli_cpu=int(d["requests"]["cpu"]),
                   memory=int(d["requests"]["memory"]),
                   ephemeral_storage=int(d["requests"].get("ephemeral", 0)),
                   scalar_resources=dict(d["requests"].get("scalar", {})))
    ports = tuple(ContainerPort(host_port=int(hp["port"]),
                                protocol=hp.get("protocol", "TCP"),
                                host_ip=hp.get("hostIP", ""))
                  for hp in d.get("hostPorts", ()))
    p = Pod(
        name=d["name"], namespace=d.get("namespace", "default"),
        uid=d["uid"], node_name=d.get("nodeName", ""),
        scheduler_name=d.get("schedulerName", "default-scheduler"),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        priority=int(d.get("priority", 0)),
        containers=[Container(name="c0", requests=req, ports=ports)],
        tolerations=[Toleration(key=t["key"], operator=t["operator"],
                                value=t.get("value", ""),
                                effect=t.get("effect", ""))
                     for t in d.get("tolerations", ())],
        node_selector=dict(d.get("nodeSelector", {})),
        affinity=_affinity_from_wire(d.get("affinity")),
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=c["maxSkew"], topology_key=c["topologyKey"],
                when_unsatisfiable=c["whenUnsatisfiable"],
                label_selector=_sel_from_wire(c.get("labelSelector")),
                min_domains=c.get("minDomains"),
                node_affinity_policy=c.get("nodeAffinityPolicy", "Honor"),
                node_taints_policy=c.get("nodeTaintsPolicy", "Ignore"))
            for c in d.get("topologySpread", ())],
        scheduling_gates=list(d.get("schedulingGates", ())),
        volumes=[Volume(name=v["name"], pvc_name=v.get("pvc"))
                 for v in d.get("volumes", ())],
    )
    p.nominated_node_name = d.get("nominatedNodeName", "")
    p.deletion_ts = d.get("deletionTs")
    p.finalizers = list(d.get("finalizers", ()))
    p.pod_group = d.get("podGroup", "")
    claims = d.get("resourceClaims", ())
    if claims:
        p.resource_claims = list(claims)
    return p


def node_to_wire(n: Node) -> dict:
    return {
        "name": n.name, "uid": n.uid, "labels": dict(n.labels),
        "unschedulable": n.unschedulable,
        "allocatable": {"cpu": n.allocatable.milli_cpu,
                        "memory": n.allocatable.memory,
                        "ephemeral": n.allocatable.ephemeral_storage,
                        "pods": n.allocatable.allowed_pod_number,
                        "scalar": dict(n.allocatable.scalar_resources)},
        "taints": [{"key": t.key, "value": t.value, "effect": t.effect}
                   for t in n.taints],
        "declaredFeatures": dict(n.declared_features),
    }


def node_from_wire(d: dict) -> Node:
    from ..api.types import Taint
    alloc = Resource(milli_cpu=int(d["allocatable"]["cpu"]),
                     memory=int(d["allocatable"]["memory"]),
                     ephemeral_storage=int(d["allocatable"].get("ephemeral", 0)),
                     allowed_pod_number=int(d["allocatable"]["pods"]),
                     scalar_resources=dict(d["allocatable"].get("scalar", {})))
    n = Node(
        name=d["name"], uid=d["uid"], labels=dict(d.get("labels", {})),
        unschedulable=bool(d.get("unschedulable", False)),
        capacity=alloc.clone(), allocatable=alloc,
        taints=[Taint(key=t["key"], value=t.get("value", ""),
                      effect=t.get("effect", "NoSchedule"))
                for t in d.get("taints", ())],
    )
    n.declared_features = dict(d.get("declaredFeatures", {}))
    return n


def pod_group_to_wire(g) -> dict:
    """PodGroup / CompositePodGroup wire. One kind ("podgroups") carries
    both object classes — a `composite` flag picks the decode — because
    they share a handler channel everywhere else (the FakeClientset fans
    both through on_pod_group_event, handlers type-switch)."""
    from ..api.types import CompositePodGroup
    d = {"name": g.name, "namespace": g.namespace, "uid": g.uid,
         "priority": int(g.priority),
         "parentName": g.parent_name,
         "composite": isinstance(g, CompositePodGroup)}
    if not d["composite"]:
        d["minCount"] = int(g.min_count)
        d["labels"] = dict(g.labels)
        d["topologyKeys"] = list(g.topology_keys)
    return d


def pod_group_from_wire(d: dict):
    from ..api.types import CompositePodGroup, PodGroup
    if d.get("composite"):
        return CompositePodGroup(
            name=d["name"], namespace=d.get("namespace") or "default",
            uid=d.get("uid", ""), parent_name=d.get("parentName", ""),
            priority=int(d.get("priority", 0)))
    return PodGroup(
        name=d["name"], namespace=d.get("namespace") or "default",
        uid=d.get("uid", ""), min_count=int(d.get("minCount", 0)),
        priority=int(d.get("priority", 0)),
        labels=dict(d.get("labels", {})),
        topology_keys=tuple(d.get("topologyKeys", ())),
        parent_name=d.get("parentName", ""))


# Node-lifecycle plane (kubernetes_tpu/controllers/): the taint the
# controller PUTs on a silent node, and the annotation an evicted-then-
# recreated pod carries (stamped server-side in the eviction subresource,
# under the write lock) so the scheduler can count eviction requeues.
UNREACHABLE_TAINT = "node.kubernetes.io/unreachable"
EVICTED_ANNOTATION = "node-lifecycle.kubernetes.io/evicted"

# Workload-plane kinds (controllers/workload.py): server-owned wire-dict
# maps keyed "ns/name" — no store-dict twin, the HTTP verb is the only
# writer and the broadcast (WAL -> watch cache -> fanout) IS the commit.
# They ride every durability/replication surface the store kinds do: WAL
# records, apply_frame, snapshots, watch/list/paged-list.
WORKLOAD_KINDS = ("replicasets", "deployments", "pdbs")


# ---------------------------------------------------------------------------
# The apiserver
# ---------------------------------------------------------------------------


class _WatchStream:
    """One attached watch stream: its event queue plus the optional
    per-stream shard filter (``?watch=true&shard=i/n``). The filter runs
    on the fanout path (broadcast lock); the queue decouples the stream's
    socket from the write plane exactly as before."""

    __slots__ = ("q", "filter", "replay_rv", "replay_epoch", "replay_slim")

    def __init__(self, flt: Optional[ShardFilter] = None):
        self.q: "queue.Queue" = queue.Queue()
        self.filter = flt
        # Lazy-cursor attach replay (docs/SCALE.md): a non-resumable attach
        # no longer materializes the full ADDED replay into this queue —
        # the stream's consumer thread pages the watch-cache snapshot
        # itself (list_page) up to `replay_rv`, then emits SYNC and goes
        # live off the queue. None = resumed (or TOO_OLD'd) attach.
        # `replay_slim` freezes the slim decision AT ATTACH, in lockstep
        # with the filter prime that records the slimmed set: if
        # selector_refs drops to 0 only mid-replay, the replay must keep
        # serving fulls — slimming then would leave pods the later
        # selector-transition upgrade burst can't find in `_slimmed`.
        self.replay_rv: Optional[int] = None
        self.replay_epoch: Optional[str] = None
        self.replay_slim: bool = False


class _ShipStream:
    """One attached replication follower: its frame queue plus the ack
    bookkeeping `_await_shipped` reads. `sent_seq` is the highest frame seq
    whose bytes sendall() handed to the kernel — once there, a leader
    SIGKILL cannot lose them (the kernel flushes the buffer before FIN).
    `acked` drops a stream out of the ack quorum when it lags (a stalled
    follower must not convoy every acked write behind its backpressure).
    The queue is BOUNDED (the same window as the ship backlog): a
    connected-but-stalled follower must not make the leader accumulate
    the entire subsequent write history in memory — on overflow the
    stream is marked `dead`, detached, and the follower re-attaches
    (usually via 410 -> snapshot resync), mirroring the watch-backlog
    contract."""

    __slots__ = ("q", "sent_seq", "acked", "dead")

    def __init__(self, since: int, maxsize: int):
        self.q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.sent_seq = since
        self.acked = True
        self.dead = False


class APIServer:
    """REST + watch over an owned FakeClientset store.

    Watch streams support resourceVersion resume (the reference's
    watch-cache window): every event is stamped with a per-kind monotonic
    `rv` and retained in a bounded backlog. A client reconnecting with
    `?watch=true&resourceVersion=N` gets a RESUME marker plus a replay of
    every event it missed — no full re-list — when the window still covers
    N; otherwise (compaction, the 410 Gone analogue) it gets the usual full
    ADDED replay + SYNC and performs reflector Replace semantics.

    With ``data_dir`` set, the server is durable (core/wal.py): writes are
    WAL-logged before fanout, periodically compacted into a snapshot, and a
    restart recovers state + rv counters + epoch + backlog — the etcd3
    store seam (etcd3/store.go:284) collapsed to one process."""

    # Sentinel returned by upsert_lease when this replica is not the
    # leader (distinct from None = CAS loss / LeaseHeld): the HTTP layer
    # maps it to 421 NotLeader, and the check lives UNDER the write lock
    # so a racing demote() cannot let a lease write slip through.
    NOT_LEADER = object()

    def __init__(self, store: Optional[FakeClientset] = None,
                 backlog: int = 8192, data_dir: Optional[str] = None,
                 fsync: bool = False, snapshot_every: int = 2048):
        self.store = store or FakeClientset()
        self._watchers: Dict[str, List[_WatchStream]] = {
            "pods": [], "nodes": [], "podgroups": [],
            **{k: [] for k in WORKLOAD_KINDS}}
        self._lock = threading.Lock()
        # Shard-plane coordination (shard/leases.py): named lease records,
        # renewed through PUT /api/v1/leases/<name> with holder-CAS semantics
        # and SERVER-side clocks (expiry is computed here, so shard processes
        # never compare wall clocks). Ride the WAL like STATUS records.
        self.leases: Dict[str, dict] = {}
        # Omega-style optimistic commit validation: per-node committed usage,
        # maintained incrementally so the binding subresource can reject an
        # overcommitting bind in O(1) (409 OutOfCapacity → the losing
        # scheduler requeues through its backoffQ and re-plans against the
        # watch-fed truth).
        self._usage: Dict[str, dict] = {}
        # Serializes MUTATING verbs end-to-end (check + store write + WAL):
        # the store itself is unlocked dicts, and ThreadingHTTPServer runs
        # one thread per request — without this, two concurrent binding
        # POSTs could both pass the already-bound check (double bind), two
        # same-uid creates could both pass the 409 check, and a compaction
        # could snapshot a store another thread is mid-mutation. One writer
        # at a time is also the etcd model the reference stands on. Watch
        # streams and GETs stay unserialized.
        self._write_lock = threading.Lock()
        from collections import deque
        import uuid
        self._seq: Dict[str, int] = {"pods": 0, "nodes": 0, "podgroups": 0,
                                     **{k: 0 for k in WORKLOAD_KINDS}}
        # Watch-cache read plane (core/watchcache.py): per-kind rv-indexed
        # event ring (the RESUME window — what the old `_backlog` deques
        # held, now carrying the decoded event too so filtered streams can
        # replay) + a wire-object snapshot serving LIST / summary / uid
        # hydration / /metrics/resources under its OWN lock — reads no
        # longer touch the store dicts or the write lock at all.
        self.watch_cache: Dict[str, WatchCache] = {
            "pods": WatchCache("pods", capacity=backlog),
            "nodes": WatchCache("nodes", capacity=backlog),
            "podgroups": WatchCache("podgroups", capacity=backlog),
            **{k: WatchCache(k, capacity=backlog) for k in WORKLOAD_KINDS}}
        self.watch_slim_events = 0       # events delivered as slim wire
        self.watch_filtered_events = 0   # events dropped entirely
        # Wire-plane accounting (core/wire.py): bytes served/consumed per
        # (codec, surface) — the `apiserver_wire_bytes_total{codec,surface}`
        # series that proves which plane (binary vs JSON) actually ran on
        # each hot surface. Bumped on stream/handler threads without a
        # lock: a lost increment under race is observability noise, never
        # state (same posture as node_heartbeats). PRE-SEEDED with every
        # (codec, surface) pair so the dict never grows after init — a
        # concurrent /metrics iteration must never see a structural
        # mutation (RuntimeError), only a slightly stale count.
        self.wire_bytes: Dict[tuple, int] = {
            (codec, surface): 0
            for codec in (wire.JSON, wire.BINARY)
            for surface in ("watch", "ship", "list", "snapshot", "bindings",
                            "status")}
        # Encode-CPU accounting (PR 18): µs spent building wire bytes per
        # surface, accumulated on the stream/handler threads that pay it.
        # PRE-SEEDED like wire_bytes (never grows after init); guarded by
        # its own tiny lock — a float += is a read-modify-write, and
        # unlike a lost count a lost TIME sample would skew the
        # encode-µs/event ratios the bench detail line divides out.
        self.wire_encode_us: Dict[str, float] = {
            s: 0.0 for s in ("watch", "ship", "list", "snapshot",
                             "bindings", "status")}
        self._enc_us_lock = threading.Lock()
        # Per-SERVER negotiation override: True = answer every Accept
        # offer with JSON (a pre-wire server, for interop tests/mixed
        # fleets, without pinning the whole process the way
        # TPU_SCHED_WIRE=json does).
        self.json_only = False
        # Paged LIST plane (`?limit=&continue=`, docs/SCALE.md): pages
        # served, continuation tokens that expired off the rv ring (the
        # 410 Gone analogue), full-cluster single-response LISTs served
        # (the legacy path the 50k plane must keep at zero), and object
        # pages streamed by the replication snapshot bootstrap.
        self.list_pages = 0
        self.list_continue_410 = 0
        self.list_unpaged = 0
        self.snapshot_bootstrap_pages = 0
        self.watch_replay_pages = 0  # lazy-cursor attach replay pages served
        self.node_heartbeats = 0   # kubelet/hollow heartbeat sink hits
        # Node-lifecycle health plane: per-node last-heartbeat stamp
        # (monotonic, LEADER-LOCAL — heartbeats are a sink, never WAL'd, so
        # a promoted replica starts empty and the controller re-ages the
        # fleet from first sight). Own lock: stamped on the heartbeat fast
        # path which must not touch the write or broadcast locks.
        self.node_hb: Dict[str, float] = {}
        self._hb_lock = threading.Lock()
        # Eviction idempotency ledger (pod uid -> last eviction intent id):
        # rides the WAL as "evictions" records so a controller retry —
        # across its own restart or an apiserver failover — replays as a
        # no-op instead of double-evicting. An entry lives only for the
        # evicted-pending window: it is dropped when the pod re-binds or
        # is deleted (derived from the pod's own WAL'd BOUND/DELETED
        # records, so every replica and recovery prunes identically) —
        # a pod that re-binds to a once-failed node can be evicted again
        # under the same deterministic intent, and the ledger never grows
        # with pods that no longer need replay protection. Mutated only
        # under the write lock (eviction subresource / bind / delete /
        # frame apply / recovery).
        self.evictions: Dict[str, str] = {}
        self.pod_evictions = 0           # evictions committed
        self.pod_evictions_replayed = 0  # idempotent replays answered
        # Workload plane (WORKLOAD_KINDS): server-owned wire-dict maps
        # keyed "ns/name". The HTTP verbs are the only writers (under the
        # write lock) and the broadcast is the commit — there is no
        # FakeClientset twin for these kinds.
        self.workloads: Dict[str, Dict[str, dict]] = {
            k: {} for k in WORKLOAD_KINDS}
        # PodDisruptionBudget precondition on voluntary disruptions
        # (eviction subresource + ?voluntary=true deletes): denials
        # answered 429 so the caller backs off and retries after the
        # workload heals. Involuntary paths (zone Full, node delete) are
        # never budget-checked.
        self.evictions_budget_denied = 0
        # Overload protection (core/flowcontrol.py, docs/RESILIENCE.md
        # § overload & fairness): every mutating request is classified into
        # a flow and admitted through per-priority-level bounded-concurrency
        # fair queues BEFORE it can touch `_write_lock`; a full queue sheds
        # with 429 + Retry-After. Replication/lease control traffic rides
        # the exempt lane — a tenant flood can never starve failover.
        self.flowcontrol = FlowController()
        # Recent shipped frames by global seq: the replication window a
        # follower can resume from without a snapshot bootstrap.
        self._repl_backlog = deque(maxlen=backlog)
        # Boot epoch: rv counters restart at 0 with a fresh server, so a
        # client's rv from a PREVIOUS server instance must never resume
        # against this one's unrelated event history — resume requires the
        # epoch to match, otherwise the full re-list (Replace) runs. With a
        # durable store (data_dir) the counters RESUME instead of restarting,
        # so recovery re-announces the PERSISTED epoch and clients ride the
        # RESUME path straight across a process death.
        self.epoch = uuid.uuid4().hex[:12]
        self.resumed_watches = 0   # incremental reconnects served
        self.relisted_watches = 0  # full-list attaches served
        self.bind_conflicts = 0    # rebind-to-a-different-node rejections
        self.capacity_conflicts = 0  # overcommitting binds rejected (Omega)
        self.lease_conflicts = 0     # held-lease PUTs rejected (CAS losers)
        self.lease_transitions = 0   # holder changes (acquire + failover)
        self.compaction_failures = 0
        # Replication plane (kubernetes_tpu/replication/, docs/RESILIENCE.md):
        # every WAL record is a shippable frame stamped with a global
        # monotonic `seq` and the fencing `epoch`. A follower tails
        # GET /replication/wal, replays frames into its own store+WAL, and
        # serves the read plane; mutating verbs answer 421 NotLeader with a
        # redirect to `leader_url`. `promote()` flips follower->leader.
        self.role = "leader"
        self.leader_url = ""      # where NotLeader redirects point
        self.advertise_url = ""   # this replica's own base URL (set by serve)
        self.replica_rank = 0     # election order; 0 = the seed leader
        self.repl_peers: Dict[int, str] = {}  # rank -> follower base URL
        self.repl_epoch = 1
        self._repl_seq = 0
        self._ship_streams: List["_ShipStream"] = []
        self._ship_cond = threading.Condition()
        self.ship_wait_timeouts = 0   # acked writes that outran a follower
        self.ship_streams_dropped = 0  # stalled followers force-detached
        self.repl_frames_applied = 0  # follower: frames replayed locally
        self.repl_frames_rejected = 0  # stale-epoch frames fenced off
        self.repl_lag = 0              # follower: leader head seq - applied
        self.repl_resyncs = 0          # snapshot bootstraps performed
        self.failovers: Dict[str, int] = {}  # promotion reason -> count
        # Durability (core/wal.py): WAL + snapshot compaction + recovery.
        self.persistence = None
        self.recovered_objects = 0
        if data_dir is not None:
            from .wal import DurableStore
            self.persistence = DurableStore(
                data_dir, fsync=fsync, snapshot_every=snapshot_every)
            self._recover()
        self.store.on_pod_event(self._pod_event)
        self.store.on_node_event(self._node_event)
        # Muted registration: on_pod_group_event replays every existing
        # group at subscribe time (informer list semantics) — recovered
        # groups were already reinstalled into the watch cache and must
        # not re-broadcast as fresh WAL'd events.
        self._pg_mute = True
        self.store.on_pod_group_event(self._pod_group_event)
        self._pg_mute = False
        self._httpd: Optional[ThreadingHTTPServer] = None
        # Accepted connections (REST keep-alive + watch streams), so
        # shutdown() can tear them down: pooled clients (KeepAliveClient)
        # park idle connections whose handler threads would otherwise keep
        # this DEAD server's store reachable — and keep the process's port
        # reference alive across a restart-in-place. set add/discard are
        # GIL-atomic; handler setup/finish are the only writers.
        self._conns: set = set()
        # Trace context of the bind currently committing (core/spans.py):
        # set around _bind_one under the write lock, read by the BOUND
        # broadcast that fires synchronously inside store.bind on the same
        # thread — so the slim BOUND event and the WAL record carry the
        # binder's trace id out to every watcher.
        self._bind_ctx = None
        self.tracer = _spans.default_tracer()

    # -- durability (WAL + snapshot; core/wal.py) ---------------------------

    def _recover(self) -> None:
        """Replay snapshot+WAL into the owned store and resume the watch
        plane where the dead process left off: per-kind rv counters, the
        persisted epoch, and an event backlog rebuilt from the WAL tail so
        reflectors reconnecting with their last rv get RESUME, not Replace."""
        import itertools

        from .wal import WALQuarantineError

        rings: Dict[str, list] = {"pods": [], "nodes": [], "podgroups": [],
                                  **{k: [] for k in WORKLOAD_KINDS}}
        # Recovery-time wire state: key -> the object's CURRENT wire dict,
        # seeded from the snapshot and advanced record by record — the
        # base a WAL'd DELTA record materializes against. Tracking the
        # exact wire dicts (not store round-trips) keeps a materialized
        # object byte-identical to the one the leader broadcast.
        wire_state: Dict[str, Dict[str, dict]] = {
            k: {} for k in ("pods", "nodes", "podgroups") + WORKLOAD_KINDS}
        snap, records = self.persistence.load()
        if self.persistence.epoch is not None:
            self.epoch = self.persistence.epoch
        else:
            self.persistence.init_epoch(self.epoch)
        # Replication fencing epoch: recover the persisted generation (a
        # promoted-then-restarted replica must come back in the generation
        # it won, or it would fence off its own shipped frames).
        self.repl_epoch = max(self.repl_epoch, self.persistence.repl_epoch)
        # Recover the persisted ROLE too: a deposed leader that restarts
        # must come back fenced (follower, redirecting at the winner) —
        # restarting read-write would fork history at the winner's epoch.
        if self.persistence.role == "follower":
            self.role = "follower"
            self.leader_url = self.persistence.leader_url or self.leader_url
        if snap is not None:
            self._seq.update(snap.get("seq", {}))
            repl = snap.get("repl") or {}
            self._repl_seq = max(self._repl_seq, int(repl.get("seq", 0)))
            # Ledger before pods: a bound pod's upsert prunes its entry,
            # so the "entry => pod unbound" invariant self-heals even
            # against a snapshot written before pruning existed.
            for w in snap.get("evictions", ()):
                if w.get("uid"):
                    self.evictions[w["uid"]] = w.get("intent", "")
            for w in snap.get("pods", ()):
                self._apply_recovered("pods", "ADDED", w)
                wire_state["pods"][wire_key("pods", w)] = w
            for w in snap.get("nodes", ()):
                self._apply_recovered("nodes", "ADDED", w)
                wire_state["nodes"][wire_key("nodes", w)] = w
            for w in snap.get("podgroups", ()):
                self._apply_recovered("podgroups", "ADDED", w)
                wire_state["podgroups"][wire_key("podgroups", w)] = w
            for k in WORKLOAD_KINDS:
                for w in snap.get(k, ()):
                    self._apply_recovered(k, "ADDED", w)
                    wire_state[k][wire_key(k, w)] = w
            for w in snap.get("leases", ()):
                self._install_lease(w)
        for rec in records:
            kind = rec.get("kind")
            if rec.get("type") == "DELTA" and kind in wire_state:
                # Materialize the WAL'd DELTA against the tracked base —
                # a missing/mismatched base in a CRC-verified log is the
                # same failure class as a CRC miss: damage in the middle
                # of acked history, so quarantine, never guess.
                base = wire_state[kind].get(rec.get("key"))
                if base is None:
                    raise WALQuarantineError(
                        self.persistence._wal_path, -1,
                        wire.DeltaBaseMismatch(
                            f"WAL DELTA for {kind}/{rec.get('key')} "
                            f"has no recovered base"))
                full = wire.apply_patch(base, rec.get("patch") or [])
                delta_rec = rec
                rec = {"kind": kind, "type": "MODIFIED", "object": full,
                       "rv": delta_rec.get("rv"),
                       "seq": delta_rec.get("seq"),
                       "epoch": delta_rec.get("epoch")}
            else:
                delta_rec = None
            seq = rec.get("seq")
            if seq is not None and seq > self._repl_seq:
                self._repl_seq = seq
                # Rebuild the replication ship window too, so followers that
                # resume against a restarted leader ride frames, not a
                # snapshot bootstrap (session streams re-ship the delta).
                self._repl_backlog.append(
                    (seq, wire.WireItem(rec, delta=delta_rec)))
            if kind == "leases":
                # Lease holders survive the restart but their clocks do not
                # (renew stamps are this process's monotonic clock): restore
                # renewed-at-recovery, so a live holder keeps its lease and a
                # dead one expires exactly one lease period after recovery.
                self._install_lease(rec.get("object") or {})
                continue
            if kind == "evictions":
                # Eviction intent ledger: replayed so a controller retry
                # after OUR restart still answers idempotently.
                obj = rec.get("object") or {}
                if obj.get("uid"):
                    self.evictions[obj["uid"]] = obj.get("intent", "")
                continue
            if kind not in ("pods", "nodes", "podgroups") + WORKLOAD_KINDS:
                continue
            self._apply_recovered(kind, rec.get("type", ""), rec.get("object"))
            self._track_wire_state(wire_state[kind], kind,
                                   rec.get("type", ""), rec.get("object"))
            rv = rec.get("rv")
            if rv is not None and rv > self._seq[kind]:
                self._seq[kind] = rv
            # Rebuild the watch-cache ring exactly as _broadcast framed it
            # (the deque's maxlen keeps only the freshest `backlog` events).
            if rv is not None:
                event = {k: v for k, v in rec.items()
                         if k not in ("kind", "seq", "epoch")}
                delta_ev = (None if delta_rec is None else
                            {k: v for k, v in delta_rec.items()
                             if k not in ("kind", "seq", "epoch")})
                rings[kind].append(
                    (rv, event, wire.WireItem(event, delta=delta_ev)))
        # Object resource_versions were not persisted; fast-forward the
        # store's counter past everything ever minted so recovered and new
        # objects never share a version.
        self.store._rv_counter = itertools.count(
            self._seq["pods"] + self._seq["nodes"] + 1)
        # Seed the read plane from the recovered store (the ring keeps only
        # the freshest `backlog` events, trimmed by the deque maxlen).
        # Recovery is single-threaded, but cache mutation uniformly holds
        # the broadcast lock (the analyzer's rule has no special cases).
        with self._lock:
            cap = self.watch_cache["pods"]._ring.maxlen or 8192
            self.watch_cache["pods"].reinstall(
                [pod_to_wire(p) for p in self.store.pods.values()],
                self._seq["pods"], ring=rings["pods"][-cap:])
            self.watch_cache["nodes"].reinstall(
                [node_to_wire(n) for n in self.store.nodes.values()],
                self._seq["nodes"], ring=rings["nodes"][-cap:])
            self.watch_cache["podgroups"].reinstall(
                [pod_group_to_wire(g) for g in
                 list(self.store.pod_groups.values())
                 + list(self.store.composite_pod_groups.values())],
                self._seq["podgroups"], ring=rings["podgroups"][-cap:])
            for k in WORKLOAD_KINDS:
                self.watch_cache[k].reinstall(
                    list(self.workloads[k].values()),
                    self._seq[k], ring=rings[k][-cap:])
        self.recovered_objects = len(self.store.pods) + len(self.store.nodes)
        # Recovered nodes heartbeat-age from NOW: clocks never cross a
        # process boundary (same contract as lease renew stamps) — a live
        # node re-stamps within one period, a dead one ages out exactly one
        # grace period after recovery.
        now = time.monotonic()
        with self._hb_lock:
            for name in self.store.nodes:
                self.node_hb[name] = now
        # Rebuild the Omega commit-validation usage table from the recovered
        # bound pods — incremental maintenance resumes from here.
        self._usage.clear()
        for pod in self.store.pods.values():
            if pod.node_name:
                self._usage_apply(pod.node_name, pod, +1)

    @staticmethod
    def _track_wire_state(state: Dict[str, dict], kind: str, typ: str,
                          obj: Optional[dict]) -> None:
        """Advance the recovery-time wire-dict map by one WAL record — the
        exact base the NEXT DELTA record in the log materializes against
        (mirrors WatchCache._apply_object, including BOUND's
        copy-on-write nodeName patch)."""
        if type(obj) is not dict:
            return
        if typ == "BOUND":
            cur = state.get(obj.get("uid", ""))
            if cur is not None:
                state[obj["uid"]] = dict(cur,
                                         nodeName=obj.get("nodeName", ""))
            return
        try:
            key = wire_key(kind, obj)
        except KeyError:
            return
        if typ == "DELETED":
            state.pop(key, None)
        else:
            state[key] = obj

    def _apply_recovered(self, kind: str, typ: str, wire: Optional[dict]) -> None:
        """Apply one recovered object directly to the store dicts — no
        handler fanout (there are no watchers yet) and idempotent upserts
        (a compaction snapshot may slightly lead the WAL it truncated)."""
        if wire is None:
            return
        if kind in WORKLOAD_KINDS:
            # Workload kinds have no store twin: the server-owned wire-dict
            # map IS the state. Same idempotent-upsert posture as the rest.
            key = f'{wire.get("namespace") or "default"}/{wire.get("name")}'
            if typ == "DELETED":
                self.workloads[kind].pop(key, None)
            else:
                self.workloads[kind][key] = wire
            return
        if kind == "pods":
            if typ == "BOUND":
                # Slim bind record: patch the already-recovered pod in place
                # (its ADDED/snapshot record precedes it in the log; a pod
                # deleted later is corrected by the following DELETED).
                pod = self.store.pods.get(wire.get("uid", ""))
                if pod is not None:
                    pod.node_name = wire.get("nodeName", "")
                    if pod.node_name:
                        self.store.bindings[pod.uid] = pod.node_name
                        # Re-bind resolves the evicted-pending window: the
                        # ledger prunes here exactly as the leader's live
                        # bind path did.
                        self.evictions.pop(pod.uid, None)
                return
            pod = pod_from_wire(wire)
            if typ == "DELETED":
                self.store.pods.pop(pod.uid, None)
                self.store.bindings.pop(pod.uid, None)
                self.evictions.pop(pod.uid, None)
            else:
                self.store.pods[pod.uid] = pod
                if pod.node_name:
                    self.store.bindings[pod.uid] = pod.node_name
                    self.evictions.pop(pod.uid, None)
                else:
                    self.store.bindings.pop(pod.uid, None)
        elif kind == "podgroups":
            g = pod_group_from_wire(wire)
            target = (self.store.composite_pod_groups
                      if wire.get("composite") else self.store.pod_groups)
            key = f"{g.namespace}/{g.name}"
            if typ == "DELETED":
                target.pop(key, None)
            else:
                target[key] = g
        else:
            node = node_from_wire(wire)
            if typ == "DELETED":
                self.store.nodes.pop(node.name, None)
            else:
                self.store.nodes[node.name] = node

    def _install_lease(self, w: dict) -> None:
        """Install one recovered/replicated lease record with its renew
        stamp restarted on THIS process's clock (clocks never cross a
        process boundary: a live holder keeps its lease for one more
        period, a dead one expires exactly one period from now)."""
        if not w.get("name"):
            return
        self.leases[w["name"]] = {
            "holder": w.get("holder", ""),
            "duration": float(w.get("duration", 15.0)),
            "renew": _lease_clock(),
            "transitions": int(w.get("transitions", 0))}

    def _wal_status(self, pod) -> None:
        """Persist a non-evented status patch (nominatedNodeName): an
        rv-less `STATUS` record — recovery upserts the object but the watch
        backlog never sees it (parity with its non-evented live fanout).
        It still rides the replication stream (followers must recover the
        nomination too)."""
        with self._lock:
            wire = pod_to_wire(pod)
            self._repl_append(
                {"kind": "pods", "type": "STATUS", "object": wire})
            # Keep the read plane's object snapshot current (LIST must show
            # nominations) without a ring entry — parity with the
            # non-evented live fanout.
            self.watch_cache["pods"].note_event(None, "STATUS", wire)

    def _repl_append(self, rec: dict, stamped: bool = False,
                     delta: Optional[dict] = None) -> int:
        """Commit one WAL frame — the ONE persist→backlog→ship sequence
        both write paths share: the leader stamps a fresh seq + fencing
        epoch; a follower replaying a SHIPPED frame (`stamped=True`,
        apply_frame) keeps the leader's stamps and adopts its seq. Caller
        holds the broadcast lock (`_lock`) — seq order IS commit order.

        ``delta`` is the record's DELTA twin (minted in the watch cache
        before the event installed): the WAL stores IT (recovery
        materializes against the recovered base) and session ship
        streams forward it; plain binary and JSON followers still get
        the full record off the same WireItem."""
        if stamped:
            seq = int(rec["seq"])
            self._repl_seq = seq
        else:
            self._repl_seq += 1
            seq = self._repl_seq
            rec = dict(rec, seq=seq, epoch=self.repl_epoch)
            if delta is not None:
                delta = dict(delta, seq=seq, epoch=rec["epoch"])
        # ONE WireItem per frame: the WAL append and every attached ship
        # stream share its per-codec encodings (a binary WAL + N binary
        # followers = one binary encode, total).
        item = wire.WireItem(rec, delta=delta)
        if self.persistence is not None:
            self.persistence.append(item)
        self._repl_backlog.append((seq, item))
        self._ship_fanout(seq, item)
        return seq

    def _ship_fanout(self, seq: int, item) -> None:
        """Feed one frame (a shared WireItem) to every attached ship
        stream. Caller holds the broadcast lock. A stream whose bounded
        queue overflows (stalled follower: no socket error, it just
        stopped reading) is marked dead and detached — it re-attaches
        from its applied seq, or resyncs."""
        dead = []
        for st in self._ship_streams:
            try:
                st.q.put_nowait((seq, item))
            except queue.Full:
                st.dead = True
                self.ship_streams_dropped += 1
                dead.append(st)
        for st in dead:
            self._ship_streams.remove(st)

    def _count_wire(self, codec: str, surface: str, n: int) -> None:
        """Attribute `n` served/consumed wire bytes to (codec, surface)."""
        key = (codec, surface)
        self.wire_bytes[key] = self.wire_bytes.get(key, 0) + n

    def _count_encode_us(self, surface: str, seconds: float) -> None:
        """Attribute encode wall time to a wire surface (stream/handler
        threads; never under the broadcast lock)."""
        with self._enc_us_lock:
            self.wire_encode_us[surface] += seconds * 1e6

    def _snapshot_state(self) -> dict:
        """Full-state compaction snapshot. The calling thread holds BOTH the
        write lock (its own verb — no other store mutation can be in
        flight) and the broadcast lock (no event can interleave); bindings
        ride on nodeName."""
        return {
            "epoch": self.epoch,
            "seq": dict(self._seq),
            "repl": {"seq": self._repl_seq, "epoch": self.repl_epoch},
            "pods": [pod_to_wire(p) for p in list(self.store.pods.values())],
            "nodes": [node_to_wire(n) for n in list(self.store.nodes.values())],
            "podgroups": [pod_group_to_wire(g) for g in
                          list(self.store.pod_groups.values())
                          + list(self.store.composite_pod_groups.values())],
            "leases": [dict(rec, name=name, renew=None)
                       for name, rec in list(self.leases.items())],
            "evictions": [{"uid": u, "intent": i}
                          for u, i in list(self.evictions.items())],
            **{k: list(self.workloads[k].values())
               for k in WORKLOAD_KINDS},
        }

    # -- Omega commit validation (per-node committed usage) -----------------

    def _usage_apply(self, node_name: str, pod, sign: int) -> None:
        """Incrementally maintain the committed-usage aggregate the binding
        subresource validates against. Caller holds the write lock (or is
        single-threaded recovery)."""
        req = pod.resource_request()
        u = self._usage.setdefault(
            node_name, {"cpu": 0, "mem": 0, "eph": 0, "pods": 0, "scalar": {}})
        u["cpu"] += sign * req.milli_cpu
        u["mem"] += sign * req.memory
        u["eph"] += sign * req.ephemeral_storage
        u["pods"] += sign
        for k, v in req.scalar_resources.items():
            u["scalar"][k] = u["scalar"].get(k, 0) + sign * v

    def _bind_overcommits(self, node_name: str, pod) -> bool:
        """Would committing `pod` onto `node_name` exceed the node's
        allocatable? The shared-state transaction check (Omega §3): every
        scheduler plans optimistically against its own watch-fed view; the
        single store is where conflicting plans meet, and the loser gets a
        409 instead of an overcommitted node. A bind to a node the store
        does not know is left to the scheduler's own validation."""
        node = self.store.nodes.get(node_name)
        if node is None:
            return False
        u = self._usage.get(
            node_name, {"cpu": 0, "mem": 0, "eph": 0, "pods": 0, "scalar": {}})
        req = pod.resource_request()
        alloc = node.allocatable
        if (u["cpu"] + req.milli_cpu > alloc.milli_cpu
                or u["mem"] + req.memory > alloc.memory
                or u["eph"] + req.ephemeral_storage > alloc.ephemeral_storage
                or u["pods"] + 1 > alloc.allowed_pod_number):
            return True
        return any(u["scalar"].get(k, 0) + v > alloc.scalar_resources.get(k, 0)
                   for k, v in req.scalar_resources.items())

    def _bind_one(self, uid: str, node: str, tctx: Optional[str] = None):
        """One bind attempt (caller holds the write lock) → (code, payload).
        Shared by the single binding subresource and the bulk endpoint.
        ``tctx`` is the binder's wire trace context (X-Trace-Context header
        / bulk-item tctx field); absent, the context derives from the pod
        uid — deterministic sampling means both sides agree anyway."""
        tr = self.tracer
        ctx = (_spans.parse_ctx(tctx) if tctx else None) \
            or tr.context_for(uid)
        if not tr.wants(ctx):
            return self._bind_one_locked(uid, node)
        t0 = time.perf_counter()
        self._bind_ctx = ctx
        try:
            code, payload = self._bind_one_locked(uid, node)
        finally:
            self._bind_ctx = None
        tr.record("api.bind", ctx, time.perf_counter() - t0,
                  node=node, code=code)
        return code, payload

    def _bind_one_locked(self, uid: str, node: str):
        pod = self.store.pods.get(uid)
        if pod is None:
            return 404, {"error": "pod not found"}
        if pod.node_name:
            # Already bound: a same-node POST is a retry replay of a bind
            # whose reply was lost (pre-crash write, recovered from the
            # WAL) — idempotent success, no re-fired event. A different
            # node is a genuine conflict (409, registry AlreadyExists
            # analogue): a pod must never be bound twice.
            if pod.node_name == node:
                return 200, {"bound": True}
            self.bind_conflicts += 1
            return 409, {"error": "AlreadyBound"}
        if self._bind_overcommits(node, pod):
            # Optimistic-concurrency loser (Omega transaction validation):
            # another scheduler's commits filled this node first. 409 →
            # conflict-driven requeue.
            self.capacity_conflicts += 1
            return 409, {"error": "OutOfCapacity"}
        self.store.bind(pod, node)
        self._usage_apply(node, pod, +1)
        # A successful (re-)bind closes the evicted-pending window: drop
        # the idempotency ledger entry so a LATER failure of this pod's
        # new home — including a re-bind onto a recovered node that
        # failed before — mints a fresh evictable wave instead of being
        # swallowed by a stale already=True. Replicas/recovery derive the
        # same prune from this bind's own WAL'd BOUND record.
        self.evictions.pop(uid, None)
        return 200, {"bound": True}

    # -- shard leases (PUT-CAS + server-side expiry) ------------------------

    def _lease_wire(self, name: str, rec: dict, now: float) -> dict:
        age = now - rec["renew"]
        return {"name": name, "holder": rec["holder"],
                "leaseDurationSeconds": rec["duration"],
                "ageSeconds": round(age, 3),
                "transitions": rec["transitions"],
                "expired": (not rec["holder"]) or age >= rec["duration"]}

    def list_leases(self) -> List[dict]:
        now = _lease_clock()
        with self._lock:
            return [self._lease_wire(n, r, now)
                    for n, r in sorted(self.leases.items())]

    def upsert_lease(self, name: str, holder: str,
                     duration: float) -> Optional[dict]:
        """Acquire-or-renew under CAS semantics: a held, unexpired lease
        only renews for its CURRENT holder; anyone else gets None (HTTP
        409) — the resourcelock's update-if-expired collapsed to one verb.
        The record rides the WAL so a `kill -9`'d apiserver recovers the
        holder table (with clocks restarted, see _recover)."""
        now = _lease_clock()
        with self._write_lock:
            if self.role != "leader":
                return self.NOT_LEADER
            rec = self.leases.get(name)
            if (rec is not None and rec["holder"] and rec["holder"] != holder
                    and now - rec["renew"] < rec["duration"]):
                self.lease_conflicts += 1
                return None
            if rec is None:
                rec = {"holder": "", "duration": float(duration),
                       "renew": now, "transitions": 0}
                self.leases[name] = rec
            if rec["holder"] != holder:
                rec["transitions"] += 1
                self.lease_transitions += 1
            rec["holder"] = holder
            rec["duration"] = float(duration)
            rec["renew"] = now
            with self._lock:
                self._repl_append({
                    "kind": "leases", "type": "LEASE",
                    "object": {"name": name, "holder": holder,
                               "duration": rec["duration"],
                               "transitions": rec["transitions"]}})
                if (self.persistence is not None
                        and self.persistence.should_compact()):
                    # Renewals are the steady-state WAL traffic of an
                    # idle sharded plane (N shards × 3 appends per lease
                    # period, forever); without compacting here — the
                    # broadcast path never runs on a quiet cluster —
                    # the WAL and its replay time grow without bound.
                    # Same locking posture as _broadcast: this thread
                    # holds the write lock, so the store snapshot is
                    # stable, and a failed compaction must not fail the
                    # renewal.
                    try:
                        self.persistence.write_snapshot(
                            self._snapshot_state())
                    except Exception:  # noqa: BLE001
                        self.compaction_failures += 1
            return self._lease_wire(name, rec, now)

    # -- replication (WAL shipping + leader/follower roles) -----------------
    #
    # The reference splits its control plane into a replicated log (etcd3)
    # and read-serving watch caches; this section rebuilds that split
    # natively: every committed write is a shippable WAL frame
    # (seq+epoch-stamped by _repl_append), followers tail
    # GET /replication/wal and replay frames via apply_frame, and a leader
    # kill -9 promotes a follower (promote) fenced by the monotonic
    # replication epoch. docs/RESILIENCE.md § replication.

    def replication_status(self) -> dict:
        """The discovery document election and client leader-resolution
        read: role, rank, fencing epoch, applied head, redirect target —
        plus the tail's election counters when one is attached
        (`repl_tail`, set by the follower binary): 'why is this follower
        not converging' must be answerable from the outside."""
        out = {"role": self.role, "rank": self.replica_rank,
               "replEpoch": self.repl_epoch, "seq": self._repl_seq,
               "watchEpoch": self.epoch, "leader": self.leader_url,
               "lag": self.repl_lag}
        tail = getattr(self, "repl_tail", None)
        if tail is not None:
            thread = tail._thread
            out["tail"] = {
                "elections": tail.elections, "deferrals": tail.deferrals,
                "reconnects": tail.reconnects, "bootstraps": tail.bootstraps,
                "fenced": tail.fenced_streams,
                "alive": thread is not None and thread.is_alive(),
                "lastContactAge": round(
                    time.monotonic() - tail.last_contact, 3)}
        return out

    def apply_frame(self, rec: dict,
                    stream_epoch: Optional[int] = None) -> bool:
        """Follower-side replay of one shipped WAL frame: append to the
        LOCAL WAL first, then upsert the store and fan the event out to
        this replica's own watch streams — the exact write-path ordering
        the leader uses, so an event a local watcher saw is always
        recoverable here too. Returns False for a frame from a stale
        fencing epoch (a deposed leader's append — rejected, the tail must
        disconnect).

        ``stream_epoch`` is the generation the SERVING leader claims
        (election/announcement/HB): a frame stamped with an older epoch is
        still legitimate when it is part of a newer leader's committed
        history — a lagging survivor that adopted the winner's epoch
        before catching up must not fence off the pre-promotion frames it
        still needs. Only a frame whose OWN stamp and whose stream's claim
        are both stale is a deposed leader's append."""
        seq = int(rec.get("seq", 0))
        ep = int(rec.get("epoch", 0))
        with self._write_lock:
            with self._lock:
                if max(ep, int(stream_epoch or 0)) < self.repl_epoch:
                    self.repl_frames_rejected += 1
                    return False
                if seq <= self._repl_seq:
                    return True  # reconnect overlap: already applied
                if ep > self.repl_epoch:
                    # A legitimately promoted leader's first frames carry
                    # the bumped epoch: adopt it (and persist — fencing
                    # must survive our own restart).
                    self.repl_epoch = ep
                    if self.persistence is not None:
                        self.persistence.set_repl_epoch(ep)
                delta_rec = None
                if rec.get("type") == "DELTA":
                    # Shipped field-path patch: materialize the full
                    # object against OUR watch-cache base BEFORE anything
                    # installs this frame's state. A base-rv mismatch
                    # raises DeltaBaseMismatch out of apply_frame — the
                    # tail catches it and snapshot-resyncs; a patch is
                    # never applied onto a divergent base.
                    full = self.watch_cache[rec["kind"]] \
                        .materialize_delta(rec)
                    delta_rec = rec
                    rec = {"kind": rec["kind"], "type": "MODIFIED",
                           "object": full, "rv": rec.get("rv"),
                           "seq": seq, "epoch": ep}
                # The local WAL + our own ship fanout carry the delta
                # twin (same WireItem routing the leader used), while
                # the full record serves JSON/plain-binary peers.
                self._repl_append(rec, stamped=True, delta=delta_rec)
                self.repl_frames_applied += 1
                kind = rec.get("kind")
                if kind == "leases":
                    self._install_lease(rec.get("object") or {})
                elif kind == "evictions":
                    # Replicated intent ledger: a promoted follower must
                    # answer an in-flight eviction wave's retries
                    # idempotently — losing this would double-evict.
                    obj = rec.get("object") or {}
                    if obj.get("uid"):
                        self.evictions[obj["uid"]] = obj.get("intent", "")
                elif kind in ("pods", "nodes", "podgroups") + WORKLOAD_KINDS:
                    self._apply_recovered(kind, rec.get("type", ""),
                                          rec.get("object"))
                    rv = rec.get("rv")
                    if rv is not None:
                        if rv > self._seq[kind]:
                            self._seq[kind] = rv
                        event = {k: v for k, v in rec.items()
                                 if k not in ("kind", "seq", "epoch")}
                        delta_ev = None
                        if delta_rec is not None:
                            delta_ev = {k: v for k, v in delta_rec.items()
                                        if k not in ("kind", "seq",
                                                     "epoch")}
                        # Same fanout as the leader's broadcast: this
                        # follower's watch cache + its own (possibly
                        # filtered) streams stay converged in the shared
                        # rv space — clients RESUME against any replica.
                        self._fan_event(kind, event,
                                        wire.WireItem(event,
                                                      delta=delta_ev))
                    else:
                        # rv-less STATUS: snapshot upsert, no ring entry
                        # (parity with its non-evented live fanout).
                        self.watch_cache[kind].note_event(
                            None, rec.get("type", ""), rec.get("object"))
                # Compaction runs LAST, after the frame is in the store and
                # _repl_seq has advanced: a snapshot taken between append
                # and apply would exclude the triggering frame while
                # write_snapshot resets the WAL that just absorbed it — the
                # frame would exist nowhere durable, and recovery would
                # fast-forward straight past the hole (silent divergence).
                if (self.persistence is not None
                        and self.persistence.should_compact()):
                    try:
                        self.persistence.write_snapshot(
                            self._snapshot_state())
                    except Exception:  # noqa: BLE001
                        self.compaction_failures += 1
        return True

    def install_snapshot(self, snap: dict) -> None:
        """Cold-follower bootstrap: replace local state with a leader
        snapshot (GET /replication/snapshot) and persist it as OUR
        compaction snapshot, so a restart recovers locally and re-tails
        from the snapshot's seq. Adopts the leader's WATCH epoch too —
        rv continuity across replicas is what lets clients RESUME against
        any of them."""
        with self._write_lock:
            with self._lock:
                self.store.pods.clear()
                self.store.nodes.clear()
                self.store.bindings.clear()
                self.store.pod_groups.clear()
                self.store.composite_pod_groups.clear()
                self.leases.clear()
                self.evictions.clear()
                for k in WORKLOAD_KINDS:
                    self.workloads[k].clear()
                self._seq.update(snap.get("seq", {}))
                # Ledger before pods (see _recover): bound-pod upserts
                # prune their entries, keeping "entry => pod unbound".
                for w in snap.get("evictions", ()):
                    if w.get("uid"):
                        self.evictions[w["uid"]] = w.get("intent", "")
                for w in snap.get("pods", ()):
                    self._apply_recovered("pods", "ADDED", w)
                for w in snap.get("nodes", ()):
                    self._apply_recovered("nodes", "ADDED", w)
                for w in snap.get("podgroups", ()):
                    self._apply_recovered("podgroups", "ADDED", w)
                for k in WORKLOAD_KINDS:
                    for w in snap.get(k, ()):
                        self._apply_recovered(k, "ADDED", w)
                for w in snap.get("leases", ()):
                    self._install_lease(w)
                repl = snap.get("repl") or {}
                self._repl_seq = int(repl.get("seq", 0))
                self.repl_epoch = max(self.repl_epoch,
                                      int(repl.get("epoch", 1)))
                if snap.get("epoch"):
                    self.epoch = snap["epoch"]
                self.repl_resyncs += 1
                # A RESYNC skipped frames: any ATTACHED watch stream has a
                # gap its client cannot see, and the retained backlog spans
                # it. Clear the resume window and end those streams
                # (sentinel); reconnecting clients full-re-list against the
                # installed state (reflector Replace heals their caches).
                self._repl_backlog.clear()
                self.watch_cache["pods"].reinstall(
                    list(snap.get("pods", ())), self._seq.get("pods", 0))
                self.watch_cache["nodes"].reinstall(
                    list(snap.get("nodes", ())), self._seq.get("nodes", 0))
                self.watch_cache["podgroups"].reinstall(
                    list(snap.get("podgroups", ())),
                    self._seq.get("podgroups", 0))
                for k in WORKLOAD_KINDS:
                    self.watch_cache[k].reinstall(
                        list(snap.get(k, ())), self._seq.get(k, 0))
                for kind in self._watchers:
                    for w in self._watchers[kind]:
                        w.q.put(None)
                if self.persistence is not None:
                    self.persistence.epoch = self.epoch
                    self.persistence.set_repl_epoch(self.repl_epoch)
                    try:
                        self.persistence.write_snapshot(self._snapshot_state())
                    except Exception:  # noqa: BLE001
                        self.compaction_failures += 1

    def promote(self, reason: str = "leader_lost") -> None:
        """Follower -> leader: bump the fencing epoch (persisted BEFORE the
        first write of the new generation), rebuild the Omega usage table
        from replicated truth, fast-forward the store's rv mint, flip to
        read-write, and tell every attached client (FAILOVER marker) so
        writes re-resolve and schedulers reconcile any bind the dead
        leader acked but never shipped."""
        import itertools
        with self._write_lock:
            with self._lock:
                if self.role == "leader":
                    return
                self.repl_epoch += 1
                self.role = "leader"
                self.leader_url = self.advertise_url
                if self.persistence is not None:
                    self.persistence.set_repl_epoch(self.repl_epoch)
                    self.persistence.set_role("leader", self.advertise_url)
                self.repl_lag = 0
                self.failovers[reason] = self.failovers.get(reason, 0) + 1
            self._usage.clear()
            for pod in self.store.pods.values():
                if pod.node_name:
                    self._usage_apply(pod.node_name, pod, +1)
            self.store._rv_counter = itertools.count(
                self._seq["pods"] + self._seq["nodes"] + 1)
        # Forensic moment: a 100%-sampled replication.promote span marks the
        # takeover instant, and the flight recorder dumps the ring around it.
        tr = self.tracer
        tr.record("replication.promote", tr.proc_ctx(),
                  epoch=self.repl_epoch, reason=reason, seq=self._repl_seq,
                  rank=self.replica_rank)
        _spans.request_dump("replication_promote")
        self._emit_control({"type": "FAILOVER", "epoch": self.repl_epoch,
                            "leader": self.advertise_url})

    def demote(self, leader_url: str, epoch: int) -> None:
        """Deposed-leader fencing: a peer announced a NEWER fencing epoch
        (or won an EQUAL-epoch race by rank — the /replication/leader
        handler decides that tie-break before calling). Stop accepting
        writes immediately (NotLeader from here on) and point clients at
        the winner; this replica's divergent tail, if any, resolves via
        snapshot resync when its tail re-attaches."""
        with self._write_lock:
            with self._lock:
                if int(epoch) < self.repl_epoch:
                    return  # the claimant is from an older generation
                self.role = "follower"
                self.leader_url = leader_url
                self.repl_epoch = int(epoch)
                if self.persistence is not None:
                    # Persist the DEPOSED role too: restarting read-write
                    # at the winner's epoch would fork history unfenceably.
                    self.persistence.set_repl_epoch(self.repl_epoch)
                    self.persistence.set_role("follower", leader_url)
                self.failovers["deposed"] = self.failovers.get("deposed", 0) + 1
        self._emit_control({"type": "FAILOVER", "epoch": self.repl_epoch,
                            "leader": leader_url})

    def note_leader(self, leader_url: str, epoch: int) -> bool:
        """Follower bookkeeping when its tail re-attaches: record the
        (possibly new) leader and, when leadership actually MOVED, notify
        local watch clients with a FAILOVER marker so their write routing
        re-resolves and their schedulers reconcile. Returns True when the
        leader changed."""
        with self._lock:
            changed = (leader_url != self.leader_url
                       or epoch > self.repl_epoch)
            self.leader_url = leader_url
            if epoch > self.repl_epoch:
                self.repl_epoch = int(epoch)
                if self.persistence is not None:
                    self.persistence.set_repl_epoch(self.repl_epoch)
        if changed:
            self._emit_control({"type": "FAILOVER", "epoch": self.repl_epoch,
                                "leader": leader_url})
        return changed

    def _emit_control(self, event: dict) -> None:
        """Push a control marker (FAILOVER) to every live watch stream of
        both kinds — rv-less and never WAL'd, like BOOKMARK. One shared
        WireItem: each stream's consumer encodes it in its own codec."""
        item = wire.WireItem(event)
        with self._lock:
            for kind in self._watchers:
                for w in self._watchers[kind]:
                    w.q.put(item)

    def _attach_ship(self, since: int):
        """Attach a follower's ship stream at `since` (its last applied
        seq). Under the broadcast lock: the backlog replay and live-queue
        registration cannot let a frame fall between them. Returns None
        when the window no longer covers `since` — the follower must
        snapshot-bootstrap (RESYNC)."""
        with self._lock:
            if since > self._repl_seq:
                # The follower is AHEAD of this server (it applied frames a
                # torn-tailed restart of ours discarded): histories
                # diverged — only a snapshot resync reconverges them.
                return None
            covered = (since == self._repl_seq
                       or (self._repl_backlog
                           and self._repl_backlog[0][0] <= since + 1))
            if not covered:
                return None
            st = _ShipStream(since, self._repl_backlog.maxlen or 8192)
            for seq, data in self._repl_backlog:
                if seq > since:
                    st.q.put_nowait((seq, data))
            self._ship_streams.append(st)
        return st

    def _detach_ship(self, st) -> None:
        with self._lock:
            if st in self._ship_streams:
                self._ship_streams.remove(st)
        with self._ship_cond:
            self._ship_cond.notify_all()

    def _ship_mark_sent(self, st, seq: int) -> None:
        """Ship thread: frame bytes for `seq` are in the kernel send buffer
        (sendall returned) — a leader SIGKILL can no longer lose them."""
        with self._ship_cond:
            st.sent_seq = max(st.sent_seq, seq)
            if not st.acked and st.sent_seq >= self._repl_seq:
                st.acked = True  # lagging follower caught back up
            self._ship_cond.notify_all()

    def _await_shipped(self, seq: int, timeout: float = 0.25) -> bool:
        """Reply gating for acked mutations: wait (briefly, outside every
        lock) until each in-quorum follower stream has `seq` on the wire.
        This is what turns a leader kill -9 from 'acked writes silently
        vanish' into 'acked writes survive on a follower'. A follower that
        cannot keep up inside `timeout` is dropped from the ack quorum
        (counted) instead of convoying the whole write plane — availability
        over completeness, the degraded-mode contract."""
        if not self._ship_streams:
            return True
        deadline = time.monotonic() + timeout
        with self._ship_cond:
            while True:
                laggards = [st for st in self._ship_streams
                            if st.acked and st.sent_seq < seq]
                if not laggards:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    for st in laggards:
                        st.acked = False
                    self.ship_wait_timeouts += 1
                    return False
                self._ship_cond.wait(remaining)

    def expose_metrics(self) -> str:
        """Control-plane counters (conflict/lease/watch planes) in the
        Prometheus text format — scraped by the shard chaos/bench harnesses
        so failover and conflict behavior is observable from outside."""
        out = []
        for name, v in (
                ("apiserver_bind_conflicts_total", self.bind_conflicts),
                ("apiserver_capacity_conflicts_total",
                 self.capacity_conflicts),
                ("apiserver_lease_conflicts_total", self.lease_conflicts),
                ("apiserver_lease_transitions_total", self.lease_transitions),
                ("apiserver_resumed_watches_total", self.resumed_watches),
                ("apiserver_relisted_watches_total", self.relisted_watches),
                ("apiserver_compaction_failures_total",
                 self.compaction_failures),
                ("apiserver_replication_frames_applied_total",
                 self.repl_frames_applied),
                ("apiserver_replication_frames_rejected_total",
                 self.repl_frames_rejected),
                ("apiserver_replication_resyncs_total", self.repl_resyncs),
                ("apiserver_replication_ship_wait_timeouts_total",
                 self.ship_wait_timeouts),
                ("apiserver_replication_ship_streams_dropped_total",
                 self.ship_streams_dropped),
                # Watch-cache read plane (core/watchcache.py): reads served
                # from the cache (list/summary/uids//metrics/resources),
                # RESUME replays from the ring, resume rvs that fell off
                # the window (410-too-old -> full re-list), and the
                # shard-filter's slimmed/suppressed event counts.
                ("apiserver_watch_cache_hits_total",
                 sum(wc.hits for wc in self.watch_cache.values())),
                ("apiserver_watch_cache_resumes_total",
                 sum(wc.resumes for wc in self.watch_cache.values())),
                ("apiserver_watch_cache_too_old_total",
                 sum(wc.too_old for wc in self.watch_cache.values())),
                # Incremental paged-LIST key index: full re-sorts actually
                # paid (lazy builds after reinstall / first page) — a
                # churning hollow fleet must hold this near-constant
                # instead of re-sorting 50k keys per page.
                ("apiserver_watch_cache_key_resorts_total",
                 sum(wc.key_resorts for wc in self.watch_cache.values())),
                ("apiserver_watch_events_slim_total", self.watch_slim_events),
                ("apiserver_watch_events_filtered_out_total",
                 self.watch_filtered_events),
                # Paged LIST plane (docs/SCALE.md): pages served, expired
                # continuations (410 -> the client restarts its list),
                # legacy full-cluster single-response LISTs (zero on a
                # paged-only plane — the 50k acceptance counter), and
                # snapshot-bootstrap object pages streamed to followers.
                ("apiserver_list_pages_total", self.list_pages),
                ("apiserver_list_continue_410_total", self.list_continue_410),
                ("apiserver_list_unpaged_total", self.list_unpaged),
                ("apiserver_watch_replay_pages_total",
                 self.watch_replay_pages),
                ("apiserver_snapshot_bootstrap_pages_total",
                 self.snapshot_bootstrap_pages),
                ("apiserver_node_heartbeats_total",
                 self.node_heartbeats),
                # Eviction subresource (node-lifecycle controller plane):
                # committed DELETE-then-recreate evictions, and idempotent
                # intent replays answered without touching the pod —
                # exactly-once across controller restart and failover.
                ("apiserver_pod_evictions_total", self.pod_evictions),
                ("apiserver_pod_evictions_replayed_total",
                 self.pod_evictions_replayed),
                # PDB precondition: voluntary disruptions denied because
                # committing them would take a workload below minAvailable.
                ("apiserver_pod_evictions_budget_denied_total",
                 self.evictions_budget_denied),
                # WAL CRC plane (core/wal.py): complete-but-corrupt middle
                # records detected at recovery (each one quarantined boot).
                ("apiserver_wal_crc_failures_total",
                 self.persistence.crc_failures
                 if self.persistence is not None else 0)):
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {v}")
        # Flow-control plane (core/flowcontrol.py): per-priority-level
        # admission counters + live seat/queue gauges — the series the
        # flood chaos scenario reads to prove the exempt lane bypassed
        # tenant queues while the flood was shed.
        fc = self.flowcontrol.snapshot()
        for metric, key in (("rejected", "rejected"),
                            ("dispatched", "dispatched"),
                            ("queued", "queued")):
            name = f"apiserver_flowcontrol_{metric}_total"
            out.append(f"# TYPE {name} counter")
            for level in sorted(fc):
                out.append('%s{priority_level="%s"} %d'
                           % (name, level, fc[level][key]))
        for name, key in (("apiserver_flowcontrol_current_seats", "seats"),
                          ("apiserver_flowcontrol_queue_depth",
                           "queue_depth")):
            out.append(f"# TYPE {name} gauge")
            for level in sorted(fc):
                out.append('%s{priority_level="%s"} %d'
                           % (name, level, fc[level][key]))
        out.append("# TYPE apiserver_failover_total counter")
        for reason, v in sorted(self.failovers.items()):
            out.append('apiserver_failover_total{reason="%s"} %d'
                       % (reason, v))
        # Wire plane: bytes per (codec, surface) — the bench's `wire`
        # summary and the binary-negotiated acceptance check read this.
        out.append("# TYPE apiserver_wire_bytes_total counter")
        for (codec, surface), v in sorted(self.wire_bytes.items()):
            out.append('apiserver_wire_bytes_total{codec="%s",surface="%s"}'
                       ' %d' % (codec, surface, v))
        # Encode CPU per surface (µs) and the delta plane's mint/apply
        # counters — the bench detail line divides micros by events to
        # attribute shard-scaling gaps to encode cost.
        out.append("# TYPE apiserver_wire_encode_micros_total counter")
        with self._enc_us_lock:
            enc_us = dict(self.wire_encode_us)
        for surface, us in sorted(enc_us.items()):
            out.append('apiserver_wire_encode_micros_total{surface="%s"}'
                       ' %d' % (surface, int(us)))
        minted = sum(wc.deltas_minted for wc in self.watch_cache.values())
        applied = sum(wc.deltas_applied for wc in self.watch_cache.values())
        out.append("# TYPE apiserver_wire_deltas_minted_total counter")
        out.append("apiserver_wire_deltas_minted_total %d" % minted)
        out.append("# TYPE apiserver_wire_deltas_applied_total counter")
        out.append("apiserver_wire_deltas_applied_total %d" % applied)
        # Gauges: current role (1 = leader) and replication lag. On the
        # leader, lag is its head minus the slowest attached ship stream;
        # on a follower, the head the tail last heard minus what it applied.
        with self._ship_cond:
            if self._ship_streams:
                lag = max(self._repl_seq - st.sent_seq
                          for st in self._ship_streams)
            else:
                lag = self.repl_lag
        out.append("# TYPE apiserver_replication_role gauge")
        out.append("apiserver_replication_role %d"
                   % (1 if self.role == "leader" else 0))
        out.append("# TYPE apiserver_replication_lag_records gauge")
        out.append("apiserver_replication_lag_records %d" % max(0, lag))
        return "\n".join(out) + "\n"

    # -- event fanout to watch streams -------------------------------------

    def _broadcast(self, kind: str, event: dict) -> None:
        with self._lock:
            self._seq[kind] += 1
            event["rv"] = self._seq[kind]
            # Span context of the committing bind (None for every other
            # event class): times the WAL append and the watcher fanout
            # into the binder's trace (stages wal.append / bound.fanout).
            ctx = self._bind_ctx
            # Mint the event's DELTA twin FIRST — before the WAL append
            # or the fanout installs the new object, while the watch
            # cache's snapshot still holds the exact base every attached
            # receiver (and the WAL's recovered state) already has. The
            # prior wire object is read under the cache's own lock
            # (mint_delta; the delta-base-under-cache-lock rule).
            delta = self.watch_cache[kind].mint_delta(event)
            # WAL append BEFORE fanout: an event a watcher saw is always
            # recoverable. The record is the event itself plus the kind
            # (and the replication seq/epoch stamp), so recovery — and a
            # tailing follower — rebuilds both the store and the watch
            # backlog from one stream.
            _tw = time.perf_counter() if ctx is not None else 0.0
            self._repl_append(
                {"kind": kind, **event},
                delta=None if delta is None else {"kind": kind, **delta})
            if ctx is not None:
                self.tracer.record("wal.append", ctx,
                                   time.perf_counter() - _tw,
                                   rv=event["rv"])
            if (self.persistence is not None
                    and self.persistence.should_compact()):
                try:
                    # Safe to read the store here: the writing thread
                    # holds _write_lock, so no other mutation is in
                    # flight. write_snapshot is atomic (tmp+replace)
                    # and only resets the WAL after the replace — a
                    # failed compaction leaves snapshot+WAL coherent,
                    # so it must never abort the broadcast (that would
                    # punch a hole in the fanout/backlog at this rv).
                    self.persistence.write_snapshot(self._snapshot_state())
                except Exception:  # noqa: BLE001
                    self.compaction_failures += 1
            item = wire.WireItem(event, delta=delta)
            _tf = time.perf_counter() if ctx is not None else 0.0
            self._fan_event(kind, event, item)
            if ctx is not None:
                self.tracer.record("bound.fanout", ctx,
                                   time.perf_counter() - _tf,
                                   watchers=len(self._watchers[kind]),
                                   rv=event["rv"])

    def _fan_event(self, kind: str, event: dict, item) -> None:
        """The one commit→read-plane fanout both write paths share (the
        leader's _broadcast and a follower's apply_frame): install the
        event into the watch cache (ring + object snapshot), then feed
        every attached stream — full wire, or through its shard filter.
        ``item`` is the event's shared WireItem: every stream's consumer
        encodes it in its OWN codec, once per codec total. Caller holds
        the broadcast lock, AFTER the WAL append: ring order is commit
        order, and a cached/fanned event is always durable."""
        self.watch_cache[kind].note_event(
            event.get("rv"), event.get("type", ""), event.get("object"),
            data=item, event=event)
        # One per-event memo shared across the filtered streams: the slim
        # projection/item is identical for all of them, so N shards pay
        # ONE dict build under the broadcast lock, not N — and the encode
        # itself runs on the consumer threads, once per codec.
        memo: dict = {}
        for w in self._watchers[kind]:
            self._route_to(w, event, item, self.watch_cache[kind], memo)

    def _route_to(self, st: _WatchStream, event: dict, data,
                  wc: WatchCache, memo: Optional[dict] = None) -> None:
        """Deliver one event to one stream through its filter (or raw) —
        the ONE routing+counting sequence the live fanout and the
        attach-time replay both use. Caller holds the broadcast lock."""
        if st.filter is None:
            st.q.put(data)
            return
        outs, slim, dropped = st.filter.route(event, data, wc, memo)
        self.watch_slim_events += slim
        self.watch_filtered_events += dropped
        for d in outs:
            st.q.put(d)

    def _pod_event(self, kind: str, old, new) -> None:
        typ = {"add": "ADDED", "update": "MODIFIED", "delete": "DELETED"}[kind]
        if (kind == "update" and old is not None
                and new.node_name and not old.node_name):
            # Bind commit — the hottest event class on a sharded plane, and
            # the only server-side writer of nodeName (the pod's spec is
            # otherwise the one the watcher already caches from ADDED). A
            # slim BOUND event carries just {uid, nodeName}: N shards each
            # decode every peer's binds, so the full-pod wire encode +
            # pod_from_wire rebuild per bind per watcher is pure scaling tax.
            # A sampled bind adds its trace context (tctx) so every foreign
            # shard's bound.observe span joins the binder's trace — and the
            # WAL record (the event itself) preserves it across recovery.
            obj = {"uid": new.uid, "nodeName": new.node_name}
            if self._bind_ctx is not None:
                obj["tctx"] = _spans.format_ctx(self._bind_ctx)
            self._broadcast("pods", {"type": "BOUND", "object": obj})
            return
        self._broadcast("pods", {"type": typ, "object": pod_to_wire(new)})

    def _node_event(self, kind: str, old, new) -> None:
        typ = {"add": "ADDED", "update": "MODIFIED", "delete": "DELETED"}[kind]
        self._broadcast("nodes", {"type": typ, "object": node_to_wire(new)})

    def _pod_group_event(self, group) -> None:
        # Pod groups are create-only upserts on this surface (the store has
        # no update/delete verb), so every event is ADDED. Muted during
        # registration: the store replays recovered groups at subscribe
        # time and those are already in the WAL + watch cache.
        if self._pg_mute:
            return
        self._broadcast("podgroups",
                        {"type": "ADDED", "object": pod_group_to_wire(group)})

    # -- node-lifecycle health plane (controllers/node_lifecycle.py) --------

    def _note_heartbeats(self, names) -> None:
        """Stamp last-heartbeat for `names` on THIS process's clock. Called
        from the heartbeat sink and node create/PUT paths; never WAL'd."""
        now = time.monotonic()
        with self._hb_lock:
            for n in names:
                self.node_hb[n] = now

    def _drop_heartbeat(self, name: str) -> None:
        with self._hb_lock:
            self.node_hb.pop(name, None)

    def heartbeat_ages(self) -> Dict[str, float]:
        """Seconds since each node's last heartbeat (leader-local truth —
        the GET /api/v1/nodes/heartbeats surface the lifecycle controller
        polls; followers answer 421 so the client leader-routes)."""
        now = time.monotonic()
        with self._hb_lock:
            snap = dict(self.node_hb)
        return {n: round(now - t, 3) for n, t in snap.items()}

    # -- eviction subresource (POST /api/v1/pods/<uid>/eviction) ------------

    def _evict_locked(self, uid: str, body: dict):
        """Evict one bound pod: DELETE-then-recreate-pending, so the
        scheduler re-places it through the normal queue. Caller holds the
        write lock. Idempotent by intent id: the (uid, intent) pair is
        ledgered in `self.evictions` and WAL'd, so any retry — controller
        restart, or replay against a promoted leader — answers
        `already=True` without touching the pod. The entry lives only
        until the pod re-binds (or is deleted): once re-placed, the same
        uid@node intent names a NEW wave — a pod that returns to a
        recovered node must be evictable again when that node fails a
        second time. Mutation-before-ledger is the crash-safe order: a
        crash between them leaves a pending pod the retry sees as
        already-evicted work (no-op), whereas ledger-first could ack an
        eviction that never happened."""
        intent = str(body.get("intent") or "")
        want_node = str(body.get("node") or "")
        if not intent:
            return 400, {"error": "intent required"}
        if self.evictions.get(uid) == intent:
            self.pod_evictions_replayed += 1
            return 200, {"evicted": True, "already": True}
        pod = self.store.pods.get(uid)
        if pod is None:
            return 404, {"error": "pod not found"}
        if not pod.node_name:
            # Already pending (a prior wave's recreate, or never bound):
            # nothing to evict — and NOT a ledger entry, so a later bind
            # to a fresh failing node can still be evicted under a new
            # intent.
            return 200, {"evicted": False, "pending": True}
        if want_node and pod.node_name != want_node:
            # The pod moved since the controller planned this eviction
            # (taint lifted / already rescheduled): refuse — evicting a
            # healthy placement would be the storm the rate limiter exists
            # to prevent.
            return 409, {"error": "NodeMismatch", "node": pod.node_name}
        if pod.finalizers:
            return 409, {"error": "FinalizerParked"}
        denied = self._pdb_blocks_eviction(pod)
        if denied is not None:
            self.evictions_budget_denied += 1
            return 429, denied
        bound_to = pod.node_name
        self.store.delete_pod(pod)
        if uid in self.store.pods:
            return 409, {"error": "FinalizerParked"}
        self._usage_apply(bound_to, pod, -1)
        w = pod_to_wire(pod)
        w["nodeName"] = ""
        w["nominatedNodeName"] = ""
        ann = dict(w.get("annotations") or {})
        ann[EVICTED_ANNOTATION] = intent
        w["annotations"] = ann
        self.store.create_pod(pod_from_wire(w))
        with self._lock:
            self._repl_append({"kind": "evictions", "type": "EVICT",
                               "object": {"uid": uid, "intent": intent,
                                          "node": bound_to}})
        self.evictions[uid] = intent
        self.pod_evictions += 1
        return 200, {"evicted": True, "node": bound_to}

    @staticmethod
    def _pdb_threshold(value, total: int, round_up: bool) -> int:
        """One PDB field — an int or an ``"N%"`` string — resolved against
        the budget's matched-pod census (the reference's
        GetScaledValueFromIntOrPercent split): minAvailable percentages
        round UP (protect at least that share), maxUnavailable percentages
        round DOWN (never disrupt more than that share)."""
        if isinstance(value, str) and value.rstrip().endswith("%"):
            pct = int(value.rstrip()[:-1] or 0)
            scaled = pct * total
            return -(-scaled // 100) if round_up else scaled // 100
        return int(value or 0)

    def _pdb_blocks_eviction(self, pod) -> Optional[dict]:
        """PodDisruptionBudget precondition for VOLUNTARY disruptions
        (eviction subresource, ?voluntary=true deletes). Caller holds the
        write lock. Returns a 429 payload when committing the disruption
        would take a selected workload below its budget floor, else None.

        ``available`` counts BOUND pods (node_name set) in the PDB's
        namespace matching its selector — the same census the chaos suite
        polls; ``matched`` counts every selected pod bound or not (the
        workload-size base percentages and maxUnavailable scale against —
        disruption.go's expectedCount stand-in). Either budget form gates:
        minAvailable blocks when the post-eviction bound count would dip
        below the floor; maxUnavailable blocks when it would dip below
        ``matched - maxUnavailable``. Both present ⇒ both must pass. An
        empty matchLabels selector matches NOTHING (a typo'd PDB must not
        accidentally freeze the whole cluster). Involuntary paths (zone
        Full, node delete) never call this — exactly the reference's
        split (disruption.go guards the Eviction subresource, not the
        node controller's deletes)."""
        labels = pod.labels or {}
        ns = getattr(pod, "namespace", "") or "default"
        for key, pdb in self.workloads["pdbs"].items():
            if (pdb.get("namespace") or "default") != ns:
                continue
            sel = pdb.get("matchLabels") or {}
            if not sel:
                continue
            if any(labels.get(k) != v for k, v in sel.items()):
                continue
            matched = [
                p for p in self.store.pods.values()
                if (getattr(p, "namespace", "") or "default") == ns
                and all((p.labels or {}).get(k) == v
                        for k, v in sel.items())]
            available = sum(1 for p in matched if p.node_name)
            total = len(matched)
            min_avail = self._pdb_threshold(
                pdb.get("minAvailable", 0), total, round_up=True)
            if available - 1 < min_avail:
                return {"error": "DisruptionBudget",
                        "pdb": pdb.get("name", key),
                        "available": available,
                        "matched": total,
                        "minAvailable": min_avail}
            if pdb.get("maxUnavailable") is not None:
                max_unavail = self._pdb_threshold(
                    pdb["maxUnavailable"], total, round_up=False)
                if available - 1 < total - max_unavail:
                    return {"error": "DisruptionBudget",
                            "pdb": pdb.get("name", key),
                            "available": available,
                            "matched": total,
                            "maxUnavailable": max_unavail}
        return None

    def _workload_upsert_locked(self, kind: str, body,
                                create: bool = False):
        """Create/upsert one workload object (WORKLOAD_KINDS). Caller
        holds the write lock. The broadcast IS the commit: WAL record,
        watch-cache upsert, stream fanout — same ordering as every store
        kind, with the server-owned wire dict standing in for the store.
        Create answers 409 AlreadyExists on a duplicate name — the
        retry-safe half of the controllers' exactly-once contract."""
        if not isinstance(body, dict) or not body.get("name"):
            return 400, {"error": "name required"}
        w = dict(body)
        ns = w.get("namespace") or "default"
        w["namespace"] = ns
        w.setdefault("uid", f"{kind}/{ns}/{w['name']}")
        key = f"{ns}/{w['name']}"
        exists = key in self.workloads[kind]
        if create and exists:
            return 409, {"error": "AlreadyExists"}
        self.workloads[kind][key] = w
        self._broadcast(kind, {"type": "MODIFIED" if exists else "ADDED",
                               "object": w})
        return (201 if create else 200), w

    def _workload_delete_locked(self, kind: str, ns: str, name: str):
        key = f"{ns or 'default'}/{name}"
        w = self.workloads[kind].pop(key, None)
        if w is None:
            return 404, {"error": "not found"}
        self._broadcast(kind, {"type": "DELETED", "object": w})
        return 200, {}

    def _attach_watch(self, kind: str, since: Optional[int] = None,
                      epoch: Optional[str] = None,
                      flt: Optional[ShardFilter] = None,
                      paged: bool = False,
                      fresh: bool = False) -> _WatchStream:
        """Attach a watch under the broadcast lock, THEN register for live
        events — no create can fall between snapshot and registration.
        The snapshot and the resume ring both serve from the watch cache
        (never the store dicts, never the write lock).

        since=None (or outside the ring window, or an epoch from another
        server instance): resourceVersion=0 semantics — ADDED for every
        existing object, then a SYNC marker carrying the current rv +
        epoch. since=N inside the window with a matching epoch: a RESUME
        marker, then a replay of exactly the events with rv > N. A shard
        filter (``flt``) routes both replays; a filtered RESUME against a
        selector-ful cluster re-lists instead (the per-stream slim set
        died with the old connection — see core/watchcache.py)."""
        st = _WatchStream(flt)
        wc = self.watch_cache[kind]
        with self._lock:
            seq = self._seq[kind]
            tail = None
            # Resumable iff the rv names THIS server's history (epoch) and
            # NOTHING after `since` was compacted away. Anything else —
            # unknown epoch (server restarted, counters reset), a future
            # rv, a pruned ring window — full-re-lists, never silently
            # resumes (events_since counts the 410-too-old case). A
            # selector-ful FILTERED resume is refused (the old stream's
            # slim set died with it) UNLESS `fresh` marks this attach as
            # the one straight after a completed paged re-list: that
            # client's cache was just rebuilt from full objects, and
            # nothing slims while selector_refs > 0, so there is no slim
            # set to lose.
            resumable = (since is not None and epoch == self.epoch
                         and since <= seq)
            if (resumable and flt is not None and wc.selector_refs > 0
                    and not fresh):
                resumable = False
            if resumable:
                tail = wc.events_since(since)
            if tail is not None:
                st.q.put(wire.WireItem({"type": "RESUME", "rv": seq,
                                        "epoch": self.epoch}))
                for _rv, event, data in tail:
                    self._route_to(st, event, data, wc)
                if flt is not None:
                    # Prime AFTER the replay: the fresh filter's empty slim
                    # map means no replayed event can be suppressed (the
                    # primed projections are built from the CURRENT
                    # snapshot — priming first would make a replayed
                    # MODIFIED that produced that very state compare equal
                    # and be dropped, losing e.g. a deletionTs the client
                    # missed while disconnected). Priming afterwards only
                    # seeds the upgrade set for a later selector
                    # transition.
                    flt.prime(wc)
                    if wc.selector_refs > 0:
                        # Only reachable on a `fresh` attach (non-fresh
                        # selector-ful filtered resumes are refused
                        # above): the paged list that just rebuilt this
                        # client slimmed while refs were still 0, and a
                        # selector source landed in the list→attach gap.
                        # Upgrade everything the list slimmed NOW — the
                        # in-band burst in route() only fires on the
                        # next event, which a quiet cluster may never
                        # send.
                        for item in flt.upgrade_all(wc):
                            st.q.put(item)
                self.resumed_watches += 1
            elif paged and since is not None:
                # A paged client re-lists through `?limit=&continue=`
                # (Replace semantics, bounded pages) instead of consuming
                # a full ADDED replay materialized into this queue: tell
                # it the resume window is gone and close the stream — it
                # re-lists, then re-attaches with fresh=true at the list
                # anchor.
                st.q.put(wire.WireItem({"type": "TOO_OLD", "rv": seq,
                                        "epoch": self.epoch}))
                st.q.put(None)
            else:
                # Lazy-cursor replay (the legacy path materialized a full
                # ADDED event per object INTO this queue, under the
                # broadcast lock — at 50k nodes that is the whole cluster
                # encoded per attaching client). Now the attach only
                # records the snapshot rv; the stream's consumer thread
                # pages the watch-cache snapshot itself (list_page, the
                # cache's own lock) and emits SYNC at this rv. Live events
                # queue from here on as usual — an object mutated while
                # paging upserts twice (pages serve current copy-on-write
                # state), which the client's replayed-ADDED upsert path
                # already absorbs.
                st.replay_rv = seq
                st.replay_epoch = self.epoch
                if flt is not None and wc.selector_refs == 0:
                    # Seed the filter's slim map for the objects the page
                    # replay will slim (pre-attach pods); pods created
                    # DURING the replay are recorded by their own queued
                    # live events routing through the filter. The replay
                    # slims IFF this prime ran (st.replay_slim): decision
                    # and bookkeeping are frozen together, so a
                    # selector_refs flip mid-replay can't produce slims
                    # the upgrade burst has no record of.
                    flt.prime(wc)
                    st.replay_slim = True
                self.relisted_watches += 1
            self._watchers[kind].append(st)
        return st

    def _detach_watch(self, kind: str, st: _WatchStream) -> None:
        with self._lock:
            if st in self._watchers[kind]:
                self._watchers[kind].remove(st)

    # -- http --------------------------------------------------------------

    def serve(self, port: int = 0) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # The handler writes responses as several small send()s (status
            # line, headers, body) and clients send headers/body the same
            # way: with Nagle on, each small segment waits on the peer's
            # delayed ACK — measured ~3.8ms/request on LOOPBACK (≈260
            # writes/s ceiling on an idle server). TCP_NODELAY on both
            # sides (see KeepAliveClient) lifts the write plane ~4x.
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            def setup(self):
                super().setup()
                server._conns.add(self.connection)

            def finish(self):
                server._conns.discard(self.connection)
                super().finish()

            def _read_body(self) -> dict:
                # Socket I/O — must run OUTSIDE the write lock (a stalled
                # sender would otherwise wedge the whole write plane).
                # Sniff-decoded (core/wire.py): a negotiated client sends
                # binary frames (bulk bindings, bulk creates), everything
                # else stays the JSON compat plane.
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                self._body_len = len(raw)
                self._body_codec = (wire.BINARY if raw[0] == wire.MAGIC
                                    else wire.JSON)
                return wire.decode(raw)

            def _body(self) -> dict:
                return self._body_cache

            def _accept(self) -> str:
                """This request's negotiated reply codec (Accept:-style;
                core/wire.py). Error bodies stay JSON regardless — the
                debug plane."""
                if server.json_only:
                    return wire.JSON
                return wire.accept_codec(self.headers.get("Accept"))

            def _json(self, code: int, obj,
                      surface: Optional[str] = None,
                      retry_after: Optional[int] = None) -> None:
                codec = self._accept() if code < 400 else wire.JSON
                _t0 = time.perf_counter()
                data = wire.encode(obj, codec)
                if surface is not None:
                    server._count_encode_us(surface,
                                            time.perf_counter() - _t0)
                    server._count_wire(codec, surface, len(data))
                self.send_response(code)
                self.send_header("Content-Type", wire.mime_for(codec))
                if retry_after is not None:
                    # The shed contract (core/flowcontrol.py): a 429 always
                    # carries Retry-After — the client half honors it with
                    # decorrelated jitter (core/backoff.py), so shed work
                    # returns after the backlog horizon, never as a
                    # synchronized retry storm.
                    self.send_header("Retry-After", str(int(retry_after)))
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _flow_namespace(self) -> str:
                """The tenant namespace this mutating request bills to
                (workload flow key). Binding/delete paths carry only a uid;
                the pod's namespace resolves through the store dict (a
                GIL-atomic get — no lock, a racing delete just falls back
                to the default flow)."""
                path, body = self.path, self._body_cache
                if path in ("/api/v1/pods", "/api/v1/podgroups") \
                        or path.split("?")[0] in tuple(
                            f"/api/v1/{k}" for k in WORKLOAD_KINDS):
                    if isinstance(body, list):
                        return (body[0].get("namespace", "")
                                if body else "")
                    if isinstance(body, dict):
                        return body.get("namespace", "")
                    return ""
                uid = ""
                if path == "/api/v1/bindings":
                    if isinstance(body, list) and body:
                        uid = body[0].get("uid", "")
                elif path.startswith("/api/v1/pods/"):
                    parts = path.split("/")
                    uid = parts[4] if len(parts) > 4 else ""
                if uid:
                    pod = server.store.pods.get(uid)
                    if pod is not None:
                        return pod.namespace
                return ""

            def _flow_admit(self, method: str):
                """Admission through the priority-and-fairness plane
                (core/flowcontrol.py) — BEFORE `_write_lock`, always. A
                shed request is answered 429 + Retry-After right here
                (returns None); the caller must release the ticket in a
                finally once the write plane is done with it."""
                fc = server.flowcontrol
                level, flow = fc.classify(method, self.path,
                                          self._flow_namespace())
                ticket = fc.admit(level, flow)
                if ticket is None:
                    ra = fc.retry_after(level)
                    self._json(429, {"error": "TooManyRequests",
                                     "retryAfter": ra}, retry_after=ra)
                return ticket

            def do_GET(self):
                path, _, query = self.path.partition("?")
                watch = "watch=true" in query
                paged = "paged=true" in query
                fresh = "fresh=true" in query
                since, epoch, flt, uids = None, None, None, None
                limit, cont = 0, ""
                for part in query.split("&"):
                    if part.startswith("resourceVersion="):
                        try:
                            since = int(part.split("=", 1)[1])
                        except ValueError:
                            pass
                    elif part.startswith("epoch="):
                        epoch = part.split("=", 1)[1]
                    elif part.startswith("limit="):
                        try:
                            limit = int(part.split("=", 1)[1])
                        except ValueError:
                            pass
                    elif part.startswith("continue="):
                        cont = part.split("=", 1)[1]
                    elif part.startswith("shard="):
                        # Server-side shard-filtered stream: shard=i/n
                        # applies the shard/partition.py crc32 map HERE,
                        # so a shard's decode cost scales with 1/n. A spec
                        # that names no real slot (count<=0, index out of
                        # range) is IGNORED, not coerced — a coerced
                        # filter would slim every pod including the
                        # stream owner's own.
                        try:
                            i, _, n = part.split("=", 1)[1].partition("/")
                            idx, cnt = int(i), int(n)
                            if cnt >= 1 and 0 <= idx < cnt:
                                flt = ShardFilter(idx, cnt)
                        except ValueError:
                            pass
                    elif part.startswith("uids="):
                        uids = [u for u in
                                part.split("=", 1)[1].split(",") if u]
                if path == "/api/v1/pods":
                    if watch:
                        return self._stream("pods", since, epoch, flt,
                                            paged=paged, fresh=fresh)
                    # Every non-watch read below serves from the watch
                    # cache under ITS lock — no store-dict iteration, no
                    # write-lock contention, and safe against concurrent
                    # mutation by construction.
                    if "summary=true" in query:
                        # Progress-poll surface: counting is ~3 orders of
                        # magnitude cheaper than wire-encoding the full
                        # list, and pollers (bench/chaos harnesses) only
                        # need the counts — at 10k pods a full-list poll
                        # every 0.5s costs the control plane more CPU than
                        # the binds themselves.
                        s = server.watch_cache["pods"].read_summary()
                        return self._json(200, {"total": s["total"],
                                                "bound": s["bound"]})
                    if uids is not None:
                        # Hydration read (shard adoption): full wire for
                        # pods a filtered stream delivered slim.
                        return self._json(
                            200, server.watch_cache["pods"].get_many(uids))
                    if limit:
                        # Paged LIST (docs/SCALE.md): bounded pages with
                        # rv-anchored continuation tokens — the 50k-node
                        # read path. The whole cluster never rides one
                        # response body.
                        return self._list_paged("pods", limit, cont, flt)
                    server.list_unpaged += 1
                    return self._json(200,
                                      server.watch_cache["pods"].list_wire())
                if path == "/api/v1/nodes":
                    if watch:
                        return self._stream("nodes", since, epoch,
                                            paged=paged, fresh=fresh)
                    if limit:
                        return self._list_paged("nodes", limit, cont)
                    server.list_unpaged += 1
                    return self._json(200,
                                      server.watch_cache["nodes"].list_wire())
                if path == "/api/v1/nodes/heartbeats":
                    # Heartbeat ages are LEADER-LOCAL (the sink is never
                    # WAL'd): a follower answering from its empty/stale map
                    # would age out the whole fleet — 421 so the lifecycle
                    # controller's client leader-routes this GET.
                    if server.role != "leader":
                        return self._json(421, {"error": "NotLeader",
                                                "leader": server.leader_url})
                    return self._json(200, {"ages": server.heartbeat_ages()})
                if path == "/api/v1/podgroups":
                    if watch:
                        return self._stream("podgroups", since, epoch,
                                            paged=paged, fresh=fresh)
                    if limit:
                        return self._list_paged("podgroups", limit, cont)
                    server.list_unpaged += 1
                    return self._json(
                        200, server.watch_cache["podgroups"].list_wire())
                for wk in WORKLOAD_KINDS:
                    if path == f"/api/v1/{wk}":
                        if watch:
                            return self._stream(wk, since, epoch,
                                                paged=paged, fresh=fresh)
                        if limit:
                            return self._list_paged(wk, limit, cont)
                        server.list_unpaged += 1
                        return self._json(
                            200, server.watch_cache[wk].list_wire())
                if path == "/flow":
                    # APF admin surface: current per-level weights + live
                    # admission counters (the POST half re-weights).
                    return self._json(
                        200, {"levels": server.flowcontrol.snapshot(),
                              "weights": server.flowcontrol.weights()})
                if path == "/metrics/resources":
                    # kube_pod_resource_request rendered straight from the
                    # watch cache's wire snapshot: harness pollers scrape
                    # this from FOLLOWER replicas, off the leader entirely.
                    data = server.watch_cache["pods"].render_resources()
                    data = data.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if path == "/api/v1/leases":
                    return self._json(200, server.list_leases())
                if path == "/replication/status":
                    return self._json(200, server.replication_status())
                if path == "/replication/snapshot":
                    if limit:
                        # Streaming paged bootstrap (docs/SCALE.md): meta
                        # under the locks, object pages streamed from the
                        # watch cache OUTSIDE every lock — a 50k-node
                        # bootstrap neither stalls the write plane for
                        # the encode nor rides one response body.
                        return self._snapshot_stream(limit)
                    # Legacy single-body bootstrap: a consistent full-state
                    # snapshot. Encode UNDER the locks (no write can
                    # interleave), send after releasing them — the socket
                    # write must never run under a held lock.
                    with server._write_lock:
                        with server._lock:
                            snap = server._snapshot_state()
                    return self._json(200, snap)
                if path == "/replication/wal":
                    since, repl_epoch, leader_hint, hb = 0, None, "", 1.0
                    for part in query.split("&"):
                        k, _, v = part.partition("=")
                        try:
                            if k == "from":
                                since = int(v)
                            elif k == "epoch":
                                repl_epoch = int(v)
                            elif k == "hb":
                                hb = max(0.05, float(v))
                        except ValueError:
                            pass
                        if k == "leader":
                            leader_hint = v
                    return self._ship(since, repl_epoch, leader_hint, hb)
                if path == "/metrics":
                    data = server.expose_metrics().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                self._json(404, {"error": "not found"})

            def _write_chunk(self, data: bytes) -> None:
                self.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n")

            def _list_paged(self, kind: str, limit: int, token: str,
                            flt: Optional[ShardFilter] = None) -> None:
                """One page of `?limit=&continue=`: up to `limit` objects
                as chunked json lines (the ship stream's framing) + a PAGE
                trailer carrying the continuation token, the list-anchor
                rv (`listRv` — what the client attaches its watch at) and
                the epoch. Serves entirely from the watch cache under ITS
                lock; an anchor that fell off the resume ring answers 410
                and the client restarts its list."""
                wc = server.watch_cache[kind]
                last_key, anchor = "", None
                if token:
                    tok = parse_continue(token)
                    if tok is None or tok.get("e") != server.epoch:
                        server.list_continue_410 += 1
                        return self._json(410, {"error": "ExpiredContinue"})
                    last_key, anchor = tok.get("k", ""), int(tok.get("rv", 0))
                page = wc.list_page(limit, last_key=last_key,
                                    anchor_rv=anchor)
                if page is None:
                    server.list_continue_410 += 1
                    return self._json(410, {"error": "ExpiredContinue"})
                objs, next_key, anchor, rv = page
                server.list_pages += 1
                codec = self._accept()
                # Slim foreign plain pods through the shard filter exactly
                # as the watch plane would deliver them (selector-free
                # clusters only — core/watchcache.py).
                slim_ok = (flt is not None and kind == "pods"
                           and wc.selector_refs == 0)
                try:
                    # Headers inside the guard too: a client that closed
                    # between request and response must tear only THIS
                    # handler, quietly.
                    self.send_response(200)
                    self.send_header("Content-Type", wire.mime_for(codec))
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    buf = bytearray()
                    sent = 0
                    enc_s = 0.0
                    for obj in objs:
                        if (slim_ok and wire_plain(obj)
                                and shard_of_wire(obj, flt.count)
                                != flt.index):
                            obj = slim_object(obj)
                            server.watch_slim_events += 1
                        _t0 = time.perf_counter()
                        buf += wire.encode({"type": "ADDED", "object": obj},
                                           codec)
                        enc_s += time.perf_counter() - _t0
                        if len(buf) >= 65536:
                            sent += len(buf)
                            self._write_chunk(bytes(buf))
                            buf.clear()
                    trailer = {"type": "PAGE", "rv": rv, "listRv": anchor,
                               "epoch": server.epoch}
                    if next_key:
                        trailer["continue"] = mint_continue(
                            anchor, next_key, server.epoch)
                    _t0 = time.perf_counter()
                    buf += wire.encode(trailer, codec)
                    server._count_encode_us(
                        "list", enc_s + time.perf_counter() - _t0)
                    server._count_wire(codec, "list", sent + len(buf))
                    self._write_chunk(bytes(buf))
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self.close_connection = True

            def _snapshot_stream(self, limit: int) -> None:
                """Streaming replication bootstrap: SNAP_META (the control
                cut — seq map, repl seq/epoch, leases — captured under the
                locks), then object pages from the watch cache streamed
                OUTSIDE every lock, then SNAP_END. Objects may be AHEAD of
                the meta seq; the follower re-tails from meta seq and the
                frame replay upsert-heals every difference (docs/SCALE.md
                bootstrap contract). A torn stream (no SNAP_END) is never
                installed."""
                with server._write_lock:
                    with server._lock:
                        meta = {
                            "epoch": server.epoch,
                            "seq": dict(server._seq),
                            "repl": {"seq": server._repl_seq,
                                     "epoch": server.repl_epoch},
                            "leases": [dict(rec, name=name, renew=None)
                                       for name, rec in
                                       list(server.leases.items())],
                            # Intent ledger rides the meta cut (small,
                            # bounded): a bootstrapping replica must
                            # answer an in-flight wave's retries
                            # idempotently from its very first frame.
                            "evictions": [
                                {"uid": u, "intent": i} for u, i in
                                list(server.evictions.items())],
                            "role": server.role,
                        }
                codec = self._accept()
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", wire.mime_for(codec))
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    sent = 0
                    data = wire.encode({"type": "SNAP_META", **meta}, codec)
                    sent += len(data)
                    self._write_chunk(data)
                    for kind in ("pods", "nodes", "podgroups") \
                            + WORKLOAD_KINDS:
                        last = ""
                        while True:
                            objs, next_key, _a, _rv = (
                                server.watch_cache[kind].list_page(
                                    limit, last_key=last))
                            server.snapshot_bootstrap_pages += 1
                            buf = bytearray()
                            enc_s = 0.0
                            for obj in objs:
                                _t0 = time.perf_counter()
                                buf += wire.encode(
                                    {"kind": kind, "object": obj}, codec)
                                enc_s += time.perf_counter() - _t0
                                if len(buf) >= 65536:
                                    sent += len(buf)
                                    self._write_chunk(bytes(buf))
                                    buf.clear()
                            server._count_encode_us("snapshot", enc_s)
                            if buf:
                                sent += len(buf)
                                self._write_chunk(bytes(buf))
                            if not next_key:
                                break
                            last = next_key
                    data = wire.encode({"type": "SNAP_END"}, codec)
                    sent += len(data)
                    self._write_chunk(data)
                    server._count_wire(codec, "snapshot", sent)
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self.close_connection = True

            def _replay_lazy(self, kind: str, st, codec: str,
                             enc=None) -> None:
                """The attach-time replay as a lazy cursor into the watch
                cache's snapshot: bounded pages in sorted-key order
                (list_page — the cache's own lock, never the broadcast or
                write lock), encoded and sent on this stream's consumer
                thread. Shard filters slim statelessly here, exactly as
                the paged LIST plane does; live events committed while
                paging are already queued and upsert over the replay."""
                wc = server.watch_cache[kind]
                flt = st.filter
                last = ""
                sent = 0
                while server._httpd is not None:
                    page = wc.list_page(500, last_key=last)
                    if page is None:  # unanchored pages never expire
                        break
                    objs, next_key, _anchor, _rv = page
                    server.watch_replay_pages += 1
                    buf = bytearray()
                    for obj in objs:
                        if (st.replay_slim and kind == "pods"
                                and wire_plain(obj)
                                and shard_of_wire(obj, flt.count)
                                != flt.index):
                            obj = slim_object(obj)
                            server.watch_slim_events += 1
                        ev = {"type": "ADDED", "object": obj}
                        _t0 = time.perf_counter()
                        # Replay frames ride the session table too — the
                        # whole cluster's names intern once, so the live
                        # tail that follows ships refs from frame one.
                        data = (enc.encode(ev) if enc is not None
                                else wire.encode(ev, codec))
                        server._count_encode_us(
                            "watch", time.perf_counter() - _t0)
                        sent += len(data)
                        buf += f"{len(data):x}\r\n".encode() + data + b"\r\n"
                        if len(buf) >= 65536:
                            self.wfile.write(bytes(buf))
                            buf.clear()
                    if buf:
                        self.wfile.write(bytes(buf))
                    self.wfile.flush()
                    if not next_key:
                        break
                    last = next_key
                server._count_wire(codec, "watch", sent)

            def _stream(self, kind: str, since: Optional[int] = None,
                        epoch: Optional[str] = None,
                        flt: Optional[ShardFilter] = None,
                        paged: bool = False, fresh: bool = False) -> None:
                # watch.Interface: hold the connection open, one JSON event
                # per line (chunked); blocking queue — no idle polling. A
                # BOOKMARK heartbeat goes out on idle (~10s) so a quiet
                # cluster keeps the client's read timeout from killing the
                # watch (the reference's watch bookmarks serve the same
                # liveness role).
                codec = self._accept()
                enc = None
                if codec == wire.BINARY and wire.accept_session(
                        self.headers.get("Accept")):
                    # Session intern table: per-connection, constructed
                    # and touched ONLY on this consumer thread (never the
                    # broadcast lock) — the second half of the analyzer's
                    # delta-base-under-cache-lock rule. Its MIME also
                    # signals delta capability: WireItems queued here may
                    # encode as DELTA records against the client's cache.
                    enc = wire.SessionEncoder()
                self.send_response(200)
                self.send_header("Content-Type",
                                 wire.mime_for(codec,
                                               session=enc is not None))
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                st = server._attach_watch(kind, since, epoch, flt,
                                          paged=paged, fresh=fresh)
                idle = 0.0
                try:
                    if st.replay_rv is not None:
                        # Lazy-cursor attach replay: page the snapshot on
                        # THIS consumer thread (watch-cache lock only, one
                        # bounded page at a time — the full cluster never
                        # materializes in the stream queue or under the
                        # broadcast lock), then SYNC at the attach rv.
                        self._replay_lazy(kind, st, codec, enc)
                        data = wire.encode(
                            {"type": "SYNC", "rv": st.replay_rv,
                             "epoch": st.replay_epoch}, codec)
                        server._count_wire(codec, "watch", len(data))
                        self._write_chunk(data)
                        self.wfile.flush()
                    while server._httpd is not None:
                        try:
                            data = st.q.get(timeout=0.5)
                            idle = 0.0
                        except queue.Empty:
                            idle += 0.5
                            if idle < 10.0:
                                continue
                            idle = 0.0
                            data = wire.encode({"type": "BOOKMARK"}, codec)
                        if data is None:
                            # Stream-end sentinel (snapshot RESYNC skipped
                            # frames): close; the client re-lists fresh.
                            break
                        # Encode HERE, on this stream's own thread, in
                        # THIS stream's codec — never under the broadcast
                        # lock the fanout path holds; WireItems cache the
                        # result so it happens once per codec, not per
                        # stream (session frames are per-connection and
                        # never cached).
                        _t0 = time.perf_counter()
                        data = encode_stream_item(data, codec, enc)
                        server._count_encode_us(
                            "watch", time.perf_counter() - _t0)
                        server._count_wire(codec, "watch", len(data))
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    server._detach_watch(kind, st)
                    # End of stream (server shutdown): close the TCP
                    # connection instead of waiting for another request on
                    # it, so the client's reflector sees EOF immediately
                    # and re-lists against the next server.
                    self.close_connection = True

            def _ship(self, since: int, repl_epoch: Optional[int],
                      leader_hint: str, hb: float) -> None:
                """Replication ship stream: WAL frames with seq > `since`,
                one json line per chunk, heartbeats (`HB`, carrying the
                head seq + fencing epoch) on idle. The queue is loaded and
                registered under the broadcast lock (_attach_ship); every
                socket send happens OUT HERE, lock-free — a slow follower
                backpressures only its own queue, never the write plane."""
                from urllib.parse import unquote
                if repl_epoch is not None and repl_epoch > server.repl_epoch:
                    # The follower has seen a newer generation: this
                    # replica was deposed while partitioned. Fence off.
                    # The hint is the follower's TAIL TARGET — by
                    # construction this very server — so it never names
                    # the winner: demote without a redirect target and
                    # let clients re-resolve through status probing.
                    hint = unquote(leader_hint).rstrip("/")
                    if hint == server.advertise_url:
                        hint = ""
                    server.demote(hint, repl_epoch)
                    return self._json(409, {
                        "error": "StaleEpoch",
                        "replEpoch": server.repl_epoch})
                st = server._attach_ship(since)
                if st is None:
                    # The ship window no longer covers `since` (compaction
                    # outran the follower): 410 Gone — snapshot bootstrap.
                    return self._json(410, {"error": "ResyncRequired",
                                            "seq": server._repl_seq})
                codec = self._accept()
                enc = None
                if codec == wire.BINARY and wire.accept_session(
                        self.headers.get("Accept")):
                    # Session ship stream: per-connection intern table on
                    # THIS handler thread, and the delta-capability
                    # signal — DELTA twins ship as-is; the follower
                    # materializes against its own watch-cache base.
                    enc = wire.SessionEncoder()
                self.send_response(200)
                self.send_header("Content-Type",
                                 wire.mime_for(codec,
                                               session=enc is not None))
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while server._httpd is not None and not st.dead:
                        try:
                            seq, item = st.q.get(timeout=hb)
                            # Shared frame WireItem: the plain encode is
                            # cached per codec, so N binary followers
                            # reuse the WAL append's bytes; session
                            # followers get the delta twin when one was
                            # minted.
                            _t0 = time.perf_counter()
                            data = (item.session_bytes(enc)
                                    if enc is not None
                                    else item.bytes(codec))
                            server._count_encode_us(
                                "ship", time.perf_counter() - _t0)
                        except queue.Empty:
                            seq = None
                            # HBs carry this replica's ROLE: a follower
                            # tailing a stream whose server was deposed
                            # must not count these as leader liveness.
                            hb_ev = {"type": "HB", "seq": server._repl_seq,
                                     "epoch": server.repl_epoch,
                                     "role": server.role}
                            data = (enc.encode(hb_ev) if enc is not None
                                    else wire.encode(hb_ev, codec))
                        server._count_wire(codec, "ship", len(data))
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                        if seq is not None:
                            server._ship_mark_sent(st, seq)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    server._detach_ship(st)
                    self.close_connection = True

            def do_POST(self):
                self._body_cache = self._read_body()
                if self.path == "/replication/peers":
                    # Replication-internal wiring (accepted in ANY role):
                    # the harness injects the rank -> base URL map after
                    # every replica's ephemeral port is known. Not WAL'd —
                    # topology, not state. Exempt lane by construction:
                    # answered before admission ever runs.
                    server.flowcontrol.count_exempt()
                    server.repl_peers = {
                        int(k): v for k, v in
                        (self._body().get("peers") or {}).items()}
                    return self._json(200, {"peers": len(server.repl_peers)})
                if self.path == "/replication/leader":
                    # Promotion announcement (accepted in ANY role): the
                    # freshly promoted leader pushes its generation to
                    # every peer, so surviving followers re-tail
                    # immediately (instead of waiting out their own
                    # silence detection) and a stale co-leader demotes
                    # itself even though no follower ever tails it. Two
                    # followers promoting CONCURRENTLY land on the same
                    # epoch — the rank tie-break (lower announcer rank
                    # wins) stands one of them down; its forked tail
                    # resolves via snapshot resync on re-attach.
                    server.flowcontrol.count_exempt()
                    body = self._body()
                    ep = int(body.get("epoch", 0))
                    rank = int(body.get("rank", 1 << 30))
                    url = (body.get("leader") or "").rstrip("/")
                    if server.role == "leader":
                        if (ep > server.repl_epoch
                                or (ep == server.repl_epoch
                                    and rank < server.replica_rank)):
                            server.demote(url, ep)
                    elif url and ep >= server.repl_epoch:
                        server.note_leader(url, ep)
                    return self._json(200, {"replEpoch": server.repl_epoch})
                if self.path == "/flow":
                    # Live APF re-weight (operator plane, accepted in ANY
                    # role — each replica admits with its own controller).
                    # Applied under the FlowController's OWN lock, never
                    # the write lock: re-weighting mid-storm must not queue
                    # behind the flooded write plane it is trying to fix.
                    server.flowcontrol.count_exempt()
                    body = self._body()
                    level = str(body.get("level") or "")
                    try:
                        got = server.flowcontrol.set_weights(
                            level, body.get("weights") or {})
                    except KeyError:
                        return self._json(404, {"error": "unknown level"})
                    except ValueError as e:
                        return self._json(400, {"error": str(e)})
                    return self._json(200, {"level": level, "weights": got})
                if server.role != "leader":
                    return self._json(421, {"error": "NotLeader",
                                            "leader": server.leader_url})
                # Flow-control admission strictly BEFORE the write lock: a
                # shed request (429 + Retry-After, sent inside _flow_admit)
                # must never have contended for — let alone held — the
                # write plane's lock.
                ticket = self._flow_admit("POST")
                if ticket is None:
                    return
                try:
                    with server._write_lock:
                        if server.role != "leader":
                            # Re-checked UNDER the lock: a demote() racing
                            # the unlocked fast-path check above must not
                            # let this write commit on a freshly deposed
                            # replica (it would be stamped with the
                            # WINNER's epoch — unfenceable divergence).
                            code, obj, seq = 421, {
                                "error": "NotLeader",
                                "leader": server.leader_url}, 0
                        else:
                            code, obj = self._post_locked()
                            seq = server._repl_seq
                    # Reply gating, OUTSIDE every lock: an acked write is
                    # on the wire to each in-quorum follower before the
                    # client hears 200 — a leader kill -9 cannot silently
                    # lose it.
                    server._await_shipped(seq)
                finally:
                    server.flowcontrol.release(ticket)
                if self.path == "/api/v1/bindings":
                    # Bulk-binding wire accounting: the request envelope
                    # (in its sniffed codec) and the per-item verdict
                    # reply (negotiated) both land on the same surface.
                    server._count_wire(self._body_codec, "bindings",
                                       self._body_len)
                    return self._json(code, obj, surface="bindings")
                self._json(code, obj)

            def _post_locked(self):
                if self.path == "/api/v1/pods":
                    body = self._body()
                    if isinstance(body, list):
                        # Bulk create: one request, one lock acquisition,
                        # one HTTP turnaround for a whole creation burst.
                        # Per-object creates cost ~1.5ms of control-plane
                        # turnaround each under load — at 10k pods that is
                        # ~45s of a 60s sharded bench spent just ARRIVING.
                        # Wire semantics match looped single creates: one
                        # ADDED event per pod (watchers see no difference),
                        # duplicates skipped and reported, never re-fired.
                        dup = 0
                        for w in body:
                            pod = pod_from_wire(w)
                            if pod.uid in server.store.pods:
                                dup += 1
                                continue
                            server.store.create_pod(pod)
                            if pod.node_name:
                                server._usage_apply(pod.node_name, pod, +1)
                        return 201, {"created": len(body) - dup,
                                     "alreadyExists": dup}
                    pod = pod_from_wire(body)
                    # AlreadyExists (409, like the reference registry):
                    # duplicate creates — e.g. a client retrying a write
                    # whose reply was lost — must not re-fire ADDED events
                    # or reset a pod the scheduler already bound.
                    if pod.uid in server.store.pods:
                        return 409, {"error": "AlreadyExists"}
                    server.store.create_pod(pod)
                    if pod.node_name:  # created pre-bound: commit its usage
                        server._usage_apply(pod.node_name, pod, +1)
                    return 201, pod_to_wire(pod)
                if self.path == "/api/v1/nodes":
                    body = self._body()
                    if isinstance(body, list):
                        dup = 0
                        for w in body:
                            node = node_from_wire(w)
                            if node.name in server.store.nodes:
                                dup += 1
                                continue
                            server.store.create_node(node)
                            server._note_heartbeats((node.name,))
                        return 201, {"created": len(body) - dup,
                                     "alreadyExists": dup}
                    node = node_from_wire(body)
                    if node.name in server.store.nodes:
                        return 409, {"error": "AlreadyExists"}
                    server.store.create_node(node)
                    # Registration counts as the first heartbeat: a node is
                    # never born already-silent.
                    server._note_heartbeats((node.name,))
                    return 201, node_to_wire(node)
                if (self.path.startswith("/api/v1/nodes/")
                        and self.path.endswith("/status")):
                    # Kubelet heartbeat sink (parity stub, no event). The
                    # hollow plane's bulk form (`/api/v1/nodes/status`,
                    # {"names": [...]}) rides the same branch — one
                    # request per fleet slice, counted per node. Each name
                    # stamps the lifecycle controller's freshness map.
                    body = self._body()
                    names = (body.get("names") if isinstance(body, dict)
                             else None) or ()
                    if not names:
                        nm = self.path.split("/")[4]
                        names = (nm,) if nm != "status" else ()
                    # The bulk form is the largest client->server stream
                    # at hollow scale: attribute its request bytes to the
                    # "status" surface so the bench proves which codec
                    # actually carried it.
                    server._count_wire(self._body_codec, "status",
                                       self._body_len)
                    server.node_heartbeats += max(1, len(names))
                    server._note_heartbeats(names)
                    return 200, {}
                if self.path == "/api/v1/podgroups":
                    body = self._body()
                    g = pod_group_from_wire(body)
                    target = (server.store.composite_pod_groups
                              if body.get("composite")
                              else server.store.pod_groups)
                    if f"{g.namespace}/{g.name}" in target:
                        return 409, {"error": "AlreadyExists"}
                    if body.get("composite"):
                        server.store.create_composite_pod_group(g)
                    else:
                        server.store.create_pod_group(g)
                    return 201, pod_group_to_wire(g)
                for wk in WORKLOAD_KINDS:
                    if self.path.split("?")[0] == f"/api/v1/{wk}":
                        return server._workload_upsert_locked(
                            wk, self._body(), create=True)
                if self.path == "/api/v1/bindings":
                    # Bulk binding commits: one request, one write-lock
                    # acquisition for a whole drained dispatcher queue
                    # (api_dispatcher bulk path). Per-item verdicts ride a
                    # 200 envelope — one pod's conflict must not fail its
                    # batch-mates' commits.
                    out = [dict(payload, code=code) for code, payload in
                           (server._bind_one(item.get("uid", ""),
                                             item.get("node", ""),
                                             tctx=item.get("tctx"))
                            for item in self._body())]
                    return 200, out
                parts = self.path.split("/")
                if (self.path.startswith("/api/v1/pods/")
                        and self.path.endswith("/eviction")):
                    return server._evict_locked(parts[4], self._body())
                if (self.path.startswith("/api/v1/pods/")
                        and self.path.endswith("/binding")):
                    return server._bind_one(
                        parts[4], self._body()["node"],
                        tctx=self.headers.get(_spans.TRACE_HEADER))
                if (self.path.startswith("/api/v1/pods/")
                        and self.path.endswith("/status")):
                    pod = server.store.pods.get(parts[4])
                    if pod is None:
                        return 404, {"error": "pod not found"}
                    body = self._body()
                    server.store.patch_pod_status(
                        pod,
                        nominated_node_name=body.get("nominatedNodeName", ""),
                        phase=body.get("phase", ""))
                    # Status patches fan out no watch event (store parity),
                    # but their scheduling-relevant slice (nominations) must
                    # still survive a restart: WAL an rv-less STATUS record
                    # — replayed as an upsert, never entering the backlog.
                    server._wal_status(pod)
                    return 200, {}
                return 404, {"error": "not found"}

            def do_PUT(self):
                self._body_cache = self._read_body()
                if server.role != "leader":
                    return self._json(421, {"error": "NotLeader",
                                            "leader": server.leader_url})
                if self.path.startswith("/api/v1/leases/"):
                    # upsert_lease serializes under the write lock itself
                    # (it is also an in-process API); don't wrap it twice.
                    # Its own under-the-lock role check covers the
                    # demote() race (NOT_LEADER sentinel -> 421). Lease CAS
                    # is the EXEMPT flow-control lane: shard/leader lease
                    # renewals are what failover detection runs on, and a
                    # tenant flood must never queue them behind itself.
                    server.flowcontrol.count_exempt()
                    body = self._body()
                    got = server.upsert_lease(
                        self.path.split("/")[4],
                        body.get("holder", ""),
                        float(body.get("leaseDurationSeconds", 15.0)))
                    if got is APIServer.NOT_LEADER:
                        return self._json(421, {"error": "NotLeader",
                                                "leader": server.leader_url})
                    if got is None:
                        return self._json(409, {"error": "LeaseHeld"})
                    server._await_shipped(server._repl_seq)
                    return self._json(200, got)
                ticket = self._flow_admit("PUT")
                if ticket is None:
                    return
                try:
                    with server._write_lock:
                        if server.role != "leader":
                            code, obj, seq = 421, {
                                "error": "NotLeader",
                                "leader": server.leader_url}, 0
                        else:
                            code, obj = self._put_locked()
                            seq = server._repl_seq
                    server._await_shipped(seq)
                finally:
                    server.flowcontrol.release(ticket)
                self._json(code, obj)

            def _put_locked(self):
                if (self.path.startswith("/api/v1/nodes/")
                        and self.path.endswith("/status")):
                    # heartbeat parity stub — stamps freshness, no event
                    nm = self.path.split("/")[4]
                    if nm != "status":
                        server._note_heartbeats((nm,))
                    return 200, {}
                # Node update (relabel / retaint / capacity change): the
                # store fans a MODIFIED event to every watch stream, so
                # churn workloads run over the wire (eventhandlers.go
                # updateNodeInCache; round-4 VERDICT item 5).
                if self.path.startswith("/api/v1/nodes/"):
                    node = node_from_wire(self._body())
                    if node.name != self.path.split("/")[4]:
                        return 400, {"error": "name mismatch"}
                    server.store.update_node(node)
                    return 200, node_to_wire(node)
                # Workload upsert: PUT /api/v1/{kind}/{ns}/{name} — the
                # path names the object (idempotent spec writes: scale,
                # rolling-update template flips, PDB edits).
                parts = self.path.split("?")[0].split("/")
                if len(parts) >= 6 and parts[3] in WORKLOAD_KINDS:
                    body = self._body()
                    if isinstance(body, dict):
                        body = dict(body, namespace=parts[4] or "default",
                                    name=parts[5])
                    return server._workload_upsert_locked(parts[3], body)
                return 404, {"error": "not found"}

            def do_DELETE(self):
                self._body_cache = {}
                if server.role != "leader":
                    return self._json(421, {"error": "NotLeader",
                                            "leader": server.leader_url})
                ticket = self._flow_admit("DELETE")
                if ticket is None:
                    return
                try:
                    with server._write_lock:
                        if server.role != "leader":
                            code, obj, seq = 421, {
                                "error": "NotLeader",
                                "leader": server.leader_url}, 0
                        else:
                            code, obj = self._delete_locked()
                            seq = server._repl_seq
                    server._await_shipped(seq)
                finally:
                    server.flowcontrol.release(ticket)
                self._json(code, obj)

            def _delete_locked(self):
                path, _, query = self.path.partition("?")
                if path.startswith("/api/v1/pods/"):
                    uid = path.split("/")[4]
                    pod = server.store.pods.get(uid)
                    if pod is not None:
                        if "voluntary=true" in query and pod.node_name:
                            # Voluntary disruption (rolling-update scale-
                            # down): same PDB precondition as the eviction
                            # subresource — a deliberate delete must not
                            # take a workload below minAvailable either.
                            denied = server._pdb_blocks_eviction(pod)
                            if denied is not None:
                                server.evictions_budget_denied += 1
                                return 429, denied
                        bound_to = pod.node_name
                        server.store.delete_pod(pod)
                        if uid not in server.store.pods:
                            # Finalizer-parked deletes keep the pod (and its
                            # committed usage); only a completed delete
                            # releases the node's share — and retires the
                            # pod's eviction-ledger entry (a gone pod needs
                            # no replay protection; the ledger must not
                            # grow with every pod ever evicted).
                            if bound_to:
                                server._usage_apply(bound_to, pod, -1)
                            server.evictions.pop(uid, None)
                    return 200, {}
                if path.startswith("/api/v1/nodes/"):
                    name = path.split("/")[4]
                    server.store.delete_node(name)
                    server._drop_heartbeat(name)
                    return 200, {}
                parts = path.split("/")
                if len(parts) >= 6 and parts[3] in WORKLOAD_KINDS:
                    return server._workload_delete_locked(
                        parts[3], parts[4], parts[5])
                return 404, {"error": "not found"}

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        bound_port = self._httpd.server_address[1]
        # Replication identity: this replica's own base URL is what a
        # NotLeader redirect points at once it promotes, and what its
        # status document advertises for election probes.
        self.advertise_url = f"http://127.0.0.1:{bound_port}"
        if self.role == "leader" and not self.leader_url:
            self.leader_url = self.advertise_url
        return bound_port

    def shutdown(self) -> None:
        httpd = self._httpd
        self._httpd = None
        if httpd is not None:
            httpd.shutdown()
            # Tear down accepted connections (parked keep-alive REST conns +
            # watch streams) so their handler threads exit and pooled
            # clients see EOF — a lingering thread would keep serving this
            # dead server's store. Then release the LISTENING socket:
            # restart-in-place must be able to rebind the port immediately
            # (ThreadingHTTPServer.shutdown() alone never closes it).
            for sock in list(self._conns):
                try:
                    import socket as _sock
                    sock.shutdown(_sock.SHUT_RDWR)
                except Exception:  # noqa: BLE001 - already closing
                    pass
            httpd.server_close()
        if self.persistence is not None:
            self.persistence.close()


# ---------------------------------------------------------------------------
# The client: REST writes + reflector-fed informer cache
# ---------------------------------------------------------------------------


def iter_paged(conn, kind: str, limit: int, shard=None,
               max_restarts: int = 8):
    """Drive one complete paged LIST (`?limit=&continue=`) over an open
    HTTPConnection, yielding as lines arrive (bounded buffering):

    - ``("restart", None, (0, ""))`` — a continuation expired off the
      resume ring (410): the whole list restarts; the consumer must reset
      any accumulation;
    - ``("object", wire_dict, (wire_bytes, codec))`` — one listed object,
      with its decode-cost accounting (core/wire.py negotiated codec);
    - ``("done", trailer_dict, (0, ""))`` — the final PAGE trailer
      (carries ``listRv``/``epoch``), after which the generator ends.

    The ONE consumption loop `fetch_paged` (collecting oracle) and the
    reflector's `_paged_list_sync` (per-line dispatch) both ride —
    request building, the 410-restart policy, and trailer parsing cannot
    diverge between them."""
    from urllib.error import URLError

    for _attempt in range(max_restarts):
        token = ""
        expired = False
        while True:
            path = f"/api/v1/{kind}?limit={limit}"
            if shard is not None:
                path += f"&shard={shard[0]}/{shard[1]}"
            if token:
                path += f"&continue={token}"
            conn.request("GET", path, headers=wire.client_headers())
            resp = conn.getresponse()
            if resp.status == 410:
                resp.read()
                expired = True
                break
            if resp.status != 200:
                resp.read()
                raise URLError(f"paged {kind} list: HTTP {resp.status}")
            token = ""
            trailer: Optional[dict] = None
            while True:
                got = wire.read_event(resp)
                if got is None:
                    break
                d, nbytes, codec = got
                if d.get("type") == "PAGE":
                    token = d.get("continue") or ""
                    trailer = d
                elif d.get("object") is not None:
                    yield "object", d["object"], (nbytes, codec)
            if not token:
                yield "done", trailer or {}, (0, "")
                return
        if expired:
            yield "restart", None, (0, "")
    raise URLError(
        f"paged {kind} list: continuation kept expiring "
        f"after {max_restarts} restarts")


def fetch_paged(base_url: str, kind: str, limit: int = 1000,
                timeout: float = 60.0, max_restarts: int = 8) -> List[dict]:
    """Collect one complete paged LIST (`?limit=&continue=`) — the helper
    harnesses and oracles use instead of the full-cluster single-response
    GET."""
    import http.client as _hc

    host = base_url.rstrip("/").split("//", 1)[1]
    conn = _hc.HTTPConnection(host, timeout=timeout)
    try:
        out: List[dict] = []
        for what, payload, _line in iter_paged(conn, kind, limit,
                                               max_restarts=max_restarts):
            if what == "restart":
                out = []
            elif what == "object":
                out.append(payload)
            else:
                break
        return out
    finally:
        conn.close()


class KeepAliveClient:
    """Thread-local persistent HTTP/1.1 connections to one server.

    The apiserver handler already speaks HTTP/1.1 keep-alive; what burned
    CPU was the CLIENT side opening a fresh TCP connection per call (urllib
    does not pool), which also costs the ThreadingHTTPServer one thread
    spawn per request. At bind rates (>100/s per scheduler, every bind a
    POST) the setup tax dominated the write path — the profiled 1-shard
    bench spent 68s of a 78s run inside the serial host-commit loop, most
    of it connection overhead. One pooled connection per calling thread
    keeps the server thread persistent too.

    Transport-failure policy: the pooled connection is dropped, then
    - GET/PUT (idempotent on this surface — list/summary reads, node
      updates, lease renews) transparently retry ONCE on a fresh
      connection;
    - POST/DELETE retry once too, but ONLY when a REUSED connection died
      before yielding any response byte (RemoteDisconnected/reset/EPIPE —
      the keep-alive staleness signature: the server restarted or closed
      the parked conn, and a closed server socket RSTs late data, so the
      request was almost certainly never processed). Every verb on this
      surface tolerates the rare did-process replay: creates answer 409
      AlreadyExists (a caller-visible wart only when the response to a
      processed create was lost mid-crash), same-node bind replays answer
      200, deletes/status are idempotent. All other POST/DELETE failures
      surface a URLError to the caller's retry policy (RetryingClientset
      owns replay-409 forgiveness for ITS replays).
    """

    def __init__(self, base_url: str, timeout: float = 10.0):
        from urllib.parse import urlsplit
        sp = urlsplit(base_url.rstrip("/"))
        self._host = sp.hostname
        self._port = sp.port or 80
        self._base = base_url.rstrip("/")
        self._timeout = timeout
        self._local = threading.local()
        # Wire negotiation state (core/wire.py): None until the first
        # response proves what the server speaks. Request BODIES go out
        # binary only after a binary reply has been seen — a JSON-only
        # server must never receive a frame it cannot parse (the Accept
        # offer itself is always safe). Shared across threads; benignly
        # racy (worst case: one extra JSON body).
        self._server_wire: Optional[bool] = None

    def call(self, method: str, path: str, body: Optional[dict] = None,
             timeout: Optional[float] = None,
             headers: Optional[Dict[str, str]] = None,
             replay: bool = True):
        import http.client as _hc
        import io
        from urllib import error as urlerror

        offer = wire.client_headers()
        body_codec = (wire.BINARY if self._server_wire and offer
                      else wire.JSON)
        if body is not None:
            data = wire.encode(body, body_codec)
        else:
            data = None
        headers = dict(headers or (), **offer,
                       **{"Content-Type": wire.mime_for(body_codec)})
        t = timeout if timeout is not None else self._timeout
        # replay=False: the caller owns replays (HTTPClientset's
        # leader-routed writes — against a REPLICATED control plane a dead
        # connection may mean the leader itself died, and a blind same-host
        # replay would race the promotion; the caller must re-resolve the
        # leader first, then replay through the idempotent/409 surface).
        may_replay = replay and method in ("GET", "PUT")
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            fresh = conn is None
            if fresh:
                conn = _hc.HTTPConnection(self._host, self._port, timeout=t)
                self._local.conn = conn
                try:  # headers+body go out as separate small segments;
                    # without NODELAY, Nagle holds the second on the
                    # peer's delayed ACK (~ms per request, even loopback)
                    import socket as _sock
                    conn.connect()
                    conn.sock.setsockopt(_sock.IPPROTO_TCP,
                                         _sock.TCP_NODELAY, 1)
                except Exception:  # noqa: BLE001 - connect errors surface
                    pass           # identically from request() below
            elif conn.timeout != t:
                conn.timeout = t
                if conn.sock is not None:
                    conn.sock.settimeout(t)
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
                status, reason, hdrs = resp.status, resp.reason, resp.msg
                if resp.will_close:
                    self._local.conn = None
                    conn.close()
            except Exception as e:  # noqa: BLE001 - transport failure
                self._local.conn = None
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
                # A REUSED connection torn down before yielding any response
                # byte is the keep-alive staleness signature (server
                # restarted or idle-closed the parked conn; a closed server
                # socket RSTs late data, so the request was almost certainly
                # never processed). Replay it once on a fresh connection for
                # every verb: this API surface tolerates the rare
                # did-process case too (creates answer 409 AlreadyExists,
                # same-node bind replays answer 200, deletes/status are
                # idempotent).
                stale = replay and not fresh and isinstance(
                    e, (_hc.RemoteDisconnected, ConnectionResetError,
                        BrokenPipeError))
                if (may_replay or stale) and not fresh and attempt == 0:
                    continue  # stale keep-alive connection: one fresh try
                if isinstance(e, urlerror.URLError):
                    raise
                raise urlerror.URLError(e) from e
            if status >= 400:
                # Error bodies are always JSON (the server's debug-plane
                # contract) — callers' .read()+jloads keep working.
                raise urlerror.HTTPError(f"{self._base}{path}", status,
                                         reason, hdrs, io.BytesIO(payload))
            if offer:
                # Learn the server's plane from a SUCCESS reply: binary
                # content-type => binary bodies from here on; a JSON 2xx
                # despite our offer => JSON-only server (never regress a
                # learned binary peer on a bodyless reply).
                if wire.codec_of_mime(
                        hdrs.get("Content-Type")) == wire.BINARY:
                    self._server_wire = True
                elif payload:
                    self._server_wire = False
            return wire.decode(payload) if payload else None


class HTTPClientset:
    """Clientset over the wire: writes are REST calls; reads serve from the
    reflector-maintained local cache; handler registration taps the informer
    fanout (events arrive on the reflector thread → the scheduler's inbox).

    Only the pod/node surface crosses the wire (the verbs the scheduler
    core exercises); the remaining listers return empty local dicts.

    Against a REPLICATED control plane (kubernetes_tpu/replication/) the
    base URL may be a FOLLOWER: reads (list/watch/RESUME, leases) serve
    from it, while every mutating verb routes through `_write_call` —
    follow a ``421 NotLeader`` redirect to the leader, and on a transport
    failure RE-RESOLVE the leader through ``/replication/status`` before
    the single replay (a blind same-host replay would race a promotion;
    the idempotent create-409 / same-node-bind-200 surface absorbs the
    rare did-process replay). ``fallbacks`` lists sibling read bases: when
    the base itself dies (follower kill), the reflector rotates to the
    next one and RESUMEs by rv — replicas share one rv/epoch space, so no
    re-list. A ``FAILOVER`` watch marker bumps ``failover_count`` (the
    scheduler's reconcile trigger) and pre-warms the leader route."""

    # Binds terminate at the apiserver's binding subresource, whose Omega
    # commit validation rejects overcommits with 409 — the property
    # shard.ShardMember's optimistic session patching relies on. The
    # FakeClientset binds unconditionally and must not claim it.
    validates_bind_capacity = True

    def __init__(self, base_url: str, sync_timeout: float = 30.0,
                 fallbacks=(), shard=None, extra_kinds=()):
        self.base = base_url.rstrip("/")
        # Opt-in workload-kind reflection (WORKLOAD_KINDS): controllers
        # pass extra_kinds=("replicasets", ...) and get a reflector thread
        # + raw wire-dict cache per kind; the default constructor stays at
        # the three store kinds so existing clients pay nothing new.
        self.extra_kinds = tuple(k for k in extra_kinds
                                 if k in WORKLOAD_KINDS)
        # Server-side shard filtering (core/watchcache.py): with
        # shard=(index, count), the pod watch opens `?shard=i/n` and the
        # server delivers full pod wire only for owned + wire-relevant
        # pods; the rest arrive as slim projections this client MERGES
        # onto its cache (pod_from_slim). The decode counters below are
        # what bench.py --shards surfaces per shard — the measurable 1/N.
        self.shard = tuple(shard) if shard else None
        self.watch_events_full = 0
        self.watch_events_slim = 0
        self.watch_bytes_full = 0
        self.watch_bytes_slim = 0
        # The same decode accounting split by (form, codec): which plane
        # (binary vs JSON) this client's watch/list decode actually ran
        # on — scheduler_watch_decoded_*{form,codec} reads these.
        self.wire_decode_events: Dict[tuple, int] = {
            ("full", wire.JSON): 0, ("full", wire.BINARY): 0,
            ("slim", wire.JSON): 0, ("slim", wire.BINARY): 0,
            ("delta", wire.JSON): 0, ("delta", wire.BINARY): 0}
        self.wire_decode_bytes: Dict[tuple, int] = {
            ("full", wire.JSON): 0, ("full", wire.BINARY): 0,
            ("slim", wire.JSON): 0, ("slim", wire.BINARY): 0,
            ("delta", wire.JSON): 0, ("delta", wire.BINARY): 0}
        # Delta plane (PR 18): per-kind wire-object caches — the base a
        # DELTA patch applies onto. Each kind's maps are touched ONLY by
        # that kind's reflector thread (lock-free by construction).
        # delta_fallbacks counts base-rv mismatches that forced a re-list.
        self._wire: Dict[str, Dict[str, dict]] = {}
        self._wire_rv: Dict[str, Dict[str, Optional[int]]] = {}
        self.delta_fallbacks = 0
        # Read plane: the base plus sibling replicas the reflector may
        # rotate to when the base dies (shared rv/epoch space -> RESUME).
        self._bases: List[str] = [self.base] + [
            b.rstrip("/") for b in fallbacks if b]
        self._base_idx = 0
        self._ka = KeepAliveClient(self.base)
        self._ka_cache: Dict[str, KeepAliveClient] = {self.base: self._ka}
        # Write plane: the resolved leader (None until a redirect or a
        # FAILOVER marker names one — writes optimistically try the base).
        self._leader_base: Optional[str] = None
        self.failover_count = 0  # FAILOVER markers seen (reconcile trigger)
        self.write_redirects = 0  # 421 NotLeader redirects followed
        self.leader_resolutions = 0  # transport-failure re-resolutions
        self.read_rotations = 0  # read-base failovers (dead follower)
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.bindings: Dict[str, str] = {}
        # Gang state over the wire: the podgroups reflector fills these
        # ("ns/name" keys, same as the FakeClientset) so multi-process
        # shard members see one gang truth.
        self.pod_groups: Dict[str, object] = {}
        self.composite_pod_groups: Dict[str, object] = {}
        # Workload-kind caches ("ns/name" -> raw wire dict): controllers
        # read desired state straight from these — no typed twin.
        self.workloads: Dict[str, Dict[str, dict]] = {
            k: {} for k in self.extra_kinds}
        self._workload_handlers: Dict[str, List] = {
            k: [] for k in self.extra_kinds}
        # unused-surface listers (volume/DRA plugins see empty cluster state)
        self.namespaces: Dict[str, object] = {}
        self.pvs: Dict[str, object] = {}
        self.pvcs: Dict[str, object] = {}
        self.storage_classes: Dict[str, object] = {}
        self.csi_nodes: Dict[str, object] = {}
        self.resource_slices: Dict[str, list] = {}
        self.resource_claims: Dict[str, object] = {}
        self.device_classes: Dict[str, object] = {}
        self._pod_handlers: List = []
        self._node_handlers: List = []
        self._pod_group_handlers: List = []
        self._dispatch_lock = threading.Lock()
        self._stop = threading.Event()
        self._responses: List = []
        kinds = ("pods", "nodes", "podgroups") + self.extra_kinds
        self._synced = {k: threading.Event() for k in kinds}
        self._fatal: Dict[str, Exception] = {}
        self.last_sync: Dict[str, float] = {}
        # resourceVersion resume (reflector.go lastSyncResourceVersion):
        # the rv of the last event (or SYNC snapshot) each stream consumed;
        # reconnects ask the server to replay from here instead of
        # re-listing. relists/resumes count how each reconnect was served.
        self._last_rv: Dict[str, Optional[int]] = {k: None for k in kinds}
        for k in kinds:  # delta bases: one map pair per reflector thread
            self._wire[k] = {}
            self._wire_rv[k] = {}
        # Server boot epoch (from SYNC/RESUME): sent with the rv so a
        # restarted server (fresh counters) re-lists instead of resuming.
        self._epoch: Dict[str, Optional[str]] = {k: None for k in kinds}
        self.relists: Dict[str, int] = {k: 0 for k in kinds}
        self.resumes: Dict[str, int] = {k: 0 for k in kinds}
        self._threads: List[threading.Thread] = []
        for kind in kinds:
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 name=f"reflector-{kind}", daemon=True)
            t.start()
            self._threads.append(t)
        for kind in kinds:
            if not self._synced[kind].wait(sync_timeout):
                self.close()  # stop the reflector threads before raising
                raise TimeoutError(f"reflector {kind} never synced")
            if kind in self._fatal:
                self.close()
                raise ConnectionError(
                    f"reflector {kind}: initial connection failed"
                ) from self._fatal[kind]

    # -- REST --------------------------------------------------------------

    def _call(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        # Pooled keep-alive connections (one per calling thread): the bind
        # path POSTs once per scheduled pod, and per-call connection setup
        # was the dominant cost of the serial host-commit loop. Reads serve
        # from the (possibly follower) read base; mutations leader-route.
        if method == "GET":
            return self._ka.call(method, path, body)
        return self._write_call(method, path, body)

    # -- leader routing (replication/NotLeader redirect protocol) -----------

    def _ka_for(self, base: str) -> KeepAliveClient:
        client = self._ka_cache.get(base)
        if client is None:
            client = self._ka_cache[base] = KeepAliveClient(base)
        return client

    def _set_leader(self, base: str) -> None:
        base = base.rstrip("/")
        if base:
            self._leader_base = base

    def _rotate_read_base(self, from_idx: int) -> None:
        """Advance the shared read base one step. Idempotent per
        `from_idx`: both reflector streams fail together against the same
        dead replica and must not double-advance past a live one."""
        if len(self._bases) <= 1 or self._base_idx != from_idx:
            return
        self._base_idx = (from_idx + 1) % len(self._bases)
        self._ka = self._ka_for(self._bases[self._base_idx])
        self.read_rotations += 1

    def _err_body(self, e) -> dict:
        try:
            return wire.jloads(e.read() or b"{}")
        except Exception:  # noqa: BLE001 - already an error path
            return {}

    def _try_status(self, base: str) -> Optional[dict]:
        try:
            return self._ka_for(base).call(
                "GET", "/replication/status", timeout=2.0)
        except Exception:  # noqa: BLE001 - replica dead/unreachable
            return None

    def _resolve_leader(self) -> Optional[str]:
        """Who leads, per the live replicas' status documents. All claims
        are collected and the HIGHEST fencing epoch wins — a stale leader
        that has not yet learned it was deposed may still claim the role,
        and routing writes to it would lose them into a forked history.
        Followers' leader hints are probed one hop (a follower
        mid-election may still point at the dead leader — that hint fails
        its own probe and is skipped)."""
        self.leader_resolutions += 1
        claims: List = []  # (replEpoch, base) of every role=leader claim
        hints: List[str] = []
        for base in list(self._bases):
            st = self._try_status(base)
            if st is None:
                continue
            if st.get("role") == "leader":
                claims.append((int(st.get("replEpoch", 0)), base))
            elif st.get("leader"):
                hints.append(st["leader"].rstrip("/"))
        seen = {base for _, base in claims} | set(self._bases)
        for url in hints:
            if url in seen:
                continue
            seen.add(url)
            st = self._try_status(url)
            if st is not None and st.get("role") == "leader":
                claims.append((int(st.get("replEpoch", 0)), url))
        if claims:
            return max(claims)[1]
        return None

    def _write_call(self, method: str, path: str, body=None,
                    headers: Optional[Dict[str, str]] = None):
        """One mutating call with the NotLeader-redirect + single-replay
        contract: optimistic send to the resolved leader (or the base),
        follow at most one 421 redirect, and on a transport failure
        RE-RESOLVE the leader before the one replay. Exactly-once rides
        the server's idempotent surface (create->409 AlreadyExists,
        same-node bind->200), including replays that land on a freshly
        PROMOTED leader."""
        from urllib.error import HTTPError, URLError

        from .backoff import TransientAPIError

        if self._leader_base:
            client, tried = self._ka_for(self._leader_base), self._leader_base
        else:
            # The CURRENT read base — after a read-plane rotation self._ka
            # no longer points at self.base, and a redirect naming the
            # original base must still be followed.
            client, tried = self._ka, self._bases[self._base_idx]
        try:
            return client.call(method, path, body, headers=headers,
                               replay=False)
        except HTTPError as e:
            if e.code != 421:
                raise
            info = self._err_body(e)
            leader = (info.get("leader") or "").rstrip("/")
            if leader and leader != tried:
                # NotLeader redirect: one follow. The followed hop can
                # itself answer 421 (a freshly deposed leader pointing
                # onward mid-failover) — that too is "promotion in
                # flight", surfaced retriable, never a hard 4xx failure.
                self.write_redirects += 1
                self._set_leader(leader)
                try:
                    return self._ka_for(leader).call(
                        method, path, body, headers=headers, replay=False)
                except HTTPError as e2:
                    if e2.code != 421:
                        raise
                    self._leader_base = None
                    raise TransientAPIError(
                        "NotLeader after redirect: promotion in flight"
                    ) from e2
            # No redirect target (or a stale one pointing back at who we
            # just asked) — a deposed replica may not know the winner.
            # Try one status-probe resolution; failing that, surface
            # retriable — binds queue behind the retry layers until a
            # leader exists.
            self._leader_base = None
            resolved = self._resolve_leader()
            if resolved and resolved != tried:
                self._set_leader(resolved)
                return self._ka_for(resolved).call(
                    method, path, body, headers=headers, replay=False)
            raise TransientAPIError(
                "NotLeader: promotion in flight") from e
        except URLError:
            # The server we were writing to is gone (leader death /
            # restart). Re-resolve through the read plane FIRST, then
            # replay once — never a blind same-host replay.
            leader = self._resolve_leader()
            if leader is None:
                self._leader_base = None
                raise
            self._set_leader(leader)
            return self._ka_for(leader).call(
                method, path, body, headers=headers, replay=False)

    def create_pod(self, pod: Pod) -> Pod:
        self._call("POST", "/api/v1/pods", pod_to_wire(pod))
        return pod

    def create_node(self, node: Node) -> Node:
        self._call("POST", "/api/v1/nodes", node_to_wire(node))
        return node

    def update_node(self, node: Node) -> Node:
        self._call("PUT", f"/api/v1/nodes/{node.name}", node_to_wire(node))
        return node

    def delete_node(self, name: str) -> None:
        self._call("DELETE", f"/api/v1/nodes/{name}")

    def delete_pod(self, pod: Pod) -> None:
        self._call("DELETE", f"/api/v1/pods/{pod.uid}")

    def evict_pod(self, uid: str, node: str, intent: str) -> dict:
        """Eviction subresource: DELETE-then-recreate-pending, idempotent
        by `intent` (the server's WAL'd ledger answers retries with
        already=True — exactly-once across controller restart/failover).
        `node` guards against evicting a pod that moved since the plan
        (409 NodeMismatch)."""
        return self._call("POST", f"/api/v1/pods/{uid}/eviction",
                          {"intent": intent, "node": node}) or {}

    def create_pod_group(self, group):
        self._call("POST", "/api/v1/podgroups", pod_group_to_wire(group))
        return group

    def create_composite_pod_group(self, cpg):
        self._call("POST", "/api/v1/podgroups", pod_group_to_wire(cpg))
        return cpg

    # -- workload kinds (WORKLOAD_KINDS: raw wire dicts over the wire) ------

    def create_workload(self, kind: str, w: dict) -> dict:
        """POST — 409 AlreadyExists on a duplicate name (the caller's
        create-409-is-success seam handles retries)."""
        return self._call("POST", f"/api/v1/{kind}", dict(w)) or {}

    def put_workload(self, kind: str, w: dict) -> dict:
        """Idempotent named upsert: PUT /api/v1/{kind}/{ns}/{name}."""
        ns = w.get("namespace") or "default"
        return self._call(
            "PUT", f"/api/v1/{kind}/{ns}/{w['name']}", dict(w)) or {}

    def delete_workload(self, kind: str, ns: str, name: str) -> None:
        self._call("DELETE", f"/api/v1/{kind}/{ns or 'default'}/{name}")

    def delete_pod_voluntary(self, uid: str) -> None:
        """Voluntary pod delete (rolling-update scale-down): the server
        runs the PDB precondition and answers 429 DisruptionBudget when
        committing it would breach minAvailable."""
        self._call("DELETE", f"/api/v1/pods/{uid}?voluntary=true")

    def on_workload_event(self, kind: str, handler) -> None:
        """Register (action, old, new_wire_dict) fanout for one reflected
        workload kind (must have been named in extra_kinds)."""
        self._workload_handlers[kind].append(handler)

    def node_heartbeat_ages(self) -> Dict[str, float]:
        """Seconds-since-last-heartbeat per node, leader-routed (the ages
        live only on the leader — followers answer 421 and _write_call
        follows the redirect even though this is a read)."""
        got = self._write_call("GET", "/api/v1/nodes/heartbeats") or {}
        return dict(got.get("ages") or {})

    def bind(self, pod: Pod, node_name: str) -> None:
        # Trace propagation (core/spans.py): a sampled pod's bind carries
        # its context in the X-Trace-Context header and records the
        # bind.post span around the POST round trip.
        tr = _spans.default_tracer()
        ctx = tr.context_for(pod.uid)
        if not tr.wants(ctx):
            self._write_call("POST", f"/api/v1/pods/{pod.uid}/binding",
                             {"node": node_name})
            return
        t0 = time.perf_counter()
        try:
            self._write_call(
                "POST", f"/api/v1/pods/{pod.uid}/binding",
                {"node": node_name},
                headers={_spans.TRACE_HEADER: _spans.format_ctx(ctx)})
        finally:
            tr.record("bind.post", ctx, time.perf_counter() - t0,
                      node=node_name)

    def bind_many(self, pairs) -> list:
        """Bulk binding commits (POST /api/v1/bindings): one request for a
        drained dispatcher bind queue. Per-item verdicts come back in a 200
        envelope; each non-200 maps to the HTTPError the single-bind path
        would have raised (the conflict-requeue seam keys on .code == 409
        and the reason string naming AlreadyBound/OutOfCapacity)."""
        import io
        from urllib.error import HTTPError
        tr = _spans.default_tracer()
        items = []
        sampled = []  # contexts to close bind.post spans for
        for p, node in pairs:
            item = {"uid": p.uid, "node": node}
            ctx = tr.context_for(p.uid)
            if tr.wants(ctx):
                # Bulk-bind batch membership rides per-item tctx fields —
                # the server opens api.bind per item under this context.
                item["tctx"] = _spans.format_ctx(ctx)
                sampled.append(ctx)
            items.append(item)
        t0 = time.perf_counter()
        res = self._call("POST", "/api/v1/bindings", items)
        dur = time.perf_counter() - t0
        for ctx in sampled:
            tr.record("bind.post", ctx, dur, bulk=len(pairs))
        out = []
        for i, (p, _node) in enumerate(pairs):
            item = res[i] if res is not None and i < len(res) else {
                "code": 500, "error": "short bulk-bind response"}
            code = item.get("code", 200)
            out.append(None if code < 400 else HTTPError(
                f"{self.base}/api/v1/bindings", code,
                item.get("error", ""), None,
                io.BytesIO(wire.jdumps(item).encode())))
        return out

    def patch_pod_status(self, pod: Pod, nominated_node_name: str = "",
                         phase: str = "") -> None:
        self._call("POST", f"/api/v1/pods/{pod.uid}/status",
                   {"nominatedNodeName": nominated_node_name, "phase": phase})
        local = self.pods.get(pod.uid)
        if local is not None and nominated_node_name:
            local.nominated_node_name = nominated_node_name

    def update_pod(self, pod: Pod) -> Pod:  # parity stub for the surface
        return pod

    # -- slim-pod hydration (shard adoption; core/watchcache.py) ------------

    def hydrate_pods(self, uids) -> int:
        """Replace slim-cached pods with their full wire (GET ?uids=...,
        served from the server's watch cache). Used when shard ownership
        GROWS past the stream's static filter (adoption): pods this shard
        must now SCHEDULE arrived slim and need their real spec. The local
        binding view is preserved (a racing BOUND flows through the
        ordered stream as usual), and pods deleted meanwhile are skipped.
        No handler fanout: callers re-read `self.pods` — the pods are
        pending and foreign-until-now, so no cache/queue state exists."""
        uids = [u for u in uids if u]
        hydrated = 0
        for i in range(0, len(uids), 64):
            chunk = uids[i:i + 64]
            wires = self._call(
                "GET", "/api/v1/pods?uids=" + ",".join(chunk)) or []
            with self._dispatch_lock:
                for w in wires:
                    pod = pod_from_wire(w)
                    cur = self.pods.get(pod.uid)
                    if cur is None:
                        continue  # deleted while hydrating
                    pod.node_name = cur.node_name
                    pod.deletion_ts = cur.deletion_ts
                    self.pods[pod.uid] = pod
                    hydrated += 1
        return hydrated

    def hydrate_pod(self, uid: str) -> Optional[Pod]:
        """Single-pod hydration (the per-event adoption path): returns the
        full cached pod, or None when it vanished or the fetch failed."""
        try:
            self.hydrate_pods([uid])
        except Exception:  # noqa: BLE001 - transient; sweep retries
            return None
        pod = self.pods.get(uid)
        if pod is None or getattr(pod, "wire_slim", False):
            return None
        return pod

    # -- shard leases (shard/leases.py coordination surface) ----------------

    def list_leases(self) -> List[dict]:
        return self._call("GET", "/api/v1/leases")

    def upsert_lease(self, name: str, holder: str,
                     duration: float) -> Optional[dict]:
        """Acquire-or-renew; None when the lease is held by someone else
        (HTTP 409) — the CAS loss a ShardMember treats as 'not mine'."""
        from urllib.error import HTTPError
        try:
            return self._call("PUT", f"/api/v1/leases/{name}",
                              {"holder": holder,
                               "leaseDurationSeconds": duration})
        except HTTPError as e:
            if e.code == 409:
                return None
            raise

    # -- informer registration (scheduler event handlers) -------------------

    def on_pod_event(self, handler) -> None:
        # Replay-then-subscribe under the dispatch lock: live events cannot
        # interleave with (or duplicate) the attach-time replay.
        with self._dispatch_lock:
            for p in list(self.pods.values()):
                handler("add", None, p)
            self._pod_handlers.append(handler)

    def on_node_event(self, handler) -> None:
        with self._dispatch_lock:
            for n in list(self.nodes.values()):
                handler("add", None, n)
            self._node_handlers.append(handler)

    def on_namespace_event(self, handler) -> None:
        pass

    def on_pod_group_event(self, handler) -> None:
        # Replay-then-subscribe, FakeClientset parity: handlers get every
        # known group (plain then composite) once, then live upserts.
        with self._dispatch_lock:
            for g in list(self.pod_groups.values()):
                handler(g)
            for g in list(self.composite_pod_groups.values()):
                handler(g)
            self._pod_group_handlers.append(handler)

    def on_storage_event(self, handler) -> None:
        pass

    def attach_pv_controller(self, ctrl) -> None:
        pass

    # -- reflector (ListAndWatch: paged list, then watch from the anchor) ---

    def _paged_list_sync(self, kind: str, host: str):
        """Reflector (re-)list as a PAGED list (`?limit=&continue=`,
        docs/SCALE.md): dispatch each object as its line arrives (bounded
        client-side buffering — never a full-cluster response body), run
        the Replace barrier at the end, and return ``(anchor, epoch)`` —
        the list-anchor rv the following watch attach RESUMEs from,
        replaying exactly the events that happened while paging. The
        watermark is NOT published to ``_last_rv`` here: it becomes the
        client's resume point only once the watch's RESUME marker
        confirms the stream is live (a death in the gap re-lists rather
        than resuming past events no stream was attached for). A 410
        ExpiredContinue restarts the list from scratch; transport
        failures raise to the watch loop's failure/rotation handling."""
        import http.client as _hc
        import os as _os

        limit = int(_os.environ.get("TPU_SCHED_LIST_PAGE", "500"))
        shard = self.shard if kind == "pods" else None
        conn = _hc.HTTPConnection(host, timeout=60)
        try:
            seen: set = set()
            trailer: dict = {}
            nwire: Dict[str, dict] = {}
            for what, payload, line in iter_paged(conn, kind, limit,
                                                  shard=shard):
                if what == "restart":
                    # Anchor off the ring mid-list: the iterator restarts
                    # the list; objects already dispatched simply upsert
                    # again, but the Replace seen-set must reset.
                    seen = set()
                    nwire = {}
                    continue
                if what == "done":
                    trailer = payload
                    break
                obj = payload
                # Decode-cost accounting, same split as the watch loop
                # (a filtered paged list delivers foreign plain pods
                # slim); `line` is (wire_bytes, codec) from iter_paged.
                self._note_decode(
                    "slim" if obj.get("slim") else "full",
                    line[1], line[0])
                if not obj.get("slim"):
                    nwire[wire_key(kind, obj)] = obj
                with self._dispatch_lock:
                    seen.add(wire_key(kind, obj))
                    self._dispatch(kind, "ADDED", obj)
            with self._dispatch_lock:
                self._replace_barrier(kind, seen)
            # Replace semantics for the delta bases too: the listed set
            # IS the new base map, every rv unknown (accept-if-unknown —
            # replay ordering guarantees the held state is the minter's
            # base or a convergent ahead-state). Reflector thread only.
            self._wire[kind] = nwire
            self._wire_rv[kind] = {}
            self.relists[kind] += 1
            anchor = trailer.get("listRv")
            return ((int(anchor) if anchor is not None else None),
                    trailer.get("epoch"))
        finally:
            conn.close()

    def _note_decode(self, form: str, codec: str, nbytes: int) -> None:
        """One decoded wire record's cost accounting: by form (full wire
        vs slim projection — the shard filter's 1/N) and by codec (binary
        vs JSON — the wire refactor's raw-bytes lever). Reflector-thread
        only; the legacy aggregate counters stay for existing readers."""
        if form == "slim":
            self.watch_events_slim += 1
            self.watch_bytes_slim += nbytes
        else:
            self.watch_events_full += 1
            self.watch_bytes_full += nbytes
        key = (form, codec)
        self.wire_decode_events[key] = self.wire_decode_events.get(key, 0) + 1
        self.wire_decode_bytes[key] = (
            self.wire_decode_bytes.get(key, 0) + nbytes)

    def _track_wire(self, kind: str, typ: str, obj,
                    rv: Optional[int]) -> None:
        """Advance this kind's delta-base cache exactly the way the
        server's watch cache advanced its snapshot (core/watchcache.py
        `_apply_object` + the `_obj_rv` contract) — bases must be
        bit-identical whenever the recorded rv matches a DELTA's baseRv.
        Reflector-thread only (one thread per kind), so no lock. Slim
        projections and rv-less events POP the base: a stale base
        surviving into the accept-if-unknown path would be a SILENT
        divergence, the one failure mode the delta plane must not have."""
        if type(obj) is not dict:
            return
        try:
            key = wire_key(kind, obj)
        except KeyError:
            return
        w, wrv = self._wire[kind], self._wire_rv[kind]
        if typ == "DELETED" or obj.get("slim"):
            w.pop(key, None)
            wrv.pop(key, None)
            return
        if typ == "BOUND":
            cur = w.get(key)
            if cur is None:
                wrv.pop(key, None)
                return
            obj = dict(cur, nodeName=obj.get("nodeName", ""))
        w[key] = obj
        if rv is not None:
            wrv[key] = rv
        else:
            wrv.pop(key, None)

    def _delta_materialize(self, kind: str, event: dict):
        """Apply a DELTA event onto the cached base. Accept when the base
        exists and its recorded rv is unknown (fresh from a paged list —
        replay ordering makes the held state the minter's base or a
        convergent ahead-state) or equals the event's baseRv; anything
        else returns None and the caller falls back to a full re-list
        (never a silent patch onto a divergent base)."""
        key = event.get("key")
        base = self._wire[kind].get(key)
        have = self._wire_rv[kind].get(key)
        if base is None or (have is not None
                            and have != event.get("baseRv")):
            return None
        return wire.apply_patch(base, event.get("patch") or [])

    def _watch_loop(self, kind: str) -> None:
        """client-go reflector behavior (tools/cache/reflector.go:470): on
        stream EOF/timeout, re-connect with the last-seen resourceVersion.
        Inside the server's backlog window the stream opens with RESUME and
        replays exactly the missed events — the local cache converges
        without a re-list. Outside the window (or on first connect) the
        stream replays ADDED for every live object then SYNC, and objects
        that vanished during the outage dispatch DELETED at the SYNC
        barrier (the reflector's Replace semantics). Only a failure of the
        FIRST connection is fatal (recorded in _fatal so the constructor
        raises instead of returning a dead clientset)."""
        # Raw HTTPConnection so close() can shut the SOCKET down —
        # HTTPResponse.close() on an endless chunked stream would block
        # draining to EOF.
        import http.client as _hc
        import time as _time
        backoff = 0.05
        conn_fails = 0  # consecutive failures against the CURRENT read base
        while not self._stop.is_set():
            base_idx = self._base_idx
            host = self._bases[base_idx].split("//", 1)[1]
            fresh = False
            anchor: Optional[int] = None
            anchor_epoch: Optional[str] = None
            if self._last_rv[kind] is None or self._epoch[kind] is None:
                # No resumable watermark (first sync, or a TOO_OLD/epoch
                # break): paged list FIRST (Replace semantics, bounded
                # pages), then watch from the list anchor — the
                # full-cluster ADDED replay never materializes into a
                # stream queue for this client.
                try:
                    anchor, anchor_epoch = self._paged_list_sync(kind, host)
                    fresh = True
                except Exception as e:  # noqa: BLE001 - list failed
                    if not self._synced[kind].is_set():
                        # Initial sync failed: dead on arrival is an
                        # error, not an empty cluster.
                        self._fatal[kind] = e
                        self._synced[kind].set()
                        return
                    conn_fails += 1
                    if conn_fails >= 3:
                        self._rotate_read_base(base_idx)
                        conn_fails = 0
                    if self._stop.wait(backoff):
                        return
                    backoff = min(backoff * 2, 5.0)
                    continue
            try:
                conn = _hc.HTTPConnection(host, timeout=60)
                path = f"/api/v1/{kind}?watch=true&paged=true"
                if kind == "pods" and self.shard is not None:
                    path += f"&shard={self.shard[0]}/{self.shard[1]}"
                if fresh and anchor is not None and anchor_epoch is not None:
                    # Attach straight after a completed paged list: resume
                    # from the LIST ANCHOR (the ring replays exactly the
                    # events that happened while paging). `fresh` also
                    # allows a selector-ful FILTERED resume for this one
                    # attach (the cache was just rebuilt from full
                    # objects — core/watchcache.py).
                    path += (f"&resourceVersion={anchor}"
                             f"&epoch={anchor_epoch}&fresh=true")
                elif (self._last_rv[kind] is not None
                        and self._epoch[kind] is not None):
                    path += (f"&resourceVersion={self._last_rv[kind]}"
                             f"&epoch={self._epoch[kind]}")
                # stream_headers offers the session plane on top of the
                # plain binary offer (and nothing when the process is
                # JSON-pinned) — the server replying with the session
                # MIME is also its promise to ship DELTA frames.
                conn.request("GET", path, headers=wire.stream_headers())
                resp = conn.getresponse()
                session = (wire.SessionDecoder()
                           if wire.session_of_mime(
                               resp.getheader("Content-Type")) else None)
                conn_fails = 0
            except Exception as e:  # noqa: BLE001 - connect failure
                if not self._synced[kind].is_set():
                    # Initial connection failed: dead on arrival is an error,
                    # not an empty cluster.
                    self._fatal[kind] = e
                    self._synced[kind].set()
                    return
                # Read-plane failover: when the base itself stays dead
                # (follower kill), rotate to a sibling replica and RESUME
                # from the shared rv/epoch space — no re-list, and the
                # stall stays bounded by a few connect backoffs.
                conn_fails += 1
                if conn_fails >= 3:
                    self._rotate_read_base(base_idx)
                    conn_fails = 0
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 5.0)
                continue
            self._responses.append(conn)
            got_sync = False
            resync_seen: Optional[set] = set()  # keys replayed pre-SYNC
            try:
                while not self._stop.is_set():
                    got = wire.read_event(resp, session=session)
                    if got is None:
                        break  # EOF: server went away — re-list + re-watch
                    event, nbytes, codec = got
                    typ = event["type"]
                    if typ == "DELTA":
                        obj = self._delta_materialize(kind, event)
                        if obj is None:
                            # Base-rv mismatch: the one legal answer is a
                            # full re-list — clear the watermark and
                            # reconnect fresh. Never patch a divergent
                            # base.
                            self.delta_fallbacks += 1
                            self._last_rv[kind] = None
                            got_sync = True  # progress, not a dead stream
                            break
                        self._note_decode("delta", codec, nbytes)
                        event = {"type": "MODIFIED", "object": obj,
                                 "rv": event.get("rv")}
                        typ = "MODIFIED"
                    elif typ in ("ADDED", "MODIFIED", "DELETED"):
                        # Decode-cost accounting (the 1/N the shard filter
                        # buys, times the codec's bytes-per-event): slim
                        # projections vs full object wire, binary vs JSON.
                        self._note_decode(
                            "slim" if (event.get("object") or {}).get("slim")
                            else "full", codec, nbytes)
                    if typ == "BOOKMARK":
                        continue  # server idle heartbeat
                    if typ == "FAILOVER":
                        # Control-plane leadership moved (promotion, or our
                        # follower re-tailed to a new leader): pre-warm the
                        # write route and bump the reconcile trigger — the
                        # scheduler sweeps for binds the dead leader acked
                        # but never shipped.
                        if event.get("leader"):
                            self._set_leader(event["leader"])
                        self.failover_count += 1
                        continue
                    if typ == "TOO_OLD":
                        # The resume window no longer covers our watermark
                        # (ring overran, or the server is a new epoch):
                        # clear it and re-list PAGED on the next loop
                        # iteration — never a full ADDED replay.
                        self._last_rv[kind] = None
                        got_sync = True  # progress, not a stream failure
                        break
                    if typ == "RESUME":
                        # Incremental reconnect: the server will replay the
                        # missed tail — the local cache stays authoritative,
                        # so no Replace barrier runs.
                        resync_seen = None
                        got_sync = True
                        backoff = 0.05
                        self.resumes[kind] += 1
                        if fresh and anchor is not None:
                            # The stream is LIVE from the list anchor:
                            # publish it as the resume watermark (replayed
                            # events advance it from here). Publishing
                            # earlier would let a death in the list→watch
                            # gap silently resume past unwatched events.
                            self._last_rv[kind] = anchor
                        if event.get("epoch") is not None:
                            self._epoch[kind] = event["epoch"]
                        self._synced[kind].set()
                        self.last_sync[kind] = _time.monotonic()
                        continue
                    if typ == "SYNC":
                        with self._dispatch_lock:
                            self._replace_barrier(kind, resync_seen)
                        resync_seen = None
                        got_sync = True
                        backoff = 0.05  # healthy stream: reset the backoff
                        self.relists[kind] += 1
                        if event.get("rv") is not None:
                            self._last_rv[kind] = event["rv"]
                        if event.get("epoch") is not None:
                            self._epoch[kind] = event["epoch"]
                        self._synced[kind].set()
                        self.last_sync[kind] = _time.monotonic()
                        continue
                    # Delta-base upkeep BEFORE dispatch (this thread owns
                    # the kind's maps; handlers must never see a base the
                    # server no longer diffs against).
                    self._track_wire(kind, typ, event.get("object"),
                                     event.get("rv"))
                    with self._dispatch_lock:
                        if resync_seen is not None:
                            resync_seen.add(wire_key(kind, event["object"]))
                        self._dispatch(kind, typ, event["object"])
                        if event.get("rv") is not None:
                            self._last_rv[kind] = event["rv"]
            except Exception:  # noqa: BLE001 - stream torn down / timeout
                pass
            finally:
                try:
                    self._responses.remove(conn)
                except ValueError:
                    pass
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
            # A stream that died before delivering SYNC counts as a failure:
            # back off exponentially (client-go ListAndWatch backoff) so a
            # crash-looping server isn't hammered at ~20 reconnects/sec.
            if self._stop.wait(backoff if not got_sync else 0.05):
                return
            if not got_sync:
                backoff = min(backoff * 2, 5.0)

    def _replace_barrier(self, kind: str, seen: Optional[set]) -> None:
        """End of a (re-)list window: local objects the server did NOT replay
        no longer exist — dispatch their deletion (reflector Replace)."""
        if seen is None:
            return
        if kind == "pods":
            for uid in [u for u in self.pods if u not in seen]:
                self._dispatch(kind, "DELETED", pod_to_wire(self.pods[uid]))
        elif kind == "podgroups":
            for key in [k for k in self.pod_groups if k not in seen]:
                self._dispatch(kind, "DELETED",
                               pod_group_to_wire(self.pod_groups[key]))
            for key in [k for k in self.composite_pod_groups
                        if k not in seen]:
                self._dispatch(
                    kind, "DELETED",
                    pod_group_to_wire(self.composite_pod_groups[key]))
        elif kind in self.workloads:
            cache = self.workloads[kind]
            for key in [k for k in cache if k not in seen]:
                self._dispatch(kind, "DELETED", cache[key])
        else:
            for name in [n for n in self.nodes if n not in seen]:
                self._dispatch(kind, "DELETED", node_to_wire(self.nodes[name]))

    def _dispatch(self, kind: str, typ: str, obj: dict) -> None:
        if typ == "BOUND":
            # Slim bind event: the full pod is already cached (its ADDED
            # preceded it on this ordered stream) — patch nodeName on a copy
            # instead of rebuilding the pod from a full wire dict. The copy
            # keeps old/new distinct for handlers AND shares the spec-derived
            # memos (signature caches) with the cached object.
            old = self.pods.get(obj["uid"])
            if old is None:
                return  # pod unseen on this stream; the next re-list corrects
            tctx = obj.get("tctx")
            if tctx:
                # Foreign-shard observation: this watcher decoded another
                # scheduler's sampled bind — the span joins the binder's
                # trace (same id), closing the cross-process chain.
                ctx = _spans.parse_ctx(tctx)
                if ctx is not None:
                    _spans.default_tracer().event(
                        "bound.observe", ctx, node=obj.get("nodeName", ""))
            pod = copy.copy(old)
            pod.node_name = obj.get("nodeName", "")
            self.pods[pod.uid] = pod
            if pod.node_name:
                self.bindings[pod.uid] = pod.node_name
            else:
                self.bindings.pop(pod.uid, None)
            for h in self._pod_handlers:
                h("update", old, pod)
            return
        action = {"ADDED": "add", "MODIFIED": "update", "DELETED": "delete"}[typ]
        if kind == "pods":
            if obj.get("slim"):
                # Slim projection (shard-filtered stream): MERGE onto the
                # cached copy — the spec is immutable on this surface, so
                # any previously-delivered full wire stays authoritative
                # and only the projection fields (nodeName/deletionTs)
                # patch. Absent a cached copy, pod_from_slim builds the
                # minimal accounting pod and marks it `wire_slim` (the
                # shard plane hydrates before ever SCHEDULING one).
                pod = pod_from_slim(obj, self.pods.get(obj["uid"]))
            else:
                pod = pod_from_wire(obj)
            old = self.pods.get(pod.uid)
            if action == "add" and old is not None:
                # Replayed ADDED of a known object: a re-list replay, or
                # the post-paged-list watch replaying a create a later
                # page had already served — upsert as an update, handlers
                # must never see a duplicate add.
                action = "update"
            if action == "delete":
                self.pods.pop(pod.uid, None)
                self.bindings.pop(pod.uid, None)
            else:
                self.pods[pod.uid] = pod
                if pod.node_name:
                    self.bindings[pod.uid] = pod.node_name
                else:
                    # Re-list replay (or status update) of an UNBOUND pod:
                    # a stale binding from before a server restart must not
                    # survive in the informer cache.
                    self.bindings.pop(pod.uid, None)
            for h in self._pod_handlers:
                h(action, old, pod)
        elif kind == "podgroups":
            g = pod_group_from_wire(obj)
            target = (self.composite_pod_groups if obj.get("composite")
                      else self.pod_groups)
            key = f"{g.namespace}/{g.name}"
            if action == "delete":
                # Replace-barrier correction only (the server has no group
                # delete verb): drop the local copy, no handler channel for
                # group deletion exists (FakeClientset parity).
                target.pop(key, None)
                return
            known = key in target
            target[key] = g
            if not known:
                # Single-arg handler fanout, FakeClientset parity: only
                # first sight fans out — a re-list replay of a known group
                # must not re-register it with the gang queue.
                for h in self._pod_group_handlers:
                    h(g)
        elif kind in self.workloads:
            # Workload kinds cache RAW wire dicts — controllers consume
            # desired state fields directly; no typed object exists.
            cache = self.workloads[kind]
            key = f'{obj.get("namespace") or "default"}/{obj.get("name")}'
            old = cache.get(key)
            if action == "add" and old is not None:
                action = "update"
            if action == "delete":
                cache.pop(key, None)
            else:
                cache[key] = obj
            for h in self._workload_handlers.get(kind, ()):
                h(action, old, obj)
        else:
            node = node_from_wire(obj)
            old = self.nodes.get(node.name)
            if action == "add" and old is not None:
                action = "update"  # replayed ADDED of a known node
            if action == "delete":
                self.nodes.pop(node.name, None)
            else:
                self.nodes[node.name] = node
            for h in self._node_handlers:
                h(action, old, node)

    def close(self) -> None:
        self._stop.set()
        # Snapshot: reflector threads remove() dead connections concurrently.
        for conn in list(self._responses):
            _shutdown_conn(conn)
        for t in self._threads:
            t.join(timeout=2)


def _shutdown_conn(conn) -> None:
    try:
        import socket
        if conn.sock is not None:
            conn.sock.shutdown(socket.SHUT_RDWR)
            conn.sock.close()
    except Exception:  # noqa: BLE001
        pass


def main(argv=None) -> int:
    """Standalone apiserver process (`python -m kubernetes_tpu.core.apiserver
    --port N`): serves the REST+watch surface on a real socket until
    SIGTERM/SIGINT — the other half of the two-OS-process integration seam
    (ref test/integration/framework/test_server.go:78 StartTestServer)."""
    import argparse
    import os
    import signal

    ap = argparse.ArgumentParser(prog="kubernetes-tpu-apiserver")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default="",
                    help="durable store directory (WAL + snapshot, "
                         "core/wal.py); empty = in-memory only")
    ap.add_argument("--fsync", action="store_true",
                    help="fsync every WAL record (survives power loss, not "
                         "just process death)")
    ap.add_argument("--snapshot-every", type=int, default=2048,
                    help="compact the WAL into a snapshot every N records")
    ap.add_argument("--replicate-from", default="",
                    help="run as a FOLLOWER replica of this leader base URL "
                         "(kubernetes_tpu/replication/): tail its WAL, "
                         "serve reads, redirect writes")
    ap.add_argument("--replica-rank", type=int, default=1,
                    help="election order among followers (lowest live rank "
                         "promotes on leader death)")
    ap.add_argument("--repl-lease-duration", type=float, default=0.0,
                    help="leader-lease/failover-detection period in seconds "
                         "(0 on a standalone leader = no replication lease)")
    args = ap.parse_args(argv)
    # The server is thread-per-connection with ~a dozen live threads under
    # a sharded cluster (creators, watch streams, shard write conns). At
    # CPython's default 5ms switch interval a request handler that needs a
    # few GIL slices waits out multiple quanta — measured as ~4ms/request
    # turnaround with the CPU nearly idle (~240 creates/s arrival ceiling).
    # A 1ms interval trades a little context-switch overhead for ~5x lower
    # write-plane latency.
    import sys as _sys
    _sys.setswitchinterval(0.001)
    api = APIServer(data_dir=args.data_dir or None, fsync=args.fsync,
                    snapshot_every=args.snapshot_every)
    repl_lease = args.repl_lease_duration
    tail = None
    if args.replicate_from:
        from ..replication import ReplicationTail
        tail = ReplicationTail(api, args.replicate_from,
                               rank=max(1, args.replica_rank),
                               lease_duration=repl_lease or 2.0)
        # Synchronous initial sync BEFORE announcing ready: a cold
        # follower installs the leader snapshot, a restarted one already
        # recovered its own WAL above and just re-tails the delta.
        tail.bootstrap()
    # Observability (docs/OBSERVABILITY.md): label this process's spans and
    # install the flight recorder into the durable data dir (or the
    # explicit TPU_SCHED_FLIGHTREC_DIR). The periodic dump is what a chaos
    # kill -9 leaves behind — no handler observes SIGKILL.
    api.tracer.proc = ("apiserver" if tail is None
                       else f"apiserver-r{api.replica_rank}")
    flight = None
    flight_dir = os.environ.get("TPU_SCHED_FLIGHTREC_DIR") or args.data_dir
    if flight_dir:
        from .spans import FlightRecorder
        flight = FlightRecorder(flight_dir, tracer=api.tracer,
                                apiserver=api).install(
            at_exit=True,
            autodump_interval=float(
                os.environ.get("TPU_SCHED_FLIGHTREC_INTERVAL", "5.0")))
    port = api.serve(args.port)
    lease = None
    if tail is not None:
        # The tail thread starts only after serve(): election needs this
        # replica's advertise_url to skip itself in the peer probe. The
        # LeaderLease no-ops until a promotion makes this replica leader.
        from ..replication import LeaderLease
        tail.start()
        lease = LeaderLease(api, identity=f"apiserver-r{api.replica_rank}",
                            duration=repl_lease or 2.0).start()
    elif repl_lease > 0:
        from ..replication import LeaderLease
        lease = LeaderLease(api, identity="apiserver-leader",
                            duration=repl_lease).start()
    # "serving on" stays the FIRST line: spawn harnesses select()+readline()
    # on it, and a buffered readline would swallow any earlier line together
    # with this one (leaving select blocked on a drained pipe).
    print(f"kubernetes-tpu-apiserver: serving on 127.0.0.1:{port}",
          flush=True)
    if tail is not None:
        print(f"kubernetes-tpu-apiserver: follower rank="
              f"{api.replica_rank} of {args.replicate_from} "
              f"seq={api._repl_seq} replEpoch={api.repl_epoch}", flush=True)
    if api.persistence is not None:
        p = api.persistence
        print(f"kubernetes-tpu-apiserver: recovered {api.recovered_objects} "
              f"objects (wal={p.replayed_records} torn="
              f"{p.torn_records_discarded}) epoch={api.epoch} "
              f"rv={dict(api._seq)}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if tail is not None:
        tail.stop()
    if lease is not None:
        lease.stop()
    api.shutdown()
    if flight is not None:
        flight.dump("shutdown")
        flight.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
