"""A minimal REST + watch apiserver over the FakeClientset store, and the
HTTP client/reflector that lets a scheduler run against it across a REAL
process boundary (no shared objects — JSON on the wire).

Re-expresses the scheduler-relevant slice of the reference's L2/L3 stack:

- apiserver REST surface (staging/src/k8s.io/apiserver collapsed to the
  verbs the scheduler uses): create/delete pods and nodes, the binding and
  status subresources, and a `?watch=true` chunked event stream per
  resource. A watch opens with resourceVersion=0 semantics: the server
  streams ADDED for every existing object, then a SYNC marker, then live
  events — so nothing can fall between a separate LIST and the watch
  registration.
- client-go's reflector/informer seam (tools/cache/reflector.go:470
  ListAndWatch → shared_informer.go:841 processLoop): HTTPClientset
  consumes the stream on its own thread, maintains the informer's local
  object cache, and fans events into the scheduler's registered handlers —
  which the scheduler's off-thread inbox (core/scheduler.py _threaded)
  replays on the scheduling loop. Handler registration replays the cache
  under the dispatch lock, so attach-time replay cannot race live events.

The JSON codec covers the full scheduling-relevant pod/node spec (requests,
tolerations, selectors, node+pod affinity, topology spread, gates, host
ports, PVC volumes, resource claims, nominations, deletion state); GVK /
admission stay out of scope (SURVEY §7). The etcd seam is re-expressed by
an optional durable store (`data_dir`, core/wal.py): every committed write
appends a WAL record, snapshots compact the log, and a restarted server
replays snapshot+WAL — recovering objects, rv counters, the boot epoch, and
the watch backlog, so clients resume (`RESUME`) instead of re-listing
across a ``kill -9``.
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib import request as urlrequest

from ..api.labels import LabelSelector, Requirement
from ..api.resource import Resource
from ..api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
from .clientset import FakeClientset

# ---------------------------------------------------------------------------
# JSON codec — full scheduling-relevant spec
# ---------------------------------------------------------------------------


def _req_to_wire(r: Requirement) -> dict:
    return {"key": r.key, "op": r.operator, "values": list(r.values)}


def _req_from_wire(d: dict) -> Requirement:
    return Requirement(d["key"], d["op"], tuple(d.get("values", ())))


def _sel_to_wire(s: Optional[LabelSelector]) -> Optional[dict]:
    if s is None:
        return None
    return {"matchLabels": dict(s.match_labels),
            "matchExpressions": [_req_to_wire(r) for r in s.match_expressions]}


def _sel_from_wire(d: Optional[dict]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector.of(
        d.get("matchLabels", {}),
        [_req_from_wire(r) for r in d.get("matchExpressions", ())])


def _nsel_to_wire(ns: Optional[NodeSelector]) -> Optional[list]:
    if ns is None:
        return None
    return [{"matchExpressions": [_req_to_wire(r) for r in t.match_expressions],
             "matchFields": [_req_to_wire(r) for r in t.match_fields]}
            for t in ns.terms]


def _nsel_from_wire(terms: Optional[list]) -> Optional[NodeSelector]:
    if terms is None:
        return None
    return NodeSelector(terms=tuple(
        NodeSelectorTerm(
            match_expressions=tuple(_req_from_wire(r)
                                    for r in t.get("matchExpressions", ())),
            match_fields=tuple(_req_from_wire(r)
                               for r in t.get("matchFields", ())))
        for t in terms))


def _pterm_to_wire(t: PodAffinityTerm) -> dict:
    return {"labelSelector": _sel_to_wire(t.label_selector),
            "namespaces": list(t.namespaces),
            "topologyKey": t.topology_key,
            "namespaceSelector": _sel_to_wire(t.namespace_selector)}


def _pterm_from_wire(d: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_sel_from_wire(d.get("labelSelector")),
        namespaces=tuple(d.get("namespaces", ())),
        topology_key=d.get("topologyKey", ""),
        namespace_selector=_sel_from_wire(d.get("namespaceSelector")))


def _affinity_to_wire(a: Optional[Affinity]) -> Optional[dict]:
    if a is None:
        return None
    out: dict = {}
    if a.node_affinity is not None:
        out["nodeAffinity"] = {
            "required": _nsel_to_wire(a.node_affinity.required),
            "preferred": [{"weight": p.weight,
                           "term": _nsel_to_wire(NodeSelector((p.preference,)))[0]}
                          for p in a.node_affinity.preferred],
        }
    for attr, key in (("pod_affinity", "podAffinity"),
                      ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(a, attr)
        if pa is not None:
            out[key] = {
                "required": [_pterm_to_wire(t) for t in pa.required],
                "preferred": [{"weight": w.weight,
                               "term": _pterm_to_wire(w.term)}
                              for w in pa.preferred],
            }
    return out or None


def _affinity_from_wire(d: Optional[dict]) -> Optional[Affinity]:
    if not d:
        return None
    na = None
    if "nodeAffinity" in d:
        nd = d["nodeAffinity"]
        na = NodeAffinity(
            required=_nsel_from_wire(nd.get("required")),
            preferred=tuple(
                PreferredSchedulingTerm(
                    weight=p["weight"],
                    preference=_nsel_from_wire([p["term"]]).terms[0])
                for p in nd.get("preferred", ())))

    def _pa(key, cls):
        if key not in d:
            return None
        pd = d[key]
        return cls(
            required=tuple(_pterm_from_wire(t) for t in pd.get("required", ())),
            preferred=tuple(
                WeightedPodAffinityTerm(weight=w["weight"],
                                        term=_pterm_from_wire(w["term"]))
                for w in pd.get("preferred", ())))

    return Affinity(node_affinity=na,
                    pod_affinity=_pa("podAffinity", PodAffinity),
                    pod_anti_affinity=_pa("podAntiAffinity", PodAntiAffinity))


def pod_to_wire(p: Pod) -> dict:
    req = p.resource_request()
    return {
        "name": p.name, "namespace": p.namespace, "uid": p.uid,
        "nodeName": p.node_name, "schedulerName": p.scheduler_name,
        "nominatedNodeName": p.nominated_node_name,
        "labels": dict(p.labels), "annotations": dict(p.annotations),
        "priority": p.priority, "podGroup": p.pod_group,
        "deletionTs": p.deletion_ts, "finalizers": list(p.finalizers),
        "requests": {"cpu": req.milli_cpu, "memory": req.memory,
                     "ephemeral": req.ephemeral_storage,
                     "scalar": dict(req.scalar_resources)},
        "hostPorts": [{"port": hp.host_port, "protocol": hp.protocol,
                       "hostIP": hp.host_ip}
                      for hp in p.host_ports()],
        "tolerations": [
            {"key": t.key, "operator": t.operator, "value": t.value,
             "effect": t.effect} for t in p.tolerations],
        "nodeSelector": dict(p.node_selector),
        "affinity": _affinity_to_wire(p.affinity),
        "topologySpread": [
            {"maxSkew": c.max_skew, "topologyKey": c.topology_key,
             "whenUnsatisfiable": c.when_unsatisfiable,
             "labelSelector": _sel_to_wire(c.label_selector),
             "minDomains": c.min_domains,
             "nodeAffinityPolicy": c.node_affinity_policy,
             "nodeTaintsPolicy": c.node_taints_policy}
            for c in p.topology_spread_constraints],
        "schedulingGates": list(p.scheduling_gates),
        "volumes": [{"name": v.name, "pvc": v.pvc_name} for v in p.volumes],
        "resourceClaims": list(getattr(p, "resource_claims", ()) or ()),
    }


def pod_from_wire(d: dict) -> Pod:
    req = Resource(milli_cpu=int(d["requests"]["cpu"]),
                   memory=int(d["requests"]["memory"]),
                   ephemeral_storage=int(d["requests"].get("ephemeral", 0)),
                   scalar_resources=dict(d["requests"].get("scalar", {})))
    ports = tuple(ContainerPort(host_port=int(hp["port"]),
                                protocol=hp.get("protocol", "TCP"),
                                host_ip=hp.get("hostIP", ""))
                  for hp in d.get("hostPorts", ()))
    p = Pod(
        name=d["name"], namespace=d.get("namespace", "default"),
        uid=d["uid"], node_name=d.get("nodeName", ""),
        scheduler_name=d.get("schedulerName", "default-scheduler"),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        priority=int(d.get("priority", 0)),
        containers=[Container(name="c0", requests=req, ports=ports)],
        tolerations=[Toleration(key=t["key"], operator=t["operator"],
                                value=t.get("value", ""),
                                effect=t.get("effect", ""))
                     for t in d.get("tolerations", ())],
        node_selector=dict(d.get("nodeSelector", {})),
        affinity=_affinity_from_wire(d.get("affinity")),
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=c["maxSkew"], topology_key=c["topologyKey"],
                when_unsatisfiable=c["whenUnsatisfiable"],
                label_selector=_sel_from_wire(c.get("labelSelector")),
                min_domains=c.get("minDomains"),
                node_affinity_policy=c.get("nodeAffinityPolicy", "Honor"),
                node_taints_policy=c.get("nodeTaintsPolicy", "Ignore"))
            for c in d.get("topologySpread", ())],
        scheduling_gates=list(d.get("schedulingGates", ())),
        volumes=[Volume(name=v["name"], pvc_name=v.get("pvc"))
                 for v in d.get("volumes", ())],
    )
    p.nominated_node_name = d.get("nominatedNodeName", "")
    p.deletion_ts = d.get("deletionTs")
    p.finalizers = list(d.get("finalizers", ()))
    p.pod_group = d.get("podGroup", "")
    claims = d.get("resourceClaims", ())
    if claims:
        p.resource_claims = list(claims)
    return p


def node_to_wire(n: Node) -> dict:
    return {
        "name": n.name, "uid": n.uid, "labels": dict(n.labels),
        "unschedulable": n.unschedulable,
        "allocatable": {"cpu": n.allocatable.milli_cpu,
                        "memory": n.allocatable.memory,
                        "ephemeral": n.allocatable.ephemeral_storage,
                        "pods": n.allocatable.allowed_pod_number,
                        "scalar": dict(n.allocatable.scalar_resources)},
        "taints": [{"key": t.key, "value": t.value, "effect": t.effect}
                   for t in n.taints],
        "declaredFeatures": dict(n.declared_features),
    }


def node_from_wire(d: dict) -> Node:
    from ..api.types import Taint
    alloc = Resource(milli_cpu=int(d["allocatable"]["cpu"]),
                     memory=int(d["allocatable"]["memory"]),
                     ephemeral_storage=int(d["allocatable"].get("ephemeral", 0)),
                     allowed_pod_number=int(d["allocatable"]["pods"]),
                     scalar_resources=dict(d["allocatable"].get("scalar", {})))
    n = Node(
        name=d["name"], uid=d["uid"], labels=dict(d.get("labels", {})),
        unschedulable=bool(d.get("unschedulable", False)),
        capacity=alloc.clone(), allocatable=alloc,
        taints=[Taint(key=t["key"], value=t.get("value", ""),
                      effect=t.get("effect", "NoSchedule"))
                for t in d.get("taints", ())],
    )
    n.declared_features = dict(d.get("declaredFeatures", {}))
    return n


# ---------------------------------------------------------------------------
# The apiserver
# ---------------------------------------------------------------------------


class APIServer:
    """REST + watch over an owned FakeClientset store.

    Watch streams support resourceVersion resume (the reference's
    watch-cache window): every event is stamped with a per-kind monotonic
    `rv` and retained in a bounded backlog. A client reconnecting with
    `?watch=true&resourceVersion=N` gets a RESUME marker plus a replay of
    every event it missed — no full re-list — when the window still covers
    N; otherwise (compaction, the 410 Gone analogue) it gets the usual full
    ADDED replay + SYNC and performs reflector Replace semantics.

    With ``data_dir`` set, the server is durable (core/wal.py): writes are
    WAL-logged before fanout, periodically compacted into a snapshot, and a
    restart recovers state + rv counters + epoch + backlog — the etcd3
    store seam (etcd3/store.go:284) collapsed to one process."""

    def __init__(self, store: Optional[FakeClientset] = None,
                 backlog: int = 8192, data_dir: Optional[str] = None,
                 fsync: bool = False, snapshot_every: int = 2048):
        self.store = store or FakeClientset()
        self._watchers: Dict[str, List["queue.Queue"]] = {"pods": [], "nodes": []}
        self._lock = threading.Lock()
        # Serializes MUTATING verbs end-to-end (check + store write + WAL):
        # the store itself is unlocked dicts, and ThreadingHTTPServer runs
        # one thread per request — without this, two concurrent binding
        # POSTs could both pass the already-bound check (double bind), two
        # same-uid creates could both pass the 409 check, and a compaction
        # could snapshot a store another thread is mid-mutation. One writer
        # at a time is also the etcd model the reference stands on. Watch
        # streams and GETs stay unserialized.
        self._write_lock = threading.Lock()
        from collections import deque
        import uuid
        self._seq: Dict[str, int] = {"pods": 0, "nodes": 0}
        self._backlog: Dict[str, "deque"] = {
            "pods": deque(maxlen=backlog), "nodes": deque(maxlen=backlog)}
        # Boot epoch: rv counters restart at 0 with a fresh server, so a
        # client's rv from a PREVIOUS server instance must never resume
        # against this one's unrelated event history — resume requires the
        # epoch to match, otherwise the full re-list (Replace) runs. With a
        # durable store (data_dir) the counters RESUME instead of restarting,
        # so recovery re-announces the PERSISTED epoch and clients ride the
        # RESUME path straight across a process death.
        self.epoch = uuid.uuid4().hex[:12]
        self.resumed_watches = 0   # incremental reconnects served
        self.relisted_watches = 0  # full-list attaches served
        self.bind_conflicts = 0    # rebind-to-a-different-node rejections
        self.compaction_failures = 0
        # Durability (core/wal.py): WAL + snapshot compaction + recovery.
        self.persistence = None
        self.recovered_objects = 0
        if data_dir is not None:
            from .wal import DurableStore
            self.persistence = DurableStore(
                data_dir, fsync=fsync, snapshot_every=snapshot_every)
            self._recover()
        self.store.on_pod_event(self._pod_event)
        self.store.on_node_event(self._node_event)
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -- durability (WAL + snapshot; core/wal.py) ---------------------------

    def _recover(self) -> None:
        """Replay snapshot+WAL into the owned store and resume the watch
        plane where the dead process left off: per-kind rv counters, the
        persisted epoch, and an event backlog rebuilt from the WAL tail so
        reflectors reconnecting with their last rv get RESUME, not Replace."""
        import itertools

        snap, records = self.persistence.load()
        if self.persistence.epoch is not None:
            self.epoch = self.persistence.epoch
        else:
            self.persistence.init_epoch(self.epoch)
        if snap is not None:
            self._seq.update(snap.get("seq", {}))
            for w in snap.get("pods", ()):
                self._apply_recovered("pods", "ADDED", w)
            for w in snap.get("nodes", ()):
                self._apply_recovered("nodes", "ADDED", w)
        for rec in records:
            kind = rec.get("kind")
            if kind not in ("pods", "nodes"):
                continue
            self._apply_recovered(kind, rec.get("type", ""), rec.get("object"))
            rv = rec.get("rv")
            if rv is not None and rv > self._seq[kind]:
                self._seq[kind] = rv
            # Rebuild the watch backlog exactly as _broadcast framed it (the
            # deque's maxlen keeps only the freshest `backlog` events).
            if rv is not None:
                event = {k: v for k, v in rec.items() if k != "kind"}
                self._backlog[kind].append(
                    (rv, (json.dumps(event) + "\n").encode()))
        # Object resource_versions were not persisted; fast-forward the
        # store's counter past everything ever minted so recovered and new
        # objects never share a version.
        self.store._rv_counter = itertools.count(
            self._seq["pods"] + self._seq["nodes"] + 1)
        self.recovered_objects = len(self.store.pods) + len(self.store.nodes)

    def _apply_recovered(self, kind: str, typ: str, wire: Optional[dict]) -> None:
        """Apply one recovered object directly to the store dicts — no
        handler fanout (there are no watchers yet) and idempotent upserts
        (a compaction snapshot may slightly lead the WAL it truncated)."""
        if wire is None:
            return
        if kind == "pods":
            pod = pod_from_wire(wire)
            if typ == "DELETED":
                self.store.pods.pop(pod.uid, None)
                self.store.bindings.pop(pod.uid, None)
            else:
                self.store.pods[pod.uid] = pod
                if pod.node_name:
                    self.store.bindings[pod.uid] = pod.node_name
                else:
                    self.store.bindings.pop(pod.uid, None)
        else:
            node = node_from_wire(wire)
            if typ == "DELETED":
                self.store.nodes.pop(node.name, None)
            else:
                self.store.nodes[node.name] = node

    def _wal_status(self, pod) -> None:
        """Persist a non-evented status patch (nominatedNodeName): an
        rv-less `STATUS` record — recovery upserts the object but the watch
        backlog never sees it (parity with its non-evented live fanout)."""
        if self.persistence is None:
            return
        with self._lock:
            self.persistence.append(
                {"kind": "pods", "type": "STATUS", "object": pod_to_wire(pod)})

    def _snapshot_state(self) -> dict:
        """Full-state compaction snapshot. The calling thread holds BOTH the
        write lock (its own verb — no other store mutation can be in
        flight) and the broadcast lock (no event can interleave); bindings
        ride on nodeName."""
        return {
            "epoch": self.epoch,
            "seq": dict(self._seq),
            "pods": [pod_to_wire(p) for p in list(self.store.pods.values())],
            "nodes": [node_to_wire(n) for n in list(self.store.nodes.values())],
        }

    # -- event fanout to watch streams -------------------------------------

    def _broadcast(self, kind: str, event: dict) -> None:
        with self._lock:
            self._seq[kind] += 1
            event["rv"] = self._seq[kind]
            if self.persistence is not None:
                # WAL append BEFORE fanout: an event a watcher saw is always
                # recoverable. The record is the event itself plus the kind,
                # so recovery rebuilds both the store and the watch backlog
                # from one stream.
                self.persistence.append({"kind": kind, **event})
                if self.persistence.should_compact():
                    try:
                        # Safe to read the store here: the writing thread
                        # holds _write_lock, so no other mutation is in
                        # flight. write_snapshot is atomic (tmp+replace)
                        # and only resets the WAL after the replace — a
                        # failed compaction leaves snapshot+WAL coherent,
                        # so it must never abort the broadcast (that would
                        # punch a hole in the fanout/backlog at this rv).
                        self.persistence.write_snapshot(self._snapshot_state())
                    except Exception:  # noqa: BLE001
                        self.compaction_failures += 1
            data = (json.dumps(event) + "\n").encode()
            self._backlog[kind].append((self._seq[kind], data))
            for q in self._watchers[kind]:
                q.put(data)

    def _pod_event(self, kind: str, old, new) -> None:
        typ = {"add": "ADDED", "update": "MODIFIED", "delete": "DELETED"}[kind]
        self._broadcast("pods", {"type": typ, "object": pod_to_wire(new)})

    def _node_event(self, kind: str, old, new) -> None:
        typ = {"add": "ADDED", "update": "MODIFIED", "delete": "DELETED"}[kind]
        self._broadcast("nodes", {"type": typ, "object": node_to_wire(new)})

    def _attach_watch(self, kind: str, since: Optional[int] = None,
                      epoch: Optional[str] = None) -> "queue.Queue":
        """Attach a watch under the broadcast lock, THEN register for live
        events — no create can fall between snapshot and registration.

        since=None (or outside the backlog window, or an epoch from another
        server instance): resourceVersion=0 semantics — ADDED for every
        existing object, then a SYNC marker carrying the current rv +
        epoch. since=N inside the window with a matching epoch: a RESUME
        marker, then a replay of exactly the events with rv > N."""
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            backlog = self._backlog[kind]
            seq = self._seq[kind]
            # Resumable iff the rv names THIS server's history (epoch) and
            # NOTHING after `since` was compacted away. Anything else —
            # unknown epoch (server restarted, counters reset), a future
            # rv, a pruned window — full-re-lists, never silently resumes.
            if (since is not None and epoch == self.epoch and since <= seq
                    and (since == seq
                         or (backlog and backlog[0][0] <= since + 1))):
                q.put((json.dumps({"type": "RESUME", "rv": seq,
                                   "epoch": self.epoch}) + "\n").encode())
                for s, data in backlog:
                    if s > since:
                        q.put(data)
                self.resumed_watches += 1
            else:
                if kind == "pods":
                    objs = [pod_to_wire(p) for p in self.store.pods.values()]
                else:
                    objs = [node_to_wire(n) for n in self.store.nodes.values()]
                for o in objs:
                    q.put((json.dumps({"type": "ADDED", "object": o}) + "\n").encode())
                q.put((json.dumps({"type": "SYNC", "rv": seq,
                                   "epoch": self.epoch}) + "\n").encode())
                self.relisted_watches += 1
            self._watchers[kind].append(q)
        return q

    def _detach_watch(self, kind: str, q) -> None:
        with self._lock:
            if q in self._watchers[kind]:
                self._watchers[kind].remove(q)

    # -- http --------------------------------------------------------------

    def serve(self, port: int = 0) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _read_body(self) -> dict:
                # Socket I/O — must run OUTSIDE the write lock (a stalled
                # sender would otherwise wedge the whole write plane).
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _body(self) -> dict:
                return self._body_cache

            def _json(self, code: int, obj) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                watch = "watch=true" in query
                since, epoch = None, None
                for part in query.split("&"):
                    if part.startswith("resourceVersion="):
                        try:
                            since = int(part.split("=", 1)[1])
                        except ValueError:
                            pass
                    elif part.startswith("epoch="):
                        epoch = part.split("=", 1)[1]
                if path == "/api/v1/pods":
                    if watch:
                        return self._stream("pods", since, epoch)
                    return self._json(200, [pod_to_wire(p) for p in
                                            server.store.pods.values()])
                if path == "/api/v1/nodes":
                    if watch:
                        return self._stream("nodes", since, epoch)
                    return self._json(200, [node_to_wire(n) for n in
                                            server.store.nodes.values()])
                self._json(404, {"error": "not found"})

            def _stream(self, kind: str, since: Optional[int] = None,
                        epoch: Optional[str] = None) -> None:
                # watch.Interface: hold the connection open, one JSON event
                # per line (chunked); blocking queue — no idle polling. A
                # BOOKMARK heartbeat goes out on idle (~10s) so a quiet
                # cluster keeps the client's read timeout from killing the
                # watch (the reference's watch bookmarks serve the same
                # liveness role).
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                q = server._attach_watch(kind, since, epoch)
                idle = 0.0
                try:
                    while server._httpd is not None:
                        try:
                            data = q.get(timeout=0.5)
                            idle = 0.0
                        except queue.Empty:
                            idle += 0.5
                            if idle < 10.0:
                                continue
                            idle = 0.0
                            data = b'{"type": "BOOKMARK"}\n'
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    server._detach_watch(kind, q)
                    # End of stream (server shutdown): close the TCP
                    # connection instead of waiting for another request on
                    # it, so the client's reflector sees EOF immediately
                    # and re-lists against the next server.
                    self.close_connection = True

            def do_POST(self):
                self._body_cache = self._read_body()
                with server._write_lock:
                    return self._do_post()

            def _do_post(self):
                if self.path == "/api/v1/pods":
                    pod = pod_from_wire(self._body())
                    # AlreadyExists (409, like the reference registry):
                    # duplicate creates — e.g. a client retrying a write
                    # whose reply was lost — must not re-fire ADDED events
                    # or reset a pod the scheduler already bound.
                    if pod.uid in server.store.pods:
                        return self._json(409, {"error": "AlreadyExists"})
                    server.store.create_pod(pod)
                    return self._json(201, pod_to_wire(pod))
                if self.path == "/api/v1/nodes":
                    node = node_from_wire(self._body())
                    if node.name in server.store.nodes:
                        return self._json(409, {"error": "AlreadyExists"})
                    server.store.create_node(node)
                    return self._json(201, node_to_wire(node))
                if (self.path.startswith("/api/v1/nodes/")
                        and self.path.endswith("/status")):
                    # parity stub (kubelet heartbeat shape); no-op
                    return self._json(200, {})
                parts = self.path.split("/")
                if (self.path.startswith("/api/v1/pods/")
                        and self.path.endswith("/binding")):
                    pod = server.store.pods.get(parts[4])
                    if pod is None:
                        return self._json(404, {"error": "pod not found"})
                    node = self._body()["node"]
                    if pod.node_name:
                        # Already bound: a same-node POST is a retry replay
                        # of a bind whose reply was lost (pre-crash write,
                        # recovered from the WAL) — idempotent success, no
                        # re-fired event. A different node is a genuine
                        # conflict (409, registry AlreadyExists analogue):
                        # a pod must never be bound twice.
                        if pod.node_name == node:
                            return self._json(200, {"bound": True})
                        server.bind_conflicts += 1
                        return self._json(409, {"error": "AlreadyBound"})
                    server.store.bind(pod, node)
                    return self._json(200, {"bound": True})
                if (self.path.startswith("/api/v1/pods/")
                        and self.path.endswith("/status")):
                    pod = server.store.pods.get(parts[4])
                    if pod is None:
                        return self._json(404, {"error": "pod not found"})
                    body = self._body()
                    server.store.patch_pod_status(
                        pod,
                        nominated_node_name=body.get("nominatedNodeName", ""),
                        phase=body.get("phase", ""))
                    # Status patches fan out no watch event (store parity),
                    # but their scheduling-relevant slice (nominations) must
                    # still survive a restart: WAL an rv-less STATUS record
                    # — replayed as an upsert, never entering the backlog.
                    server._wal_status(pod)
                    return self._json(200, {})
                self._json(404, {"error": "not found"})

            def do_PUT(self):
                self._body_cache = self._read_body()
                with server._write_lock:
                    return self._do_put()

            def _do_put(self):
                if (self.path.startswith("/api/v1/nodes/")
                        and self.path.endswith("/status")):
                    return self._json(200, {})  # heartbeat parity stub
                # Node update (relabel / retaint / capacity change): the
                # store fans a MODIFIED event to every watch stream, so
                # churn workloads run over the wire (eventhandlers.go
                # updateNodeInCache; round-4 VERDICT item 5).
                if self.path.startswith("/api/v1/nodes/"):
                    node = node_from_wire(self._body())
                    if node.name != self.path.split("/")[4]:
                        return self._json(400, {"error": "name mismatch"})
                    server.store.update_node(node)
                    return self._json(200, node_to_wire(node))
                self._json(404, {"error": "not found"})

            def do_DELETE(self):
                with server._write_lock:
                    return self._do_delete()

            def _do_delete(self):
                if self.path.startswith("/api/v1/pods/"):
                    uid = self.path.split("/")[4]
                    pod = server.store.pods.get(uid)
                    if pod is not None:
                        server.store.delete_pod(pod)
                    return self._json(200, {})
                if self.path.startswith("/api/v1/nodes/"):
                    server.store.delete_node(self.path.split("/")[4])
                    return self._json(200, {})
                self._json(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self._httpd.server_address[1]

    def shutdown(self) -> None:
        httpd = self._httpd
        self._httpd = None
        if httpd is not None:
            httpd.shutdown()
        if self.persistence is not None:
            self.persistence.close()


# ---------------------------------------------------------------------------
# The client: REST writes + reflector-fed informer cache
# ---------------------------------------------------------------------------


class HTTPClientset:
    """Clientset over the wire: writes are REST calls; reads serve from the
    reflector-maintained local cache; handler registration taps the informer
    fanout (events arrive on the reflector thread → the scheduler's inbox).

    Only the pod/node surface crosses the wire (the verbs the scheduler
    core exercises); the remaining listers return empty local dicts."""

    def __init__(self, base_url: str, sync_timeout: float = 30.0):
        self.base = base_url.rstrip("/")
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.bindings: Dict[str, str] = {}
        # unused-surface listers (volume/DRA plugins see empty cluster state)
        self.namespaces: Dict[str, object] = {}
        self.pod_groups: Dict[str, object] = {}
        self.composite_pod_groups: Dict[str, object] = {}
        self.pvs: Dict[str, object] = {}
        self.pvcs: Dict[str, object] = {}
        self.storage_classes: Dict[str, object] = {}
        self.csi_nodes: Dict[str, object] = {}
        self.resource_slices: Dict[str, list] = {}
        self.resource_claims: Dict[str, object] = {}
        self.device_classes: Dict[str, object] = {}
        self._pod_handlers: List = []
        self._node_handlers: List = []
        self._dispatch_lock = threading.Lock()
        self._stop = threading.Event()
        self._responses: List = []
        self._synced = {"pods": threading.Event(), "nodes": threading.Event()}
        self._fatal: Dict[str, Exception] = {}
        self.last_sync: Dict[str, float] = {}
        # resourceVersion resume (reflector.go lastSyncResourceVersion):
        # the rv of the last event (or SYNC snapshot) each stream consumed;
        # reconnects ask the server to replay from here instead of
        # re-listing. relists/resumes count how each reconnect was served.
        self._last_rv: Dict[str, Optional[int]] = {"pods": None, "nodes": None}
        # Server boot epoch (from SYNC/RESUME): sent with the rv so a
        # restarted server (fresh counters) re-lists instead of resuming.
        self._epoch: Dict[str, Optional[str]] = {"pods": None, "nodes": None}
        self.relists: Dict[str, int] = {"pods": 0, "nodes": 0}
        self.resumes: Dict[str, int] = {"pods": 0, "nodes": 0}
        self._threads: List[threading.Thread] = []
        for kind in ("pods", "nodes"):
            t = threading.Thread(target=self._watch_loop, args=(kind,),
                                 name=f"reflector-{kind}", daemon=True)
            t.start()
            self._threads.append(t)
        for kind in ("pods", "nodes"):
            if not self._synced[kind].wait(sync_timeout):
                self.close()  # stop the reflector threads before raising
                raise TimeoutError(f"reflector {kind} never synced")
            if kind in self._fatal:
                self.close()
                raise ConnectionError(
                    f"reflector {kind}: initial connection failed"
                ) from self._fatal[kind]

    # -- REST --------------------------------------------------------------

    def _call(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urlrequest.Request(self.base + path, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
        with urlrequest.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    def create_pod(self, pod: Pod) -> Pod:
        self._call("POST", "/api/v1/pods", pod_to_wire(pod))
        return pod

    def create_node(self, node: Node) -> Node:
        self._call("POST", "/api/v1/nodes", node_to_wire(node))
        return node

    def update_node(self, node: Node) -> Node:
        self._call("PUT", f"/api/v1/nodes/{node.name}", node_to_wire(node))
        return node

    def delete_node(self, name: str) -> None:
        self._call("DELETE", f"/api/v1/nodes/{name}")

    def delete_pod(self, pod: Pod) -> None:
        self._call("DELETE", f"/api/v1/pods/{pod.uid}")

    def bind(self, pod: Pod, node_name: str) -> None:
        self._call("POST", f"/api/v1/pods/{pod.uid}/binding",
                   {"node": node_name})

    def patch_pod_status(self, pod: Pod, nominated_node_name: str = "",
                         phase: str = "") -> None:
        self._call("POST", f"/api/v1/pods/{pod.uid}/status",
                   {"nominatedNodeName": nominated_node_name, "phase": phase})
        local = self.pods.get(pod.uid)
        if local is not None and nominated_node_name:
            local.nominated_node_name = nominated_node_name

    def update_pod(self, pod: Pod) -> Pod:  # parity stub for the surface
        return pod

    # -- informer registration (scheduler event handlers) -------------------

    def on_pod_event(self, handler) -> None:
        # Replay-then-subscribe under the dispatch lock: live events cannot
        # interleave with (or duplicate) the attach-time replay.
        with self._dispatch_lock:
            for p in list(self.pods.values()):
                handler("add", None, p)
            self._pod_handlers.append(handler)

    def on_node_event(self, handler) -> None:
        with self._dispatch_lock:
            for n in list(self.nodes.values()):
                handler("add", None, n)
            self._node_handlers.append(handler)

    def on_namespace_event(self, handler) -> None:
        pass

    def on_pod_group_event(self, handler) -> None:
        pass

    def on_storage_event(self, handler) -> None:
        pass

    def attach_pv_controller(self, ctrl) -> None:
        pass

    # -- reflector (ListAndWatch: the watch carries the initial list) -------

    def _watch_loop(self, kind: str) -> None:
        """client-go reflector behavior (tools/cache/reflector.go:470): on
        stream EOF/timeout, re-connect with the last-seen resourceVersion.
        Inside the server's backlog window the stream opens with RESUME and
        replays exactly the missed events — the local cache converges
        without a re-list. Outside the window (or on first connect) the
        stream replays ADDED for every live object then SYNC, and objects
        that vanished during the outage dispatch DELETED at the SYNC
        barrier (the reflector's Replace semantics). Only a failure of the
        FIRST connection is fatal (recorded in _fatal so the constructor
        raises instead of returning a dead clientset)."""
        # Raw HTTPConnection so close() can shut the SOCKET down —
        # HTTPResponse.close() on an endless chunked stream would block
        # draining to EOF.
        import http.client as _hc
        import time as _time
        host = self.base.split("//", 1)[1]
        backoff = 0.05
        while not self._stop.is_set():
            try:
                conn = _hc.HTTPConnection(host, timeout=60)
                path = f"/api/v1/{kind}?watch=true"
                if (self._last_rv[kind] is not None
                        and self._epoch[kind] is not None):
                    path += (f"&resourceVersion={self._last_rv[kind]}"
                             f"&epoch={self._epoch[kind]}")
                conn.request("GET", path)
                resp = conn.getresponse()
            except Exception as e:  # noqa: BLE001 - connect failure
                if not self._synced[kind].is_set():
                    # Initial connection failed: dead on arrival is an error,
                    # not an empty cluster.
                    self._fatal[kind] = e
                    self._synced[kind].set()
                    return
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 5.0)
                continue
            self._responses.append(conn)
            got_sync = False
            resync_seen: Optional[set] = set()  # keys replayed pre-SYNC
            try:
                while not self._stop.is_set():
                    line = resp.readline()
                    if not line:
                        break  # EOF: server went away — re-list + re-watch
                    event = json.loads(line)
                    typ = event["type"]
                    if typ == "BOOKMARK":
                        continue  # server idle heartbeat
                    if typ == "RESUME":
                        # Incremental reconnect: the server will replay the
                        # missed tail — the local cache stays authoritative,
                        # so no Replace barrier runs.
                        resync_seen = None
                        got_sync = True
                        backoff = 0.05
                        self.resumes[kind] += 1
                        if event.get("epoch") is not None:
                            self._epoch[kind] = event["epoch"]
                        self._synced[kind].set()
                        self.last_sync[kind] = _time.monotonic()
                        continue
                    if typ == "SYNC":
                        with self._dispatch_lock:
                            self._replace_barrier(kind, resync_seen)
                        resync_seen = None
                        got_sync = True
                        backoff = 0.05  # healthy stream: reset the backoff
                        self.relists[kind] += 1
                        if event.get("rv") is not None:
                            self._last_rv[kind] = event["rv"]
                        if event.get("epoch") is not None:
                            self._epoch[kind] = event["epoch"]
                        self._synced[kind].set()
                        self.last_sync[kind] = _time.monotonic()
                        continue
                    with self._dispatch_lock:
                        if resync_seen is not None:
                            resync_seen.add(self._wire_key(kind, event["object"]))
                        self._dispatch(kind, typ, event["object"],
                                       relisting=resync_seen is not None)
                        if event.get("rv") is not None:
                            self._last_rv[kind] = event["rv"]
            except Exception:  # noqa: BLE001 - stream torn down / timeout
                pass
            finally:
                try:
                    self._responses.remove(conn)
                except ValueError:
                    pass
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass
            # A stream that died before delivering SYNC counts as a failure:
            # back off exponentially (client-go ListAndWatch backoff) so a
            # crash-looping server isn't hammered at ~20 reconnects/sec.
            if self._stop.wait(backoff if not got_sync else 0.05):
                return
            if not got_sync:
                backoff = min(backoff * 2, 5.0)

    @staticmethod
    def _wire_key(kind: str, obj: dict) -> str:
        return obj["uid"] if kind == "pods" else obj["name"]

    def _replace_barrier(self, kind: str, seen: Optional[set]) -> None:
        """End of a (re-)list window: local objects the server did NOT replay
        no longer exist — dispatch their deletion (reflector Replace)."""
        if seen is None:
            return
        if kind == "pods":
            for uid in [u for u in self.pods if u not in seen]:
                self._dispatch(kind, "DELETED", pod_to_wire(self.pods[uid]))
        else:
            for name in [n for n in self.nodes if n not in seen]:
                self._dispatch(kind, "DELETED", node_to_wire(self.nodes[name]))

    def _dispatch(self, kind: str, typ: str, obj: dict,
                  relisting: bool = False) -> None:
        action = {"ADDED": "add", "MODIFIED": "update", "DELETED": "delete"}[typ]
        if kind == "pods":
            pod = pod_from_wire(obj)
            old = self.pods.get(pod.uid)
            if relisting and action == "add" and old is not None:
                action = "update"  # re-list replay of a known object
            if action == "delete":
                self.pods.pop(pod.uid, None)
                self.bindings.pop(pod.uid, None)
            else:
                self.pods[pod.uid] = pod
                if pod.node_name:
                    self.bindings[pod.uid] = pod.node_name
                else:
                    # Re-list replay (or status update) of an UNBOUND pod:
                    # a stale binding from before a server restart must not
                    # survive in the informer cache.
                    self.bindings.pop(pod.uid, None)
            for h in self._pod_handlers:
                h(action, old, pod)
        else:
            node = node_from_wire(obj)
            old = self.nodes.get(node.name)
            if relisting and action == "add" and old is not None:
                action = "update"
            if action == "delete":
                self.nodes.pop(node.name, None)
            else:
                self.nodes[node.name] = node
            for h in self._node_handlers:
                h(action, old, node)

    def close(self) -> None:
        self._stop.set()
        # Snapshot: reflector threads remove() dead connections concurrently.
        for conn in list(self._responses):
            _shutdown_conn(conn)
        for t in self._threads:
            t.join(timeout=2)


def _shutdown_conn(conn) -> None:
    try:
        import socket
        if conn.sock is not None:
            conn.sock.shutdown(socket.SHUT_RDWR)
            conn.sock.close()
    except Exception:  # noqa: BLE001
        pass


def main(argv=None) -> int:
    """Standalone apiserver process (`python -m kubernetes_tpu.core.apiserver
    --port N`): serves the REST+watch surface on a real socket until
    SIGTERM/SIGINT — the other half of the two-OS-process integration seam
    (ref test/integration/framework/test_server.go:78 StartTestServer)."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(prog="kubernetes-tpu-apiserver")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default="",
                    help="durable store directory (WAL + snapshot, "
                         "core/wal.py); empty = in-memory only")
    ap.add_argument("--fsync", action="store_true",
                    help="fsync every WAL record (survives power loss, not "
                         "just process death)")
    ap.add_argument("--snapshot-every", type=int, default=2048,
                    help="compact the WAL into a snapshot every N records")
    args = ap.parse_args(argv)
    api = APIServer(data_dir=args.data_dir or None, fsync=args.fsync,
                    snapshot_every=args.snapshot_every)
    port = api.serve(args.port)
    # "serving on" stays the FIRST line: spawn harnesses select()+readline()
    # on it, and a buffered readline would swallow any earlier line together
    # with this one (leaving select blocked on a drained pipe).
    print(f"kubernetes-tpu-apiserver: serving on 127.0.0.1:{port}",
          flush=True)
    if api.persistence is not None:
        p = api.persistence
        print(f"kubernetes-tpu-apiserver: recovered {api.recovered_objects} "
              f"objects (wal={p.replayed_records} torn="
              f"{p.torn_records_discarded}) epoch={api.epoch} "
              f"rv={dict(api._seq)}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    api.shutdown()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
