"""ComponentConfig: typed scheduler configuration.

Re-expresses KubeSchedulerConfiguration (pkg/scheduler/apis/config/types.go:37
+ v1 defaults in apis/config/v1/default_plugins.go / defaults.go): profiles
with per-extension-point plugin enable/disable + weights + typed plugin args,
percentageOfNodesToScore, backoff bounds, feature gates, and the TPU batch
knobs that replace `parallelism` (the 16-goroutine fan-out has no meaning on
device — SURVEY.md §2.4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .features import FeatureGates
from .registry import DEFAULT_PLUGINS


@dataclass
class PluginSet:
    """Enabled/disabled plugin overlay (config/types.go Plugins): the default
    set, minus `disabled` names ("*" clears it), plus `enabled` (name, weight)
    entries appended in order."""

    enabled: Tuple[Tuple[str, int], ...] = ()
    disabled: Tuple[str, ...] = ()

    def resolve(self, defaults: Sequence[Tuple[str, int]] = DEFAULT_PLUGINS) -> Tuple[Tuple[str, int], ...]:
        if "*" in self.disabled:
            base: List[Tuple[str, int]] = []
        else:
            base = [(n, w) for n, w in defaults if n not in self.disabled]
        names = {n for n, _ in base}
        out = list(base)
        for name, weight in self.enabled:
            if name in names:
                out = [(n, weight if n == name else w) for n, w in out]
            else:
                out.append((name, weight))
        return tuple(out)


@dataclass
class ProfileConfig:
    """config/types.go KubeSchedulerProfile."""

    scheduler_name: str = "default-scheduler"
    plugins: PluginSet = field(default_factory=PluginSet)
    plugin_config: Dict[str, dict] = field(default_factory=dict)  # name -> args


@dataclass
class SchedulerConfiguration:
    """KubeSchedulerConfiguration (types.go:37)."""

    profiles: List[ProfileConfig] = field(default_factory=lambda: [ProfileConfig()])
    percentage_of_nodes_to_score: int = 0         # types.go:62-70 (0 = adaptive)
    pod_initial_backoff_seconds: float = 1.0      # scheduling_queue.go:78-82
    pod_max_backoff_seconds: float = 10.0
    feature_gates: Dict[str, bool] = field(default_factory=dict)
    # TPU batch knobs (replace `parallelism`, types.go:48-49).
    max_batch: int = 1024
    extenders: List[dict] = field(default_factory=list)
    # Async API writes run on a worker thread when set (the reference's
    # dispatcher goroutine); inline otherwise for determinism.
    async_dispatch_threads: bool = False
    # Per-tenant weighted fair dequeue on the pending queue (core/queue.py
    # _FairTenantHeap; docs/RESILIENCE.md § overload & fairness). Off by
    # default — single-tenant workloads keep the global queue-sort order.
    fair_tenant_dequeue: bool = False
    tenant_weights: Dict[str, float] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Mapping) -> "SchedulerConfiguration":
        profiles = []
        for p in d.get("profiles", [{}]):
            plugins = p.get("plugins", {})
            profiles.append(ProfileConfig(
                scheduler_name=p.get("schedulerName", "default-scheduler"),
                plugins=PluginSet(
                    enabled=tuple(
                        (e["name"], e.get("weight", 1)) if isinstance(e, dict) else (e, 1)
                        for e in plugins.get("enabled", ())),
                    disabled=tuple(plugins.get("disabled", ())),
                ),
                plugin_config={
                    pc["name"]: pc.get("args", {}) for pc in p.get("pluginConfig", ())
                },
            ))
        return cls(
            profiles=profiles or [ProfileConfig()],
            percentage_of_nodes_to_score=d.get("percentageOfNodesToScore", 0),
            pod_initial_backoff_seconds=d.get("podInitialBackoffSeconds", 1.0),
            pod_max_backoff_seconds=d.get("podMaxBackoffSeconds", 10.0),
            feature_gates=dict(d.get("featureGates", {})),
            max_batch=d.get("maxBatch", 1024),
            extenders=list(d.get("extenders", ())),
            async_dispatch_threads=bool(d.get("asyncDispatchThreads", False)),
            fair_tenant_dequeue=bool(d.get("fairTenantDequeue", False)),
            tenant_weights=dict(d.get("tenantWeights", {})),
        )

    def gates(self) -> FeatureGates:
        return FeatureGates(self.feature_gates)

    def validate(self) -> List[str]:
        """ValidateKubeSchedulerConfiguration
        (apis/config/validation/validation.go:38): returns field errors
        ("" = valid). The TPU fork drops parallelism/leader-election knobs
        (the batch kernel replaces the goroutine pool; leases are internal),
        so those reference checks have no analogue here."""
        errs: List[str] = []
        if not (0 <= self.percentage_of_nodes_to_score <= 100):
            errs.append(
                f"percentageOfNodesToScore: {self.percentage_of_nodes_to_score}"
                " not in valid range [0-100]")
        if self.pod_initial_backoff_seconds <= 0:
            errs.append("podInitialBackoffSeconds: must be greater than 0")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            errs.append("podMaxBackoffSeconds: must be greater than or equal"
                        " to podInitialBackoffSeconds")
        if self.max_batch <= 0:
            errs.append("maxBatch: should be an integer value greater than zero")
        if not self.profiles:
            errs.append("profiles: Required value")
        seen: Dict[str, int] = {}
        for i, p in enumerate(self.profiles):
            if not p.scheduler_name:
                errs.append(f"profiles[{i}].schedulerName: Required value")
            if p.scheduler_name in seen:
                errs.append(
                    f"profiles[{i}].schedulerName: Duplicate value "
                    f"{p.scheduler_name!r} (first at profiles[{seen[p.scheduler_name]}])")
            else:
                seen[p.scheduler_name] = i
        for i, e in enumerate(self.extenders):
            if not isinstance(e, Mapping):
                continue  # pre-built Extender objects validate themselves
            if not e.get("urlPrefix"):
                errs.append(f"extenders[{i}].urlPrefix: Required value")
            if not any(e.get(v) for v in
                       ("filterVerb", "prioritizeVerb", "bindVerb",
                        "preemptVerb")):
                errs.append(f"extenders[{i}]: must configure at least one verb")
            w = e.get("weight", 1)
            if not isinstance(w, int) or w <= 0:
                errs.append(f"extenders[{i}].weight: must be a positive integer")
        return errs
