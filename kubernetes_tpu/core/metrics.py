"""Scheduler metrics: Prometheus-style registry + the reference's series.

Re-expresses pkg/scheduler/metrics/metrics.go (names at :265-615) over a
dependency-free metrics core (component-base/metrics analogue). Series are
registered on a module-level Registry; `expose()` renders the Prometheus text
format for a /metrics endpoint.
"""

from __future__ import annotations

import time
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Histogram buckets (metrics.go uses exponential buckets starting 0.001).
DURATION_BUCKETS = (0.001, 0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128,
                    0.256, 0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


class Metric:
    def __init__(self, name: str, help_text: str, label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = label_names


class Counter(Metric):
    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *labels: str, value: float = 1.0) -> None:
        key = tuple(labels)
        self._values[key] = self._values.get(key, 0.0) + value

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {v}")
        return out


class Gauge(Metric):
    def __init__(self, name, help_text, label_names=(), fn: Optional[Callable] = None):
        super().__init__(name, help_text, tuple(label_names))
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fn = fn  # callback gauge

    def set(self, value: float, *labels: str) -> None:
        self._values[tuple(labels)] = value

    def value(self, *labels: str) -> float:
        return self._values.get(tuple(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        values = self._fn() if self._fn is not None else self._values
        for key, v in sorted(values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, key)} {v}")
        return out


class Histogram(Metric):
    """Counts are stored PER-BUCKET (non-cumulative) so observe() is O(1)
    via bisect — it runs several times per pod on a >10k pods/s path — and
    converted to Prometheus cumulative form at expose/percentile time."""

    def __init__(self, name, help_text, label_names=(), buckets=DURATION_BUCKETS):
        super().__init__(name, help_text, tuple(label_names))
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, *labels: str) -> None:
        key = labels
        counts = self._counts.get(key)
        if counts is None:
            # +1 slot: the +Inf bucket
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
        counts[bisect_left(self.buckets, value)] += 1
        self._sums[key] = self._sums.get(key, 0.0) + value
        self._totals[key] = self._totals.get(key, 0) + 1

    def _cumulative(self, key) -> List[int]:
        out = []
        c = 0
        for v in self._counts.get(key, ()):
            c += v
            out.append(c)
        return out

    def count(self, *labels: str) -> int:
        return self._totals.get(tuple(labels), 0)

    def sum(self, *labels: str) -> float:
        return self._sums.get(tuple(labels), 0.0)

    def percentile(self, q: float, *labels: str) -> float:
        """Bucket-interpolated percentile (perf collector support); mass in
        the +Inf bucket reports the top finite bound."""
        key = tuple(labels)
        total = self._totals.get(key, 0)
        if total == 0:
            return 0.0
        target = q * total
        cum_prev = 0
        cums = self._cumulative(key)
        for i, b in enumerate(self.buckets):
            cum = cums[i]
            if cum >= target:
                lo = self.buckets[i - 1] if i else 0.0
                span = cum - cum_prev
                frac = (target - cum_prev) / span if span else 1.0
                return lo + (b - lo) * frac
            cum_prev = cum
        return self.buckets[-1]

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key in sorted(self._totals):
            cums = self._cumulative(key)
            for i, b in enumerate(self.buckets):
                labels = _fmt_labels(self.label_names + ("le",), key + (str(b),))
                out.append(f"{self.name}_bucket{labels} {cums[i]}")
            inf = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
            out.append(f"{self.name}_bucket{inf} {cums[-1]}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, key)} {self._sums[key]}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, key)} {self._totals[key]}")
        return out


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class Registry:
    def __init__(self):
        self._metrics: List[Metric] = []

    def register(self, m: Metric) -> Metric:
        self._metrics.append(m)
        return m

    def expose(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class SchedulerMetrics:
    """The scheduler's series (metrics/metrics.go:265-615 subset that the
    perf harness and tests consume)."""

    def __init__(self):
        self.registry = Registry()
        r = self.registry.register
        self.schedule_attempts = r(Counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result and profile.",
            ("result", "profile")))
        self.scheduling_attempt_duration = r(Histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (scheduling algorithm + binding).",
            ("result", "profile")))
        self.pod_scheduling_sli_duration = r(Histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2e latency for a pod being scheduled, from first attempt.",
            ("attempts",)))
        self.framework_extension_point_duration = r(Histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency per extension point.", ("extension_point", "status", "profile")))
        self.plugin_execution_duration = r(Histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Plugin execution latency.", ("plugin", "extension_point", "status")))
        self.pending_pods = r(Gauge(
            "scheduler_pending_pods",
            "Pending pods by queue (active/backoff/unschedulable/gated).",
            ("queue",)))
        self.queue_incoming_pods = r(Counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to queues by event and queue.", ("queue", "event")))
        self.preemption_attempts = r(Counter(
            "scheduler_preemption_attempts_total", "Preemption attempts."))
        self.preemption_victims = r(Histogram(
            "scheduler_preemption_victims", "Victims per preemption.",
            buckets=(1, 2, 4, 8, 16, 32, 64)))
        self.batch_attempts = r(Counter(
            "scheduler_batch_attempts_total",
            "Device batch dispatches, by outcome.", ("result",)))
        self.batch_size = r(Histogram(
            "scheduler_batch_size", "Pods per device batch.",
            buckets=(1, 8, 64, 256, 512, 1024, 2048, 4096)))
        self.podgroup_schedule_attempts = r(Counter(
            "scheduler_podgroup_schedule_attempts_total",
            "Gang scheduling attempts, by result.", ("result",)))
        self.generated_placements = r(Histogram(
            "scheduler_podgroup_generated_placements",
            "Candidate placements generated per pod-group cycle "
            "(metrics.RecordGeneratedPlacements).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128)))
        self.goroutines = r(Gauge(
            "scheduler_device_dispatches_active",
            "In-flight device dispatches (Parallelizer-goroutines analogue).",
            ()))
        self.cache_size = r(Gauge(
            "scheduler_scheduler_cache_size", "Cache object counts.", ("type",)))

    def expose(self) -> str:
        return self.registry.expose()


@dataclass
class _Timer:
    start: float = field(default_factory=time.perf_counter)

    def elapsed(self) -> float:
        return time.perf_counter() - self.start


class MetricAsyncRecorder:
    """Buffered off-thread metric recording (pkg/scheduler/metrics/
    metric_recorder.go MetricAsyncRecorder): hot paths append observations
    to a bounded buffer and a flusher thread applies them to the histograms
    on an interval — the scheduling loop never pays the registry's dict
    work. observe() drops on overflow (the reference's channel send is
    non-blocking too), counting drops for observability."""

    def __init__(self, interval: float = 0.05, capacity: int = 4096):
        import threading
        from collections import deque

        # Unbounded deque + explicit capacity check: deque(maxlen) would
        # silently evict the OLDEST observation when two racing observers
        # both pass a len() check — an uncounted loss. With no maxlen the
        # worst case of the (benign) check-then-append race is a few entries
        # over capacity, all of which still flush.
        self._buf = deque()
        self._capacity = capacity
        self._interval = interval
        self.dropped = 0
        self._stop = threading.Event()
        self._flushed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="metric-recorder", daemon=True)
        self._thread.start()

    def observe(self, histogram: Histogram, value: float, *labels: str) -> None:
        if len(self._buf) >= self._capacity:
            self.dropped += 1
            return
        self._buf.append((histogram, value, labels))

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.flush_now()
        self.flush_now()

    def flush_now(self) -> None:
        buf = self._buf
        while buf:
            try:
                histogram, value, labels = buf.popleft()
            except IndexError:
                break
            histogram.observe(value, *labels)
        self._flushed.set()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.flush_now()
